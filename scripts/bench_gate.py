#!/usr/bin/env python
"""CI perf gate: fail when a fresh bench JSON regresses vs the baseline.

Usage (``make bench-gate`` wires the default form):

    python scripts/bench_gate.py \
        --baseline BENCH_BASELINE.json \
        --current  /tmp/bench_fresh.json \
        [--tolerance 0.25] [--strict] [--dry-run]

Exit codes: 0 clean (or dry-run schema OK), 1 regression(s), 2 bad input.

- Direction awareness lives in ``rag_llm_k8s_tpu/obs/regression.py``:
  latency up = bad, tok/s down = bad, improvements never fail the gate.
- ``--dry-run`` validates both documents' SCHEMA (parse + at least one
  comparable numeric metric) without judging values — the fast ``make ci``
  leg, which must not need a TPU.
- A current document carrying ``"truncated": true`` (bench ran out of its
  ``TPU_RAG_BENCH_BUDGET_S`` budget) is compared on the legs it completed;
  the truncation is reported so a "clean" gate over half a bench is never
  mistaken for a full pass.
- ``--strict`` also fails on metrics missing from the current document
  (catching a silently dropped bench leg).

Stdlib + the repo only: runs everywhere tier-1 runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rag_llm_k8s_tpu.obs import regression  # noqa: E402

# Metrics that may NEVER silently vanish from a judged bench document: a
# dropped leg reads as "no regression" under the default missing-is-info
# policy, which is exactly how the B=64 continuous-step collapse went
# unjudged for a round. Keys here fail the gate when the CURRENT document
# lacks them while the baseline has them — unless the current run was
# budget-truncated before that leg (truncation is already reported).
# b64_sync16 is tracked higher-is-better by regression.classify; the paged
# keys are the BENCH_r05 rc-124 casualties (ROADMAP BENCH_r06 housekeeping)
# — a judged run that silently drops the paged leg must fail, not pass.
REQUIRED_KEYS = (
    "continuous_device_steps_per_s.b64_sync16",
    "paged_decode_steps_per_s.b64_paged",
    "paged_b64_speedup",
    "paged_tp.b8_steps_per_s",
    # ISSUE 7: the lookahead overlapped-query leg's headline — a dropped
    # leg must fail loudly, not read as "retrieval overlap unjudged"
    "lookahead_overlap.query_p50_overlap_ms",
    # ISSUE 8: the KV-tiering capacity headline (servable cached chunks at
    # fixed HBM, tiered vs hot-only; acceptance ≥ 3) — a dropped leg must
    # never read as "tiering capacity unjudged"
    "kv_tiering.effective_capacity_x",
    # ISSUE 11: the flight recorder's measured cost (recorder-on vs -off
    # B=8 continuous decode; acceptance ≤ 2%) — the recorder is ON by
    # default, so its overhead may never go unjudged in a bench round
    "flight_overhead.overhead_frac",
    # ISSUE 12: chunk-granular prefix reuse — prefill tokens skipped on
    # the shuffled-composition stream (acceptance ≥ 0.5 with the logit
    # tolerance green); a silently dropped leg must fail the gate instead
    # of reading as "chunk reuse unjudged"
    "chunk_reuse.prefill_skip_frac",
    # ISSUE 13: speculative decoding in the continuous paged engine — the
    # B=8 spec-on/spec-off tok/s ratio on the repeat-heavy RAG workload
    # (acceptance > 1.5×); a silently dropped leg must fail the gate, not
    # read as "paged speculation unjudged"
    "continuous_spec.b8_speedup",
    # ISSUE 14: the goodput ledger's measured cost (ledger-on vs -off B=8
    # continuous decode; acceptance ≤ 2%) — the ledger is ON by default,
    # so its overhead may never go unjudged in a bench round
    "goodput_overhead.overhead_frac",
    # ISSUE 15: the shadow quality auditor's measured cost (audits-on vs
    # -off B=8 continuous decode at the default 5% sample rate;
    # acceptance ≤ 2%) — the auditor is ON by default, so its overhead
    # may never go unjudged in a bench round
    "shadow_overhead.overhead_frac",
    # ISSUE 16: unified ragged sync windows — the padding-bubble share of
    # busy chip time on the heavy-admission-churn workload with chunked
    # prefill interleaved into decode (acceptance: lower than the
    # phase-separated scheduler's; regression.classify tracks bubble_frac
    # lower-is-better) — a silently dropped leg must fail the gate, not
    # read as "admission-churn occupancy unjudged"
    "chunked_prefill.bubble_frac",
    # ISSUE 17: the replay simulator's fidelity headline — simulated
    # steps/s over the measurement its step model was calibrated on
    # (acceptance: within ±25% of 1.0; regression.classify judges it
    # "band" — drifting high is as wrong as drifting low). A silently
    # dropped leg must fail the gate, not read as "capacity-planning
    # predictions unjudged" (docs/REPLAY.md)
    "replay_fidelity.steps_per_s_ratio",
    # ISSUE 18: tenant attribution's measured cost (full per-request
    # lifecycle — edge intern, stamp, fold, counter pushes — on vs off at
    # B=8 continuous decode; acceptance ≤ 2%) — attribution is ON by
    # default, so its overhead may never go unjudged in a bench round
    "tenant_overhead.overhead_frac",
    # ISSUE 19: warm restart's measured benefit — the fraction of the
    # cold first-burst's first-touch prefill tokens the warmth-manifest
    # rehydration makes unnecessary (regression.classify tracks
    # "reduction" higher-is-better). A silently dropped leg must fail
    # the gate, not read as "restart warmth unjudged"
    "restart_warmth.warm_prefill_reduction",
    # ISSUE 20: disaggregated prefill/decode pools — tokens-per-dollar of
    # the routed pair over the unified baseline on the same concurrent
    # workload (regression.classify judges tokens_per_usd higher-is-
    # better). A silently dropped leg must fail the gate, not read as
    # "the split's cost unjudged"
    "disagg.tokens_per_usd_ratio",
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default=os.path.join(REPO, "BENCH_BASELINE.json"))
    ap.add_argument("--current", default=None,
                    help="fresh bench JSON (defaults to the baseline itself "
                         "— a self-comparison smoke that must pass)")
    ap.add_argument("--tolerance", type=float,
                    default=regression.DEFAULT_TOLERANCE,
                    help="relative band before a bad-direction move fails "
                         f"(default {regression.DEFAULT_TOLERANCE})")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on metrics missing from --current")
    ap.add_argument("--require", action="append", default=None,
                    metavar="KEY",
                    help="flattened metric key(s) the CURRENT document must "
                         "carry (repeatable); overrides the built-in "
                         "REQUIRED_KEYS list")
    ap.add_argument("--dry-run", action="store_true",
                    help="schema check only (no value judgment, no TPU)")
    args = ap.parse_args(argv)
    current_path = args.current or args.baseline

    try:
        baseline = regression.load_json(args.baseline)
    except Exception as e:  # noqa: BLE001
        print(f"bench-gate: cannot load baseline {args.baseline}: {e}",
              file=sys.stderr)
        return 2
    try:
        current = regression.load_json(current_path)
    except Exception as e:  # noqa: BLE001
        print(f"bench-gate: cannot load current {current_path}: {e}",
              file=sys.stderr)
        return 2

    problems = regression.schema_check(baseline) + regression.schema_check(current)
    if problems:
        for p in problems:
            print(f"bench-gate: schema: {p}", file=sys.stderr)
        return 2
    if args.dry_run:
        n = sum(
            1 for k, v in regression.flatten(current).items()
            if regression.classify(k) != "ignore"
            and isinstance(v, (int, float)) and not isinstance(v, bool)
        )
        print(f"bench-gate: dry-run OK ({n} comparable metrics in "
              f"{os.path.basename(current_path)})")
        return 0

    overlap = regression.comparable_overlap(current, baseline)
    if not overlap:
        # zero shared comparable metrics = the gate would judge NOTHING;
        # "OK" here would green-light any regression (schema drift, wrong
        # file, stale baseline) — fail loudly instead
        print(
            "bench-gate: the two documents share no comparable metrics — "
            "nothing would be judged. Wrong baseline/current pairing?",
            file=sys.stderr,
        )
        return 2
    findings = regression.compare(current, baseline, tolerance=args.tolerance)
    if current.get("truncated"):
        skipped = current.get("legs_skipped") or []
        print("bench-gate: NOTE current bench was budget-truncated"
              + (f" (skipped legs: {', '.join(skipped)})" if skipped else ""))
    for f in findings["improvement"]:
        print(f"bench-gate: improvement  {f.describe()}")
    for f in findings["missing"]:
        print(f"bench-gate: missing      {f.describe()}")
    for f in findings["regression"]:
        print(f"bench-gate: REGRESSION   {f.describe()}", file=sys.stderr)

    failed = bool(findings["regression"])
    cur_flat = regression.flatten(current)
    base_flat = regression.flatten(baseline)
    for key in (args.require if args.require is not None else REQUIRED_KEYS):
        if key in cur_flat or key not in base_flat:
            continue  # present, or the baseline never had it either
        if current.get("truncated"):
            # budget truncation already printed its NOTE; a leg the budget
            # cut is not a SILENT drop
            print(f"bench-gate: required {key} absent (budget-truncated run)")
            continue
        print(
            f"bench-gate: REQUIRED metric {key} missing from current — a "
            "dropped leg must never read as a pass", file=sys.stderr,
        )
        failed = True
    if args.strict and any(f.current is None for f in findings["missing"]):
        print("bench-gate: strict: metrics missing from current", file=sys.stderr)
        failed = True
    if failed:
        print(f"bench-gate: FAIL ({len(findings['regression'])} regression(s) "
              f"at tolerance {args.tolerance:.0%})", file=sys.stderr)
        return 1
    print(f"bench-gate: OK ({len(overlap)} metrics judged at tolerance "
          f"{args.tolerance:.0%}; {len(findings['improvement'])} "
          f"improvement(s), {len(findings['missing'])} missing)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
