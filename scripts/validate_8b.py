"""Full-depth 8B streaming-load validation (run on demand, not in CI).

Writes a zero-filled 4-shard safetensors checkpoint with EXACTLY the tensor
surface of Meta-Llama-3.1-8B-Instruct (~16 GB bf16, the layout
download_model.py stages into the PVC), streams it through
``load_safetensors_params`` + ``make_streaming_put`` onto an 8-virtual-device
dp2×tp4 CPU mesh, and reports transient host overhead versus checkpoint
size. Results are recorded in docs/8B.md.

Usage:  python scripts/validate_8b.py [--workdir DIR] [--keep]
"""

import argparse
import os
import resource
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

GB = 1 << 30


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import psutil

    from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig, MeshConfig
    from rag_llm_k8s_tpu.core.mesh import make_mesh
    from rag_llm_k8s_tpu.models.loader import load_safetensors_params
    from rag_llm_k8s_tpu.parallel.sharding import make_streaming_put
    from rag_llm_k8s_tpu.utils.synth import write_synth_checkpoint

    cfg = LlamaConfig.llama_3_1_8b()
    workdir = args.workdir or tempfile.mkdtemp(prefix="synth8b_")
    proc = psutil.Process()

    print(f"devices: {jax.devices()}")
    t0 = time.monotonic()
    paths = write_synth_checkpoint(workdir, cfg, n_shards=4)
    ckpt_bytes = sum(os.path.getsize(p) for p in paths)
    print(
        f"wrote {len(paths)} shards, {ckpt_bytes / GB:.2f} GB total "
        f"in {time.monotonic() - t0:.1f}s -> {workdir}"
    )

    ctx = make_mesh(MeshConfig(dp=2, sp=1, tp=4))
    print(f"mesh: {ctx.mesh}")
    put = make_streaming_put(ctx, dtype=jnp.bfloat16)

    rss_before = proc.memory_info().rss
    t0 = time.monotonic()
    params = load_safetensors_params(workdir, cfg, DTypePolicy(), put=put)
    load_s = time.monotonic() - t0
    rss_after = proc.memory_info().rss
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    placed = sum(x.nbytes for x in jax.tree.leaves(params))
    wq = params["layers"]["attn"]["wq"]["kernel"]
    per_dev = wq.addressable_shards[0].data.nbytes
    transient = peak - rss_after
    print(f"load time:            {load_s:.1f}s")
    print(f"placed param bytes:   {placed / GB:.2f} GB "
          f"({len(jax.tree.leaves(params))} tensors, stacked [32, ...])")
    print(f"wq kernel:            {wq.shape} {wq.dtype}, "
          f"per-device shard {per_dev / (1 << 20):.0f} MB (x8 devices)")
    print(f"rss before/after:     {rss_before / GB:.2f} / {rss_after / GB:.2f} GB "
          f"(placed params stay host-resident on the CPU mesh)")
    print(f"peak rss:             {peak / GB:.2f} GB")
    print(f"TRANSIENT overhead:   {transient / GB:.2f} GB "
          f"(vs {ckpt_bytes / GB:.2f} GB checkpoint)")
    ok = transient < 6 * GB
    print("RESULT:", "OK — streaming (transient << checkpoint)" if ok
          else "FAIL — loader materializes too much")

    if not args.keep:
        shutil.rmtree(workdir, ignore_errors=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
