"""ragcheck framework: source model, rule registry, suppressions, baseline.

Three layers, all stdlib:

- :class:`Repo` parses the scan roots (the package + bench.py) once and
  hands every rule the same ASTs; cross-file rules can lazily pull any
  other repo file (tests/, docs/, deploy manifests) through the same cache.
- Rules are objects with a stable ``id`` and a ``run(repo)`` generator of
  :class:`Finding`. A finding carries a *fingerprint* built from the rule
  id, the repo-relative path, and a rule-chosen stable ``key`` (never a
  line number — refactors that move code must not churn the baseline).
- The runner applies inline suppressions (``# ragcheck: disable=RULE-ID``
  on the flagged line or the line above), then gates against the committed
  baseline: a finding not in the baseline fails, and a baseline entry that
  no longer fires fails too ("stale — delete it"), which is what makes the
  baseline a ratchet: it can only shrink.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "Repo",
    "ScopedDefIndex",
    "SourceFile",
    "dotted_name",
    "gate",
    "load_baseline",
    "run_analysis",
]


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location.

    ``key`` is the stable identity used for baselining and must not embed
    line numbers; ``line`` is presentation only (``file:line`` output).
    """

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    key: str

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}::{self.path}::{self.key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_json(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


# ---------------------------------------------------------------------------
# source model
# ---------------------------------------------------------------------------


@dataclass
class SourceFile:
    path: str  # repo-relative
    text: str
    lines: List[str]
    tree: Optional[ast.AST]  # None when the file does not parse


def _norm(relpath: str) -> str:
    return relpath.replace(os.sep, "/")


class Repo:
    """The analyzed tree: eager scan roots + a lazy cache for everything
    else a cross-file rule wants (tests, docs, manifests)."""

    #: default scan roots, repo-relative (directories walk ``**/*.py``)
    SCAN_ROOTS: Tuple[str, ...] = ("rag_llm_k8s_tpu", "bench.py")

    def __init__(self, root: str, scan_roots: Optional[Sequence[str]] = None):
        self.root = os.path.abspath(root)
        self._cache: Dict[str, Optional[SourceFile]] = {}
        self.scan_files: List[SourceFile] = []
        for sr in scan_roots if scan_roots is not None else self.SCAN_ROOTS:
            ap = os.path.join(self.root, sr)
            if os.path.isfile(ap):
                sf = self.get(sr)
                if sf is not None:
                    self.scan_files.append(sf)
            elif os.path.isdir(ap):
                for dirpath, dirnames, names in os.walk(ap):
                    dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                    for n in sorted(names):
                        if not n.endswith(".py"):
                            continue
                        rel = _norm(
                            os.path.relpath(os.path.join(dirpath, n), self.root)
                        )
                        sf = self.get(rel)
                        if sf is not None:
                            self.scan_files.append(sf)
        self.scan_files.sort(key=lambda sf: sf.path)

    def get(self, relpath: str) -> Optional[SourceFile]:
        """Load + parse one python file (cached); None when absent."""
        relpath = _norm(relpath)
        if relpath in self._cache:
            return self._cache[relpath]
        ap = os.path.join(self.root, relpath)
        sf: Optional[SourceFile] = None
        if os.path.isfile(ap):
            with open(ap, encoding="utf-8") as f:
                text = f.read()
            try:
                tree = ast.parse(text, filename=relpath)
            except SyntaxError:
                tree = None
            sf = SourceFile(relpath, text, text.splitlines(), tree)
        self._cache[relpath] = sf
        return sf

    def text(self, relpath: str) -> Optional[str]:
        """Raw text of any repo file (docs, yaml); None when absent."""
        ap = os.path.join(self.root, _norm(relpath))
        if not os.path.isfile(ap):
            return None
        with open(ap, encoding="utf-8") as f:
            return f.read()

    def glob_py(self, reldir: str) -> List[SourceFile]:
        """Every ``*.py`` directly under ``reldir`` (tests/ etc.)."""
        ap = os.path.join(self.root, reldir)
        out: List[SourceFile] = []
        if os.path.isdir(ap):
            for n in sorted(os.listdir(ap)):
                if n.endswith(".py"):
                    sf = self.get(f"{reldir}/{n}")
                    if sf is not None:
                        out.append(sf)
        return out


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (calls/subscripts in
    the chain end the walk — ``jit(f).lower`` has no dotted name)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_attr(node: ast.AST) -> Optional[str]:
    """The last segment of a callee (``self._lock`` → ``_lock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def receiver_of(call_func: ast.AST) -> Optional[ast.AST]:
    """The object a method is called on (``x.join`` → ``x``)."""
    if isinstance(call_func, ast.Attribute):
        return call_func.value
    return None


def name_parts(expr: ast.AST) -> List[str]:
    """Every identifier mentioned in an expression: Name ids plus every
    Attribute segment (``cache.k`` yields both ``cache`` and ``k``)."""
    out: List[str] = []
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
    return out


class ScopedDefIndex:
    """Lexically-scoped ``def`` resolution for a module.

    ``resolve(node, name)`` finds the function definitions a bare ``name``
    at ``node`` would bind to: local sibling ``def``s first, then each
    enclosing function's scope outward, then plain module-level ``def``s.
    Class bodies do not form closure scopes (a method named ``step`` must
    NOT shadow a traced local ``def step`` elsewhere in the file — the
    collision that motivates this index).
    """

    def __init__(self, tree: ast.AST):
        self._parent: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parent[id(child)] = parent
        self._tree = tree
        # scope (FunctionDef or Module) -> {name: [defs]}
        self._by_scope: Dict[int, Dict[str, List[ast.AST]]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self._enclosing_function(node)
                if scope is None and self._has_class_ancestor(node):
                    continue  # methods are attributes, not lexical names
                key = id(scope) if scope is not None else id(tree)
                self._by_scope.setdefault(key, {}).setdefault(
                    node.name, []
                ).append(node)

    def _enclosing_function(self, node: ast.AST):
        cur = self._parent.get(id(node))
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self._parent.get(id(cur))
        return None

    def _has_class_ancestor(self, node: ast.AST) -> bool:
        cur = self._parent.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            cur = self._parent.get(id(cur))
        return False

    def resolve(self, node: ast.AST, name: str) -> List[ast.AST]:
        scope = self._enclosing_function(node)
        while scope is not None:
            hits = self._by_scope.get(id(scope), {}).get(name, [])
            if hits:
                return hits
            scope = self._enclosing_function(scope)
        return self._by_scope.get(id(self._tree), {}).get(name, [])

    def qualname(self, node: ast.AST) -> str:
        """``Class.method.inner`` for a def/lambda — rule keys built from
        this stay unique when two scopes define the same bare name (a bare
        name would dedupe one finding into the other AND let one baseline
        entry mask every same-named function in the file)."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.Module):
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            elif isinstance(cur, ast.Lambda):
                parts.append("<lambda>")
            cur = self._parent.get(id(cur))
        return ".".join(reversed(parts)) or "<module>"


class QualnameVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks ``Class.method`` qualnames in ``self.stack``."""

    def __init__(self):
        self.stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self.stack) or "<module>"

    def _scoped(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_DISABLE_RE = re.compile(r"#\s*ragcheck:\s*disable=([A-Za-z0-9_,\- ]+)")


def _disabled_rules(line_text: str) -> List[str]:
    m = _DISABLE_RE.search(line_text)
    if not m:
        return []
    return [t.strip() for t in m.group(1).split(",") if t.strip()]


def is_suppressed(finding: Finding, repo: Repo) -> bool:
    """``# ragcheck: disable=RULE`` (or ``all``) on the flagged line or the
    line directly above it suppresses the finding."""
    sf = repo.get(finding.path)
    if sf is None or finding.line <= 0:
        return False
    for ln in (finding.line, finding.line - 1):
        if 1 <= ln <= len(sf.lines):
            for rid in _disabled_rules(sf.lines[ln - 1]):
                if rid == "all" or rid == finding.rule:
                    return True
    return False


# ---------------------------------------------------------------------------
# runner + baseline gate
# ---------------------------------------------------------------------------


def run_analysis(
    root: str,
    rules: Optional[Sequence[object]] = None,
    scan_roots: Optional[Sequence[str]] = None,
) -> Tuple[Repo, List[Finding]]:
    """Run every rule over ``root``; returns (repo, suppressed-filtered,
    fingerprint-deduped findings sorted by location)."""
    if rules is None:
        from scripts.ragcheck.rules import ALL_RULES

        rules = [cls() for cls in ALL_RULES]
    repo = Repo(root, scan_roots=scan_roots)
    seen: Dict[str, Finding] = {}
    for rule in rules:
        for f in rule.run(repo):
            if is_suppressed(f, repo):
                continue
            seen.setdefault(f.fingerprint, f)
    findings = sorted(seen.values(), key=lambda f: (f.path, f.line, f.rule))
    return repo, findings


def load_baseline(path: str) -> Dict[str, str]:
    """{fingerprint: justification}. Every entry MUST carry a non-empty
    justification — an unexplained baseline entry is itself an error."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[str, str] = {}
    for e in data.get("entries", []):
        fp = e.get("fingerprint", "")
        just = (e.get("justification") or "").strip()
        if not fp:
            raise ValueError(f"{path}: baseline entry missing 'fingerprint': {e}")
        if not just:
            raise ValueError(
                f"{path}: baseline entry for {fp!r} has no justification — "
                "every baselined finding must say why it is acceptable"
            )
        out[fp] = just
    return out


def gate(
    findings: Sequence[Finding], baseline: Dict[str, str]
) -> Tuple[List[Finding], List[str]]:
    """(new_findings, stale_baseline_fingerprints).

    New findings fail CI (fix, suppress inline, or baseline with a
    justification). Stale entries fail too: the fixed finding's baseline
    row must be DELETED in the same change — that is the ratchet, the
    baseline can only shrink.
    """
    fps = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    stale = sorted(fp for fp in baseline if fp not in fps)
    return new, stale
