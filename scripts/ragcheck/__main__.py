"""CLI: ``python -m scripts.ragcheck`` (what ``make analyze`` runs).

Exit codes: 0 clean (every finding baselined), 1 new findings or a stale
baseline entry (the ratchet), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from scripts.ragcheck.core import gate, load_baseline, run_analysis

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ragcheck",
        description="repo-native static analysis (docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        help="repo root to analyze (default: this checkout)",
    )
    ap.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline JSON (default: scripts/ragcheck/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, baselined or not (exit 1 if any)",
    )
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    try:
        _, findings = run_analysis(args.root)
        baseline = {} if args.no_baseline else load_baseline(args.baseline)
    except ValueError as e:
        print(f"ragcheck: {e}", file=sys.stderr)
        return 2
    new, stale = gate(findings, baseline)

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.as_json() for f in findings],
                    "new": [f.fingerprint for f in new],
                    "stale_baseline": stale,
                    "baselined": len(findings) - len(new),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        for fp in stale:
            print(
                f"stale baseline entry (finding no longer fires): {fp} — "
                "delete it from the baseline (the ratchet only shrinks)"
            )
        n_base = len(findings) - len(new)
        if new or stale:
            print(
                f"ragcheck: {len(new)} new finding(s), {len(stale)} stale "
                f"baseline entr{'y' if len(stale) == 1 else 'ies'} "
                f"({n_base} baselined). Fix the findings, suppress a true "
                "false-positive inline with `# ragcheck: disable=RULE-ID`, "
                "or baseline with a justification "
                "(docs/STATIC_ANALYSIS.md).",
                file=sys.stderr,
            )
        else:
            print(
                f"ragcheck: OK ({len(findings)} finding(s), all baselined "
                f"with justification)" if findings
                else "ragcheck: OK (no findings)"
            )
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
