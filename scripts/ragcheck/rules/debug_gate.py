"""DEBUG-GATE: every /debug/* route handler goes through the 403 gate.

PR 11 established the discipline by hand: the debug surface (traces,
timelines, incident bundles, fault arming, goodput, quality) answers 403
unless the process started armed (``TPU_RAG_FAULTS`` / ``TPU_RAG_DEBUG``)
— a production pod must not leak its journal, config fingerprints or
fault controls to anyone who can reach the port. But nothing enforced it:
the next ``/debug/foo`` route was one forgotten ``if`` away from shipping
ungated, and the uniform-gating test only covers routes someone
remembered to list.

This rule mechanizes it at the source: every ``Rule("/debug/...",
endpoint=<name>)`` registration in the server module must map to an
``ep_<name>`` handler whose body calls one of the sanctioned gates —
``self._debug_enabled()`` (the uniform read-only gate) or
``faults.endpoint_enabled()`` (the stricter fault-arming gate) — before
it can serve anything.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional

from scripts.ragcheck.core import Finding, Repo

SERVER_MODULE = "rag_llm_k8s_tpu/server/app.py"

#: calls that count as "the handler is gated" — the uniform read-only
#: gate, or the fault endpoint's stricter own gate
GATES = ("_debug_enabled", "endpoint_enabled")


def _debug_routes(tree: ast.AST) -> Dict[str, int]:
    """``endpoint name -> lineno`` for every Rule("/debug...") call."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None
        )
        if name != "Rule" or not node.args:
            continue
        path = node.args[0]
        if not (isinstance(path, ast.Constant) and isinstance(path.value, str)):
            continue
        if not path.value.startswith("/debug"):
            continue
        endpoint: Optional[str] = None
        for kw in node.keywords:
            if kw.arg == "endpoint" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                endpoint = kw.value.value
        if endpoint is not None:
            out.setdefault(endpoint, node.lineno)
    return out


def _handlers(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("ep_"):
            out[node.name[len("ep_"):]] = node
    return out


def _is_gated(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in GATES:
            return True
    return False


class DebugGateRule:
    id = "DEBUG-GATE"

    def run(self, repo: Repo) -> Iterable[Finding]:
        sf = repo.get(SERVER_MODULE)
        if sf is None or sf.tree is None:
            return  # no server module in this tree (fixture repos)
        routes = _debug_routes(sf.tree)
        if not routes:
            return
        handlers = _handlers(sf.tree)
        for endpoint, line in sorted(routes.items()):
            fn = handlers.get(endpoint)
            if fn is None:
                yield Finding(
                    rule=self.id,
                    path=sf.path,
                    line=line,
                    message=(
                        f"/debug route endpoint {endpoint!r} has no "
                        f"ep_{endpoint} handler in {SERVER_MODULE} — the "
                        "URL map names a handler that cannot be audited"
                    ),
                    key=f"missing-handler:{endpoint}",
                )
                continue
            if not _is_gated(fn):
                yield Finding(
                    rule=self.id,
                    path=sf.path,
                    line=fn.lineno,
                    message=(
                        f"ep_{endpoint} serves a /debug route without "
                        "calling self._debug_enabled() or "
                        "faults.endpoint_enabled() — every /debug handler "
                        "must 403 unless the process started armed "
                        "(TPU_RAG_FAULTS / TPU_RAG_DEBUG); see "
                        "docs/OBSERVABILITY.md 'Debug-surface gating'"
                    ),
                    key=f"ungated-debug-route:{endpoint}",
                )
