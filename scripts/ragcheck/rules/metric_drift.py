"""METRIC-DRIFT: the metrics surface matches its docs and its own shape.

Absorbs ``scripts/check_metrics_docs.py`` (PR 2's lint gate) as sub-check
1 and adds the label-set discipline PRs 6-8 were hand-reviewing:

1. **docs**: every metric name registered in code — registry
   ``counter/gauge/histogram/labeled_*`` calls and the legacy facade's
   ``inc``/``observe`` string literals — appears in
   ``docs/OBSERVABILITY.md``. An undocumented family is invisible to the
   operator the RUNBOOK sends to the table.
2. **label-set consistency**: a family is always emitted with the same
   label NAMES. Prometheus treats ``f{stage=...}`` and ``f{phase=...}``
   as disjoint series under one name — every aggregation over the family
   silently splits.
3. **no dynamically-formatted label values**: an f-string / ``%`` /
   ``.format()`` label value is an unbounded-cardinality time series
   waiting for traffic. Pass a bounded literal (or ``str(code)`` over a
   bounded domain) instead.

Resolution is intra-file and deliberately simple: a ``.labels(...)`` /
``.labels_callback(...)`` call maps to a family when chained directly on a
``labeled_*("name", ...)`` registration or when its receiver's name was
assigned from one anywhere in the same file. Unresolvable receivers are
skipped, not guessed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from scripts.ragcheck.core import Finding, Repo, terminal_attr

DOC = "docs/OBSERVABILITY.md"
#: the registry implementation itself registers nothing
_FRAMEWORK = "rag_llm_k8s_tpu/obs/metrics.py"

_REGISTER_CALLS = {
    "counter", "gauge", "histogram",
    "labeled_counter", "labeled_gauge", "labeled_histogram",
}
_LABELED_CALLS = {"labeled_counter", "labeled_gauge", "labeled_histogram"}
_FACADE_CALLS = {"inc", "observe"}
_NAME_OK = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _metric_literal(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str) \
            and _NAME_OK.match(call.args[0].value):
        return call.args[0].value
    return None


def _is_dynamic_value(expr: ast.AST) -> bool:
    """f-string, percent-format, or ``"...".format(...)`` label values."""
    if isinstance(expr, ast.JoinedStr):
        return True
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mod) \
            and isinstance(expr.left, ast.Constant) \
            and isinstance(expr.left.value, str):
        return True
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "format":
        return True
    return False


def _registrations(sf) -> Iterable[Tuple[str, int]]:
    """Every (metric_name, line) registered in one file."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        t = terminal_attr(node.func)
        if t in _REGISTER_CALLS or (
            t in _FACADE_CALLS and isinstance(node.func, ast.Attribute)
        ):
            name = _metric_literal(node)
            if name is not None:
                yield name, node.lineno


def _family_bindings(sf) -> Dict[str, str]:
    """{local var / attribute name: family name} for labeled_* assignments
    (``fam = reg.labeled_counter("x")`` and ``self._m_x = ...``)."""
    out: Dict[str, str] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        if not isinstance(val, ast.Call):
            continue
        if terminal_attr(val.func) not in _LABELED_CALLS:
            continue
        fam = _metric_literal(val)
        if fam is None:
            continue
        for tgt in node.targets:
            t = terminal_attr(tgt)
            if t is not None:
                out[t] = fam
    return out


def _label_sites(sf, bindings: Dict[str, str]):
    """(family, frozenset(label names), lineno, dynamic kwargs) per
    resolvable .labels()/.labels_callback() call."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        t = terminal_attr(node.func)
        if t not in ("labels", "labels_callback"):
            continue
        recv = node.func.value if isinstance(node.func, ast.Attribute) else None
        fam: Optional[str] = None
        if isinstance(recv, ast.Call) and \
                terminal_attr(recv.func) in _LABELED_CALLS:
            fam = _metric_literal(recv)
        elif recv is not None:
            rn = terminal_attr(recv)
            if rn is not None:
                fam = bindings.get(rn)
        if fam is None:
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **splat: unresolvable, skip rather than guess
        names = frozenset(kw.arg for kw in node.keywords)
        dynamic = [
            (kw.arg, kw.value.lineno)
            for kw in node.keywords
            if _is_dynamic_value(kw.value)
        ]
        yield fam, names, node.lineno, dynamic


class MetricDriftRule:
    id = "METRIC-DRIFT"

    def run(self, repo: Repo) -> Iterable[Finding]:
        doc = repo.text(DOC)
        registered: Dict[str, Tuple[str, int]] = {}
        # family -> {labelset -> (path, line) first seen}
        label_sets: Dict[str, Dict[frozenset, Tuple[str, int]]] = {}
        for sf in repo.scan_files:
            if sf.tree is None or sf.path == _FRAMEWORK:
                continue
            for name, lineno in _registrations(sf):
                registered.setdefault(name, (sf.path, lineno))
            bindings = _family_bindings(sf)
            for fam, names, lineno, dynamic in _label_sites(sf, bindings):
                for label, dline in dynamic:
                    yield Finding(
                        rule=self.id,
                        path=sf.path,
                        line=dline,
                        message=(
                            f"label {label!r} of {fam} is dynamically "
                            "formatted — unbounded label cardinality; use "
                            "a bounded literal domain"
                        ),
                        key=f"dynamic-label:{fam}:{label}",
                    )
                label_sets.setdefault(fam, {}).setdefault(
                    names, (sf.path, lineno)
                )

        # 1. docs coverage (the absorbed check_metrics_docs gate)
        if not registered and doc is not None:
            # the old script's scanner-rot self-check: a tree that SHIPS an
            # OBSERVABILITY.md but registers zero discoverable metrics
            # means the matcher broke (API rename, scan-root drift) — the
            # gate must fail loudly, not go vacuously green forever
            yield Finding(
                rule=self.id, path=DOC, line=1,
                message=(
                    f"{DOC} exists but the scanner found ZERO metric "
                    "registrations — the METRIC-DRIFT matcher no longer "
                    "recognizes the registry API (scanner rot)"
                ),
                key="no-registrations-found",
            )
        if registered:
            if doc is None:
                yield Finding(
                    rule=self.id, path=DOC, line=1,
                    message=f"{DOC} missing but metrics are registered",
                    key="missing-doc",
                )
            else:
                for name, (path, lineno) in sorted(registered.items()):
                    if f"`{name}`" not in doc and name not in doc:
                        yield Finding(
                            rule=self.id,
                            path=path,
                            line=lineno,
                            message=(
                                f"metric {name} is registered here but "
                                f"absent from {DOC} — add a table row"
                            ),
                            key=f"undocumented:{name}",
                        )

        # 2. label-name consistency across every emission site of a family
        for fam, sets in sorted(label_sets.items()):
            if len(sets) <= 1:
                continue
            canon = sorted(sets.items(), key=lambda kv: (kv[1], sorted(kv[0])))
            canon_names, (cpath, cline) = canon[0]
            for names, (path, lineno) in canon[1:]:
                yield Finding(
                    rule=self.id,
                    path=path,
                    line=lineno,
                    message=(
                        f"family {fam} emitted with labels "
                        f"{{{', '.join(sorted(names)) or '∅'}}} here but "
                        f"{{{', '.join(sorted(canon_names)) or '∅'}}} at "
                        f"{cpath}:{cline} — one family, one label set"
                    ),
                    key=(
                        f"labelset:{fam}:{'/'.join(sorted(names))}"
                    ),
                )
