"""FAULT-SITE-REGISTRY: the fault-site catalog is closed AND exercised.

``resilience/faults.SITES`` is deliberately closed — a typo'd site name is
a programming error, not a silently-never-firing fault. The runtime
enforces that for *armed* names, but nothing enforced it for the
``maybe_fail("...")`` call sites themselves (a typo there compiles fine
and simply never fires, which is how a chaos lane rots), nor that each
catalog entry is actually pulled by at least one test (an unexercised
site is an untested recovery path wearing a tested one's name).

Two sub-checks:

1. every string literal passed to ``maybe_fail(...)`` / ``arm(...)`` (in
   the package AND in tests/) is a member of ``SITES``;
2. every ``SITES`` entry appears as a string literal somewhere in tests/.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from scripts.ragcheck.core import Finding, Repo, terminal_attr

FAULTS_MODULE = "rag_llm_k8s_tpu/resilience/faults.py"
_ARMING_CALLS = {"maybe_fail", "arm"}


def _declared_sites(repo: Repo) -> Tuple[Optional[int], List[str]]:
    sf = repo.get(FAULTS_MODULE)
    if sf is None or sf.tree is None:
        return None, []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "SITES":
                vals = [
                    e.value
                    for e in ast.walk(node.value)
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                return node.lineno, vals
    return None, []


def _site_literal(call: ast.Call) -> Optional[ast.Constant]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "site" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value
    return None


class FaultSiteRegistryRule:
    id = "FAULT-SITE-REGISTRY"

    def run(self, repo: Repo) -> Iterable[Finding]:
        line, sites = _declared_sites(repo)
        if line is None:
            return  # no faults module in this tree (fixture repos)
        site_set: Set[str] = set(sites)

        test_files = repo.glob_py("tests")
        scan = list(repo.scan_files) + test_files
        for sf in scan:
            if sf.tree is None or sf.path == FAULTS_MODULE:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                t = terminal_attr(node.func)
                if t not in _ARMING_CALLS:
                    continue
                lit = _site_literal(node)
                if lit is None or lit.value in site_set:
                    continue
                yield Finding(
                    rule=self.id,
                    path=sf.path,
                    line=node.lineno,
                    message=(
                        f"{t}({lit.value!r}) names a site not in "
                        f"resilience/faults.SITES — a typo'd site never "
                        "fires; add it to the catalog or fix the name"
                    ),
                    key=f"unknown-site:{lit.value}",
                )

        # 2. every catalog entry is exercised by at least one test — as an
        # EXACT string literal (AST constants): a docstring sentence that
        # merely mentions the site must not count as exercising it
        test_literals: Set[str] = set()
        for sf in test_files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    test_literals.add(node.value)
        for site in sites:
            if site not in test_literals:
                yield Finding(
                    rule=self.id,
                    path=FAULTS_MODULE,
                    line=line,
                    message=(
                        f"fault site {site!r} is in SITES but no test names "
                        "it — an unexercised site is an untested recovery "
                        "path; arm it in a chaos test or retire the entry"
                    ),
                    key=f"untested-site:{site}",
                )
