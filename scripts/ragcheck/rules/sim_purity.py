"""SIM-PURITY: path-loaded modules stay stdlib-only, package-import-free.

A small set of modules is loaded DIRECTLY by file path on hosts that
hold nothing but a journal file — flightview on a laptop, the replay/
simulator harness on a CPU pod, capacity-planning scripts. The contract
that makes that work is twofold and was, until this rule, enforced only
by convention:

1. **stdlib-only imports** — no jax, no numpy, no third-party anything
   (the loading host has none of it installed);
2. **no package-internal imports** — ``import rag_llm_k8s_tpu.…``
   (absolute or relative) would execute package ``__init__`` chains that
   pull tracing → jax; path-loaded modules reach siblings through
   ``sim/policy.py``'s ``load_sibling`` (file-path importlib) instead.

The pure set is every module under ``rag_llm_k8s_tpu/sim/`` plus the
obs/ modules flightview already path-loads (``flight.py``,
``goodput.py``, ``shadow.py``). A violation is a landmine: the package
import works fine in CI (where jax exists) and detonates on the first
laptop that opens a bundle.
"""

from __future__ import annotations

import ast
import sys
from typing import Iterable, List

from scripts.ragcheck.core import Finding, Repo

PACKAGE = "rag_llm_k8s_tpu"

#: path-loaded obs modules (flightview's `_load_obs_module` targets +
#: the replay harness's `load_sibling("../obs/...")` targets)
PURE_OBS = (
    f"{PACKAGE}/obs/flight.py",
    f"{PACKAGE}/obs/goodput.py",
    f"{PACKAGE}/obs/shadow.py",
    f"{PACKAGE}/obs/tenants.py",
)

#: stdlib fallback for interpreters predating sys.stdlib_module_names —
#: only the modules the pure set actually uses plus common suspects, so
#: an unknown import fails CLOSED (flagged) rather than open
_STDLIB_FALLBACK = frozenset({
    "abc", "argparse", "ast", "bisect", "collections", "contextlib",
    "copy", "dataclasses", "enum", "functools", "hashlib", "heapq",
    "importlib", "io", "itertools", "json", "logging", "math", "os",
    "pathlib", "random", "re", "statistics", "string", "sys",
    "threading", "time", "types", "typing", "unittest", "warnings",
    "weakref", "__future__",
})


def _stdlib_names() -> frozenset:
    names = getattr(sys, "stdlib_module_names", None)
    return frozenset(names) if names else _STDLIB_FALLBACK


class SimPurityRule:
    id = "SIM-PURITY"

    def run(self, repo: Repo) -> Iterable[Finding]:
        stdlib = _stdlib_names()
        targets: List = []
        for sf in repo.scan_files:
            if sf.path.startswith(f"{PACKAGE}/sim/"):
                targets.append(sf)
        for rel in PURE_OBS:
            sf = repo.get(rel)
            if sf is not None:
                targets.append(sf)
        for sf in targets:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        yield from self._check(sf, node, alias.name, stdlib)
                elif isinstance(node, ast.ImportFrom):
                    if node.level and node.level > 0:
                        yield Finding(
                            rule=self.id, path=sf.path, line=node.lineno,
                            message=(
                                "relative import in a path-loaded module — "
                                "there is no package when this file is "
                                "loaded by path; use policy.load_sibling"
                            ),
                            key=f"relative-import:{node.module or ''}",
                        )
                        continue
                    yield from self._check(
                        sf, node, node.module or "", stdlib
                    )

    def _check(self, sf, node, modname: str, stdlib) -> Iterable[Finding]:
        top = modname.split(".", 1)[0]
        if not top:
            return
        if top == PACKAGE:
            yield Finding(
                rule=self.id, path=sf.path, line=node.lineno,
                message=(
                    f"package-internal import {modname!r} in a path-loaded "
                    "module — executes package __init__ chains (tracing → "
                    "jax) on hosts that have neither; use "
                    "policy.load_sibling to reach siblings by file path"
                ),
                key=f"package-import:{modname}",
            )
        elif top not in stdlib:
            yield Finding(
                rule=self.id, path=sf.path, line=node.lineno,
                message=(
                    f"non-stdlib import {modname!r} in a path-loaded "
                    "module — flightview/replay hosts install no "
                    "third-party deps; keep the module stdlib-only"
                ),
                key=f"nonstdlib-import:{modname}",
            )
