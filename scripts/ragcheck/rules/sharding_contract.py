"""SHARDING-CONTRACT: executables returning arena/cache state pin shardings.

PR 6's "dense path's lesson": a ``jax.jit`` whose outputs include the KV
arena/cache planes but whose construction does not pin ``out_shardings``
lets XLA pick an output layout — on a mesh the state silently gathers to
one device (or re-lays-out per call), and the next donation round-trip
either OOMs or quietly de-shards the pool. Every paged executable in
``engine/continuous.py`` pins its arena outputs for exactly this reason.

Detection: for ``jax.jit(f, ...)``/``pjit(f, ...)`` where ``f`` is a
function defined in the same module, the rule looks at what ``f`` returns.
If a returned expression mentions a state-like identifier — ``cache``,
``arena``, ``plane(s)``, or a ``kv``-prefixed/suffixed name — directly, or
via the returned name's own assignment one level back (``out = (cache.k,
cache.v)`` … ``return out``), the jit call must carry an ``out_shardings``
keyword. Token/logit-returning executables are exempt by construction:
their returns never name cache state.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List

from scripts.ragcheck.core import (
    Finding,
    Repo,
    ScopedDefIndex,
    dotted_name,
)

_STATEFUL = re.compile(r"(cache|arena|plane|^kv$|^kv_|_kv$)", re.IGNORECASE)


def _container_names(expr: ast.AST) -> List[str]:
    """Identifiers in an expression EXCLUDING call subtrees: ``(cache.k,
    cache.v)`` exposes ``cache`` but ``model.apply(..., cache, ...)`` does
    not — a function's *result* is not the state that went in."""
    out: List[str] = []

    def walk(node: ast.AST):
        if isinstance(node, ast.Call):
            return
        if isinstance(node, ast.Name):
            out.append(node.id)
        elif isinstance(node, ast.Attribute):
            out.append(node.attr)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(expr)
    return out


def _assignment_rhs_names(fn: ast.AST, name: str) -> List[str]:
    """Call-free identifiers on the RHS of every ``name = ...`` in fn."""
    out: List[str] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Name) and t.id == name:
                        out.extend(_container_names(node.value))
    return out


def _own_returns(fn: ast.FunctionDef) -> List[ast.Return]:
    """fn's own Return statements — nested ``def``/``lambda`` bodies return
    to their own callers (while_loop bodies carry cache state legitimately)
    and must not be attributed to fn."""
    out: List[ast.Return] = []

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(child, ast.Return) and child.value is not None:
                out.append(child)
            walk(child)

    walk(fn)
    return out


def _returns_state(fn: ast.FunctionDef) -> bool:
    for node in _own_returns(fn):
        # call-excluding on the direct return too: `return model.apply(...,
        # cache, ...)[0]` is logits THROUGH a call, not state (the same
        # exemption the one-level trace applies)
        names = _container_names(node.value)
        if any(_STATEFUL.search(n) for n in names):
            return True
        # one level of indirection: `out = (cache.k, ...)` ... `return out`
        for n in names:
            if any(_STATEFUL.search(r) for r in _assignment_rhs_names(fn, n)):
                return True
    return False


def _trace_decorator_info(fn: ast.AST):
    """(is_jit_decorated, has_out_shardings) for ``@jax.jit`` /
    ``@functools.partial(jax.jit, ...)`` decorator forms."""
    for dec in getattr(fn, "decorator_list", []):
        d = dotted_name(dec)
        if d is not None and d.split(".")[-1] in ("jit", "pjit"):
            return True, False  # bare @jax.jit cannot pass out_shardings
        if isinstance(dec, ast.Call):
            dd = dotted_name(dec.func)
            if dd is None:
                continue
            last = dd.split(".")[-1]
            if last in ("jit", "pjit"):
                return True, any(
                    kw.arg == "out_shardings" for kw in dec.keywords
                )
            if last == "partial" and dec.args:
                a0 = dotted_name(dec.args[0])
                if a0 is not None and a0.split(".")[-1] in ("jit", "pjit"):
                    return True, any(
                        kw.arg == "out_shardings" for kw in dec.keywords
                    )
    return False, False


class ShardingContractRule:
    id = "SHARDING-CONTRACT"

    def run(self, repo: Repo) -> Iterable[Finding]:
        for sf in repo.scan_files:
            if sf.tree is None:
                continue
            index = ScopedDefIndex(sf.tree)
            for node in ast.walk(sf.tree):
                # decorator form: @jax.jit / @functools.partial(jax.jit, …)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    decorated, has_out = _trace_decorator_info(node)
                    if decorated and not has_out and _returns_state(node):
                        yield Finding(
                            rule=self.id,
                            path=sf.path,
                            line=node.lineno,
                            message=(
                                f"@jit-decorated {node.name} returns "
                                "arena/cache state but pins no "
                                "out_shardings — use functools.partial("
                                "jax.jit, out_shardings=...) (PR 6's "
                                "dense-path lesson)"
                            ),
                            key=f"jit:{index.qualname(node)}",
                        )
                    continue
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d is None or d.split(".")[-1] not in ("jit", "pjit"):
                    continue
                if not node.args or not isinstance(node.args[0], ast.Name):
                    continue
                fname = node.args[0].id
                fns = index.resolve(node, fname)
                if not fns:
                    continue
                has_out = any(kw.arg == "out_shardings" for kw in node.keywords)
                if has_out:
                    continue
                hit = next((fn for fn in fns if _returns_state(fn)), None)
                if hit is not None:
                    yield Finding(
                        rule=self.id,
                        path=sf.path,
                        line=node.lineno,
                        message=(
                            f"jit({fname}) returns arena/cache state but "
                            "pins no out_shardings — on a mesh the state "
                            "silently de-shards on the way out (PR 6's "
                            "dense-path lesson); pin the output specs"
                        ),
                        key=f"jit:{index.qualname(hit)}",
                    )
