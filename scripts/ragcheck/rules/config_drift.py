"""CONFIG-DRIFT: env knobs live in core/config.py and stay pinned in deploy.

Two sub-checks, one discipline — configuration has exactly one home and
two mirrors:

1. **env-read placement**: any ``os.environ`` / ``os.getenv`` reference in
   the package outside ``core/config.py`` is drift (the ``TPU_RAG_SLO_*``
   knobs hid in ``obs/slo.py`` for three PRs and one malformed value away
   from a scrape-time ValueError). ``server/main.py`` is the bootstrap
   allowlist: logging must configure before ``AppConfig`` can exist.
2. **knob pinning**: every ``TPU_RAG_*`` knob named in ``core/config.py``
   must appear in ``deploy/llm/deploy.yaml`` (a knob you cannot see in the
   manifest is a knob production is not running) and in the RUNBOOK's
   §"Configuration reference" table (an operator paged at 3am reads the
   table, not ``from_env``).

Knob extraction is AST-literal based: string constants matching
``TPU_RAG_[A-Z0-9_]+`` exactly (docstrings mention knobs inside prose and
never as exact-match literals, so they don't count).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from scripts.ragcheck.core import Finding, QualnameVisitor, Repo, dotted_name

CONFIG_HOME = "rag_llm_k8s_tpu/core/config.py"
#: bootstrap allowlist: files that may read the environment directly
#: (process setup that runs before a config object can exist)
ENV_READ_ALLOWLIST = ("rag_llm_k8s_tpu/server/main.py",)

DEPLOY_MANIFEST = "deploy/llm/deploy.yaml"
RUNBOOK = "docs/RUNBOOK.md"
_RUNBOOK_SECTION = "Configuration reference"

_KNOB = re.compile(r"^TPU_RAG_[A-Z0-9_]+$")


class _EnvReadVisitor(QualnameVisitor):
    def __init__(self):
        super().__init__()
        self.sites: List = []  # (qualname, lineno, what)

    def visit_Attribute(self, node: ast.Attribute):
        if dotted_name(node) == "os.environ":
            self.sites.append((self.qualname, node.lineno, "os.environ"))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if dotted_name(node.func) == "os.getenv":
            self.sites.append((self.qualname, node.lineno, "os.getenv"))
        self.generic_visit(node)


def _config_knobs(repo: Repo) -> List[tuple]:
    sf = repo.get(CONFIG_HOME)
    if sf is None or sf.tree is None:
        return []
    knobs = {}
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _KNOB.match(node.value)
        ):
            knobs.setdefault(node.value, node.lineno)
    return sorted(knobs.items())


def _runbook_config_table(text: str) -> str:
    """The configuration-reference SECTION only (matched as a markdown
    heading — the table of contents also names it): presence elsewhere in
    the RUNBOOK (a troubleshooting aside) is not documentation of the
    knob."""
    m = re.search(
        rf"^#+ .*{re.escape(_RUNBOOK_SECTION)}.*$", text, re.MULTILINE
    )
    if m is None:
        return ""
    rest = text[m.end():]
    nxt = re.search(r"^## ", rest, re.MULTILINE)
    return rest if nxt is None else rest[: nxt.start()]


class ConfigDriftRule:
    id = "CONFIG-DRIFT"

    def run(self, repo: Repo) -> Iterable[Finding]:
        # 1. env-read placement
        for sf in repo.scan_files:
            if sf.tree is None or not sf.path.startswith("rag_llm_k8s_tpu/"):
                continue
            if sf.path == CONFIG_HOME or sf.path in ENV_READ_ALLOWLIST:
                continue
            v = _EnvReadVisitor()
            v.visit(sf.tree)
            seen: Set[str] = set()
            for qual, lineno, what in v.sites:
                key = f"env-read:{qual}"
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    rule=self.id,
                    path=sf.path,
                    line=lineno,
                    message=(
                        f"{what} read in {qual} — every env knob is parsed "
                        "once, safely, in core/config.py; thread the value "
                        "through a config object instead"
                    ),
                    key=key,
                )

        # 2. knob pinning in deploy.yaml + RUNBOOK config-reference table
        knobs = _config_knobs(repo)
        if not knobs:
            return
        deploy = repo.text(DEPLOY_MANIFEST)
        runbook = repo.text(RUNBOOK)
        table = _runbook_config_table(runbook) if runbook is not None else None
        # a tree that DEFINES knobs but has no manifest / no config-reference
        # section is the same scanner-rot class METRIC-DRIFT guards against:
        # renaming deploy.yaml must not silently retire the whole gate
        if deploy is None:
            yield Finding(
                rule=self.id, path=DEPLOY_MANIFEST, line=1,
                message=(
                    f"{DEPLOY_MANIFEST} is missing but core/config.py "
                    "defines knobs — the pinning gate has nothing to check "
                    "(manifest moved? update ragcheck's DEPLOY_MANIFEST)"
                ),
                key="missing-deploy-manifest",
            )
        if table is None or not table.strip():
            yield Finding(
                rule=self.id, path=RUNBOOK, line=1,
                message=(
                    f"{RUNBOOK} has no '{_RUNBOOK_SECTION}' section but "
                    "core/config.py defines knobs — the documentation gate "
                    "has nothing to check"
                ),
                key="missing-runbook-config-section",
            )
            table = None
        for name, lineno in knobs:
            # word-bounded: TPU_RAG_KV_TIERING must not read as pinned just
            # because TPU_RAG_KV_TIERING_WARM_BELOW is ('_' is a word char,
            # so \b rejects the prefix-of-a-longer-knob match)
            if deploy is not None and not re.search(
                rf"\b{re.escape(name)}\b", deploy
            ):
                yield Finding(
                    rule=self.id,
                    path=DEPLOY_MANIFEST,
                    line=1,
                    message=(
                        f"config knob {name} (core/config.py:{lineno}) is "
                        "not pinned in the deployment manifest — production "
                        "must state every knob it runs, even at the default"
                    ),
                    key=f"knob-deploy:{name}",
                )
            if table is not None and f"`{name}`" not in table:
                yield Finding(
                    rule=self.id,
                    path=RUNBOOK,
                    line=1,
                    message=(
                        f"config knob {name} (core/config.py:{lineno}) has "
                        "no row in the RUNBOOK configuration-reference table"
                    ),
                    key=f"knob-runbook:{name}",
                )
