"""LOCK-DISCIPLINE: no blocking device/host work inside a lock body.

The PR 7/8 hardening class: every cache/pool lock in this repo guards
nothing but host bookkeeping, and every time a device transfer, a compiled
executable, a sleep, or a thread join crept under one, it serialized every
concurrent resolve (or deadlocked a drain) until a reviewer caught it.
Canonical fixes on file: ``PrefixCache._swap_in`` runs its ``device_put``
unlocked and installs under a stamp-guarded re-acquire; the retier sweep's
cold-spill D2H copies run off-lock with a plane-identity install guard.

Flagged inside any ``with <...>_lock:`` body (nested ``def``/``lambda``
bodies are deferred execution, not lock-held, and are skipped):

- ``jax.device_put`` / ``.block_until_ready()`` — device transfers/syncs;
- ``time.sleep`` — never hold a lock to wait;
- thread joins (``x.join(timeout=...)`` or a receiver named like a
  thread/worker/sweeper) — a join under the lock the worker needs is a
  deadlock with extra steps;
- coalescer/executor/scheduler ``submit()`` — blocks until a whole batch
  window dispatches;
- compiled-executable work: invoking a ``_compiled[...]`` entry, calling a
  ``_build_*`` executable builder, or running a ``jax.jit(...)...
  .lower(...).compile()`` chain — compiles and device programs take
  arbitrarily long and must never be timed under a lock.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from scripts.ragcheck.core import (
    Finding,
    QualnameVisitor,
    Repo,
    dotted_name,
    receiver_of,
    terminal_attr,
)

_LOCK_NAME = re.compile(r"(^|_)lock$")
_THREADISH = re.compile(r"(thread|worker|sweeper)", re.IGNORECASE)
_SUBMITTISH = re.compile(r"(coalescer|executor|scheduler|pool)", re.IGNORECASE)


def _is_lock_ctx(expr: ast.AST) -> bool:
    t = terminal_attr(expr)
    return t is not None and bool(_LOCK_NAME.search(t))


def _chain_has_jit(node: ast.AST) -> bool:
    """True when an attribute/call chain bottoms out at jax.jit/pjit
    (``jax.jit(f).lower(...).compile()``)."""
    while True:
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d is not None and d.split(".")[-1] in ("jit", "pjit"):
                return True
            node = node.func
        elif isinstance(node, ast.Attribute):
            node = node.value
        else:
            return False


def _offense(call: ast.Call) -> str | None:
    """The violation label for a call, or None when it is allowed."""
    func = call.func
    t = terminal_attr(func)
    d = dotted_name(func)
    if t == "device_put":
        return "device_put"
    if t == "block_until_ready":
        return "block_until_ready"
    if d == "time.sleep":
        return "time.sleep"
    if t == "join":
        recv = receiver_of(func)
        rname = terminal_attr(recv) if recv is not None else None
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        if has_timeout or (rname and _THREADISH.search(rname)):
            return "thread-join"
    if t == "submit":
        recv = receiver_of(func)
        rname = terminal_attr(recv) if recv is not None else None
        if rname and _SUBMITTISH.search(rname):
            return "submit"
    if t and t.startswith("_build_"):
        return f"executable-build:{t}"
    if isinstance(func, ast.Subscript):
        sub = terminal_attr(func.value)
        if sub and "compiled" in sub:
            return "compiled-executable-call"
    if t in ("lower", "compile") and _chain_has_jit(func):
        return "jit-lower-compile"
    return None


class _Visitor(QualnameVisitor):
    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self.findings: List[Finding] = []

    def visit_With(self, node: ast.With):
        lock_items = [i for i in node.items if _is_lock_ctx(i.context_expr)]
        if lock_items:
            lock = terminal_attr(lock_items[0].context_expr)
            for stmt in node.body:
                self._scan_locked(stmt, lock)
        self.generic_visit(node)

    def _scan_locked(self, node: ast.AST, lock: str):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # deferred execution — not run under the lock
        if isinstance(node, ast.Call):
            off = _offense(node)
            if off is not None:
                self.findings.append(
                    Finding(
                        rule=LockDisciplineRule.id,
                        path=self.path,
                        line=node.lineno,
                        message=(
                            f"{off} inside `with {lock}:` in {self.qualname} — "
                            "move the blocking work outside the lock and "
                            "install the result under a short re-acquire"
                        ),
                        key=f"{self.qualname}:{off}",
                    )
                )
        for child in ast.iter_child_nodes(node):
            self._scan_locked(child, lock)


class LockDisciplineRule:
    id = "LOCK-DISCIPLINE"

    def run(self, repo: Repo) -> Iterable[Finding]:
        for sf in repo.scan_files:
            if sf.tree is None:
                continue
            v = _Visitor(sf.path)
            v.visit(sf.tree)
            yield from v.findings
