"""EVENT-REGISTRY: the flight-event catalog is closed, emitted, and doc'd.

``obs/flight.EVENTS`` is deliberately closed — a typo'd event name would
journal nothing, and the lifecycle timeline it should have appeared in
reads as "this never happened". The runtime enforces that for names that
REACH ``emit`` (unknown types raise), but nothing enforced the other
directions: a catalog entry no emit site ever produces is an event type
wearing a timeline's name with nothing behind it, and an undocumented one
is a journal field nobody can read in a post-mortem. Mirrors
FAULT-SITE-REGISTRY three ways:

1. every string literal passed to ``flight.emit(...)`` across the package
   (and tests/) is a member of ``EVENTS``;
2. every ``EVENTS`` entry is emitted by at least one ``flight.emit`` call
   site in the package;
3. every ``EVENTS`` entry appears BACKTICKED in docs/OBSERVABILITY.md (a
   bare prose word that happens to match a short event name must not
   count) — and a tree that declares events while the doc is missing
   fails loudly instead of going vacuously green.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from scripts.ragcheck.core import Finding, Repo

FLIGHT_MODULE = "rag_llm_k8s_tpu/obs/flight.py"
EVENTS_DOC = "docs/OBSERVABILITY.md"


def _declared_events(repo: Repo) -> Tuple[Optional[int], List[str]]:
    sf = repo.get(FLIGHT_MODULE)
    if sf is None or sf.tree is None:
        return None, []
    for node in ast.walk(sf.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == "EVENTS":
                if not isinstance(node.value, ast.Dict):
                    return node.lineno, []
                keys = [
                    k.value for k in node.value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                ]
                return node.lineno, keys
    return None, []


def _is_flight_emit(call: ast.Call) -> bool:
    """Match the one sanctioned call shape, ``flight.emit(...)`` (any
    aliasing of the module keeps the terminal attribute) — a bare
    ``emit(...)`` could be anything and is not the package idiom."""
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "emit"
        and isinstance(f.value, ast.Name)
        and f.value.id in ("flight", "obs_flight")
    )


def _event_literal(call: ast.Call) -> Optional[ast.Constant]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "etype" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value
    return None


class EventRegistryRule:
    id = "EVENT-REGISTRY"

    def run(self, repo: Repo) -> Iterable[Finding]:
        line, events = _declared_events(repo)
        if line is None:
            return  # no flight module in this tree (fixture repos)
        event_set = set(events)

        emitted: set = set()
        scan = list(repo.scan_files) + repo.glob_py("tests")
        for sf in scan:
            if sf.tree is None or sf.path == FLIGHT_MODULE:
                continue
            in_package = not sf.path.startswith("tests/")
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call) or not _is_flight_emit(node):
                    continue
                lit = _event_literal(node)
                if lit is None:
                    continue
                if lit.value in event_set:
                    if in_package:
                        emitted.add(lit.value)
                    continue
                yield Finding(
                    rule=self.id,
                    path=sf.path,
                    line=node.lineno,
                    message=(
                        f"flight.emit({lit.value!r}) names an event not in "
                        "obs/flight.EVENTS — the catalog is closed; add the "
                        "entry (and its doc row) or fix the name"
                    ),
                    key=f"unknown-event:{lit.value}",
                )

        # 2. every catalog entry has a live emit site in the PACKAGE
        for ev in events:
            if ev not in emitted:
                yield Finding(
                    rule=self.id,
                    path=FLIGHT_MODULE,
                    line=line,
                    message=(
                        f"flight event {ev!r} is in EVENTS but no "
                        "flight.emit site in the package produces it — a "
                        "never-emitted event is a timeline that can't "
                        "happen; instrument the decision point or retire "
                        "the entry"
                    ),
                    key=f"unemitted-event:{ev}",
                )

        # 3. every catalog entry is documented (and the doc must exist
        # while events do — a renamed doc must not retire the gate)
        doc = repo.get(EVENTS_DOC)
        if doc is None:
            if events:
                yield Finding(
                    rule=self.id,
                    path=FLIGHT_MODULE,
                    line=line,
                    message=(
                        f"{EVENTS_DOC} is missing while flight.EVENTS "
                        "declares entries — the event table has nowhere "
                        "to live; restore the doc"
                    ),
                    key="events-doc-missing",
                )
            return
        for ev in events:
            # the BACKTICKED form only: a prose word that happens to match
            # a short event name ("reset", "complete") must not count as
            # documentation
            if f"`{ev}`" in doc.text:
                continue
            yield Finding(
                rule=self.id,
                path=EVENTS_DOC,
                line=1,
                message=(
                    f"flight event {ev!r} has no row in {EVENTS_DOC} — an "
                    "undocumented journal event is unreadable in a "
                    "post-mortem; add it to the event-type table"
                ),
                key=f"undocumented-event:{ev}",
            )
