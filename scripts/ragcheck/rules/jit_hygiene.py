"""JIT-HYGIENE: no host-side effects or concretization in traced functions.

A function handed to ``jax.jit``/``pjit``/``shard_map``/``pl.pallas_call``
runs ONCE at trace time; host calls inside it silently bake a single value
into the compiled program (``time.time()`` freezes the clock, ``random.*``
freezes the "randomness") and concretizing a traced value
(``float(x)``/``int(x)``/``bool(x)``/``.item()``) either raises a
``TracerError`` at the first untested call or forces a device sync where
one executable was expected. Both classes shipped to review repeatedly;
both are mechanical to detect.

Flagged inside a traced function (nested ``def``s included — ``cond``/
``body`` closures run traced too):

- calls into ``time.*``, ``random.*``, ``np.random.*`` / ``numpy.random.*``;
- ``.item()`` anywhere;
- ``float()``/``int()``/``bool()`` applied directly to one of the traced
  function's PARAMETERS (static python values computed before the closure
  are fine — only tracer concretization is the bug).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from scripts.ragcheck.core import Finding, Repo, ScopedDefIndex, dotted_name

_TRACE_ENTRYPOINTS = {"jit", "pjit", "shard_map", "pallas_call"}
_CONCRETIZERS = {"float", "int", "bool"}


def _traced_args(call: ast.Call) -> List[ast.AST]:
    """The function-valued argument(s) of a trace entry point."""
    out: List[ast.AST] = []
    if call.args:
        out.append(call.args[0])
    for kw in call.keywords:
        if kw.arg in ("f", "fun", "func", "kernel"):
            out.append(kw.value)
    return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _scan_traced(
    fn: ast.AST, fn_label: str, path: str, findings: List[Finding]
) -> None:
    params = _param_names(fn)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is not None:
                root2 = ".".join(d.split(".")[:2])
                if (
                    d.startswith(("time.", "random."))
                    or root2 in ("np.random", "numpy.random")
                ):
                    findings.append(
                        Finding(
                            rule=JitHygieneRule.id,
                            path=path,
                            line=node.lineno,
                            message=(
                                f"host call {d}() inside traced function "
                                f"{fn_label} — it executes once at trace "
                                "time; pass the value in as an argument"
                            ),
                            key=f"{fn_label}:{d}",
                        )
                    )
            if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
                findings.append(
                    Finding(
                        rule=JitHygieneRule.id,
                        path=path,
                        line=node.lineno,
                        message=(
                            f".item() inside traced function {fn_label} — "
                            "concretizing a tracer forces a device sync or "
                            "a TracerError"
                        ),
                        key=f"{fn_label}:item",
                    )
                )
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _CONCRETIZERS
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in params
            ):
                findings.append(
                    Finding(
                        rule=JitHygieneRule.id,
                        path=path,
                        line=node.lineno,
                        message=(
                            f"{node.func.id}({node.args[0].id}) concretizes a "
                            f"traced parameter of {fn_label} — use jnp casts "
                            "or mark the argument static"
                        ),
                        key=f"{fn_label}:{node.func.id}:{node.args[0].id}",
                    )
                )


def _is_trace_decorated(fn: ast.AST) -> bool:
    """``@jax.jit`` / ``@pjit`` / ``@functools.partial(jax.jit, ...)`` —
    the repo's dominant jit idiom (the ops/ kernel wrappers) traces the
    decorated function exactly like the call form does."""
    for dec in getattr(fn, "decorator_list", []):
        d = dotted_name(dec)
        if d is not None and d.split(".")[-1] in _TRACE_ENTRYPOINTS:
            return True
        if isinstance(dec, ast.Call):
            dd = dotted_name(dec.func)
            if dd is None:
                continue
            last = dd.split(".")[-1]
            if last in _TRACE_ENTRYPOINTS:
                return True
            if last == "partial" and dec.args:
                a0 = dotted_name(dec.args[0])
                if a0 is not None and a0.split(".")[-1] in _TRACE_ENTRYPOINTS:
                    return True
    return False


class JitHygieneRule:
    id = "JIT-HYGIENE"

    def run(self, repo: Repo) -> Iterable[Finding]:
        for sf in repo.scan_files:
            if sf.tree is None:
                continue
            index = ScopedDefIndex(sf.tree)
            findings: List[Finding] = []
            seen: Set[int] = set()  # id() of scanned fn nodes — scan once
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if _is_trace_decorated(node) and id(node) not in seen:
                        seen.add(id(node))
                        _scan_traced(
                            node, index.qualname(node), sf.path, findings
                        )
                    continue
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d is None or d.split(".")[-1] not in _TRACE_ENTRYPOINTS:
                    continue
                for arg in _traced_args(node):
                    targets: List[ast.AST] = []
                    if isinstance(arg, ast.Lambda):
                        targets = [arg]
                    elif isinstance(arg, ast.Name):
                        targets = index.resolve(node, arg.id)
                    for fn in targets:
                        if id(fn) in seen:
                            continue
                        seen.add(id(fn))
                        # qualified label: two same-named defs in one file
                        # must not share (and so dedupe/mask) fingerprints
                        _scan_traced(
                            fn, index.qualname(fn), sf.path, findings
                        )
            yield from findings
