"""Rule registry: one module per rule, ids match docs/STATIC_ANALYSIS.md."""

from scripts.ragcheck.rules.lock_discipline import LockDisciplineRule
from scripts.ragcheck.rules.jit_hygiene import JitHygieneRule
from scripts.ragcheck.rules.sharding_contract import ShardingContractRule
from scripts.ragcheck.rules.config_drift import ConfigDriftRule
from scripts.ragcheck.rules.fault_sites import FaultSiteRegistryRule
from scripts.ragcheck.rules.metric_drift import MetricDriftRule
from scripts.ragcheck.rules.event_registry import EventRegistryRule
from scripts.ragcheck.rules.debug_gate import DebugGateRule
from scripts.ragcheck.rules.sim_purity import SimPurityRule
from scripts.ragcheck.rules.durable_write import DurableWriteRule

ALL_RULES = [
    LockDisciplineRule,
    JitHygieneRule,
    ShardingContractRule,
    ConfigDriftRule,
    FaultSiteRegistryRule,
    MetricDriftRule,
    EventRegistryRule,
    DebugGateRule,
    SimPurityRule,
    DurableWriteRule,
]

__all__ = ["ALL_RULES"]
