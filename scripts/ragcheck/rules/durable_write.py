"""DURABLE-WRITE: crash-consistent persistence goes through durable_write.

The modules that persist state a NEXT process incarnation reads — the
incident spooler's bundles, the flight WAL's warmth manifest, the drain
coordinator's persist step — must survive SIGKILL at any instruction.
The one discipline that guarantees it is :func:`obs.flight.durable_write`:
write a temp file, flush, fsync, ``os.replace`` over the target, fsync
the directory. A reader then sees the old content or the new content,
never a torn half-file (ISSUE 19; docs/RESILIENCE.md "Crash-safe
lifecycle").

This rule pins the discipline structurally in the writer modules: any
*write-mode* ``open(...)`` (``"w"``/``"x"``) and any bare ``os.replace``
outside the body of ``durable_write`` itself is flagged — a raw write is
exactly the torn-file window the helper exists to close. Append-mode
opens are exempt: the WAL's segment appends are a different durability
design (one fsync'd JSON line per event; a torn TAIL line is detected
and skipped by ``scan_wal``), and rewriting them through a full-file
replace would turn O(1) appends into O(n) rewrites.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from scripts.ragcheck.core import Finding, Repo, dotted_name

PACKAGE = "rag_llm_k8s_tpu"

#: modules that persist cross-incarnation state (spool, WAL, manifests).
#: Extend this tuple when a new module starts writing durable files.
WRITER_MODULES = (
    f"{PACKAGE}/obs/flight.py",
    f"{PACKAGE}/resilience/lifecycle.py",
)

#: the one function allowed to perform the raw tmp-write + os.replace
HELPER = "durable_write"

_WRITE_MODES = ("w", "x")


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The mode string when ``call`` is a write-mode builtin open()."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return None
    mode: Optional[ast.AST] = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not isinstance(mode, ast.Constant) or not isinstance(mode.value, str):
        return None  # no/dynamic mode: a read, or undecidable — not flagged
    if any(c in mode.value for c in _WRITE_MODES):
        return mode.value
    return None


class DurableWriteRule:
    id = "DURABLE-WRITE"

    def run(self, repo: Repo) -> Iterable[Finding]:
        for rel in WRITER_MODULES:
            sf = repo.get(rel)
            if sf is None or sf.tree is None:
                continue
            for call, func_stack in _calls_with_scope(sf.tree):
                enclosing = func_stack[-1] if func_stack else "<module>"
                if HELPER in func_stack:
                    continue  # the helper's own tmp-write + replace
                qual = ".".join(func_stack) or "<module>"
                mode = _open_write_mode(call)
                if mode is not None:
                    yield Finding(
                        rule=self.id, path=sf.path, line=call.lineno,
                        message=(
                            f"raw write-mode open(mode={mode!r}) in "
                            f"{enclosing}() of a durable-state writer "
                            "module — a crash mid-write leaves a torn "
                            f"file; route it through {HELPER}() "
                            "(tmp → fsync → rename)"
                        ),
                        key=f"raw-open:{qual}:{mode}",
                    )
                elif dotted_name(call.func) == "os.replace":
                    yield Finding(
                        rule=self.id, path=sf.path, line=call.lineno,
                        message=(
                            f"bare os.replace in {enclosing}() — a rename "
                            "without the preceding tmp-file fsync (and the "
                            "directory fsync after) is not crash-durable; "
                            f"use {HELPER}()"
                        ),
                        key=f"raw-replace:{qual}",
                    )


def _calls_with_scope(
    tree: ast.AST,
) -> Iterable[Tuple[ast.Call, List[str]]]:
    """Every Call node paired with its enclosing def-name stack (class
    names excluded — the exemption keys on FUNCTION identity)."""
    out: List[Tuple[ast.Call, List[str]]] = []

    def walk(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, stack + [child.name])
            else:
                if isinstance(child, ast.Call):
                    out.append((child, stack))
                walk(child, stack)

    walk(tree, [])
    return out
