"""ragcheck — the repo-native static-analysis suite (``make analyze``).

The serving stack has a handful of correctness disciplines that reviews
kept re-finding as bugs: no blocking device work under the cache lock, no
host calls inside traced functions, ``out_shardings`` pinned on every
executable that round-trips arena/cache state, every ``TPU_RAG_*`` knob
routed through ``core/config.py`` and pinned in deploy.yaml + the RUNBOOK,
a closed fault-site catalog with test coverage, and a metrics surface that
matches its documentation. ragcheck mechanizes those disciplines as
deterministic AST rules so ``make ci`` catches the violation, not the
reviewer three PRs later.

Stdlib-only on purpose: this runs everywhere the tier-1 gate runs.

See docs/STATIC_ANALYSIS.md for the rule catalog, the inline-suppression
syntax (``# ragcheck: disable=RULE-ID``), and the baseline-ratchet
workflow (scripts/ragcheck/baseline.json may only shrink).
"""

from scripts.ragcheck.core import (  # noqa: F401
    Finding,
    Repo,
    gate,
    load_baseline,
    run_analysis,
)

__all__ = ["Finding", "Repo", "gate", "load_baseline", "run_analysis"]
