#!/usr/bin/env python
"""On-chip A/B: single-fetch fused /query vs the host-assembly path at the
8B int8+int8-KV behavioral point (bench.py::make_params_8b_behavioral).

Small-bucket (1024) probe for fast iteration — the full-bucket headline
comes from bench.py. Also sweeps spec_tokens / spec_ngram when --sweep.

Usage: python scripts/ab_fused_8b.py [--sweep] [--queries N]
Prints one JSON object.
"""

import argparse
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def build_service(cfg_8b, params, dtypes, llm_tok, enc_tok, encoder, store,
                  rag_fused=True, spec="auto", spec_tokens=None,
                  spec_ngram=None, bucket=1024):
    """spec_tokens/spec_ngram default to None = the PRODUCTION EngineConfig
    defaults, so the headline A/B always measures what actually serves."""
    from rag_llm_k8s_tpu.core.config import (
        AppConfig, EngineConfig, SamplingConfig,
    )
    from rag_llm_k8s_tpu.engine.batching import BatchScheduler
    from rag_llm_k8s_tpu.engine.engine import InferenceEngine
    from rag_llm_k8s_tpu.server.app import RagService, create_app

    app_cfg = AppConfig(model=cfg_8b, encoder=encoder.config)
    spec_kw = {}
    if spec_tokens is not None:
        spec_kw["spec_tokens"] = spec_tokens
    if spec_ngram is not None:
        spec_kw["spec_ngram"] = spec_ngram
    engine = InferenceEngine(
        cfg_8b, params,
        sampling=SamplingConfig(),
        engine_config=EngineConfig(
            prompt_buckets=(bucket,), max_batch_size=4, weight_quant="int8",
            kv_quant="int8", speculative=spec, rag_fused=rag_fused, **spec_kw,
        ),
        dtypes=dtypes,
    )
    scheduler = BatchScheduler(engine, max_wait_ms=30.0)
    service = RagService(app_cfg, engine, llm_tok, encoder, enc_tok, store,
                         scheduler=scheduler)
    service.warmup()
    return service, create_app(service), engine


def run_leg(app, n):
    client = app.test_client()
    client.post("/query", json={"prompt": bench.QUERIES[0]})  # warm/compile
    lats = []
    for q in bench.QUERIES[:n]:
        t0 = time.monotonic()
        r = client.post("/query", json={"prompt": q})
        lats.append((time.monotonic() - t0) * 1e3)
        assert r.status_code == 200, r.get_data()
    lats.sort()
    return round(lats[len(lats) // 2], 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--queries", type=int, default=6)
    ap.add_argument("--bucket", type=int, default=1024)
    args = ap.parse_args()

    import jax

    from rag_llm_k8s_tpu.core.config import (
        DTypePolicy, EncoderConfig, LlamaConfig,
    )
    from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
    from rag_llm_k8s_tpu.index.store import VectorStore
    from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
    import jax.numpy as jnp

    dtypes = DTypePolicy()
    enc_cfg = EncoderConfig.bge_m3()
    encoder = EncoderRunner(
        enc_cfg,
        jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda: init_encoder_params(jax.random.PRNGKey(1), enc_cfg, dtypes)),
        ),
        dtypes=dtypes, length_buckets=(128, 1536), max_batch=8,
    )
    llm_tok, enc_tok = bench._real_tokenizers()
    cfg_8b = LlamaConfig.llama_3_1_8b()
    params, alpha, top1 = bench.make_params_8b_behavioral(cfg_8b, dtypes, llm_tok)

    out = {"alpha": alpha, "top1": top1, "bucket": args.bucket,
           "tunnel_ms": round(bench.measure_tunnel_fetch_ms(), 1)}

    def fresh_store():
        s = VectorStore(dim=enc_cfg.embed_dim)
        return s

    def leg(tag, **kw):
        s = fresh_store()
        svc, app, engine = build_service(
            cfg_8b, params, dtypes, llm_tok, enc_tok, encoder, s, **kw
        )
        try:
            pdf = bench._synthetic_pdf(2500)
            r = app.test_client().post(
                "/upload_pdf", data={"file": (io.BytesIO(pdf), "c.pdf")},
                content_type="multipart/form-data",
            )
            assert r.status_code == 200, r.get_data()
            p50 = run_leg(app, args.queries)
            snap = svc.metrics.snapshot()
            v = engine.stats.spec_verify_steps
            out[tag] = {
                "p50_ms": p50,
                "single_fetch": snap.get("query_single_fetch", 0),
                "tokens_per_verify": round(
                    engine.stats.spec_emitted_tokens / v, 2) if v else None,
            }
            print(f"[{tag}] {out[tag]}", file=sys.stderr)
        finally:
            svc.shutdown()

    leg("fused", rag_fused=True)
    leg("host", rag_fused=False)
    if args.sweep:
        for k in (7, 11, 15, 19, 23, 31):
            leg(f"fused_k{k}", rag_fused=True, spec_tokens=k)
        leg("fused_n3", rag_fused=True, spec_ngram=3)
        leg("fused_nospec", rag_fused=True, spec="off")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
