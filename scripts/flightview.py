"""flightview — offline renderer for flight-recorder bundles and journals.

Turns an incident bundle (``GET /debug/incidents?id=...``, or a file
copied off a pod's spool directory) — or a bare journal dump — into:

- **per-request lifecycle timelines**: each request's ordered event chain
  (admit → sync windows → eos/preempt/evict/resubmit → complete) with
  inter-event deltas, as ASCII or JSON;
- **a scheduler-occupancy summary**: windows observed, active-row
  distribution, rows completed, resets/preemptions/sheds in the window
  the journal covers;
- **the goodput report** (``--goodput``): per-category chip-time split,
  rolling MFU/roofline per executable kind, and cost-per-query
  percentiles, rebuilt from the journal's ``goodput_window``/``complete``
  events by the SAME renderer ``GET /debug/goodput`` uses live
  (rag_llm_k8s_tpu/obs/goodput.py, loaded by file path so no jax is
  pulled in) — the two reports cannot drift apart;
- **the shadow quality report** (``--quality``): audit outcomes,
  divergence rate, logit-err/first-divergence distributions and
  per-approximation attribution, rebuilt from the journal's
  ``shadow_audit`` events by the SAME renderer ``GET /debug/quality``
  uses live (rag_llm_k8s_tpu/obs/shadow.py, same jax-free contract);
- **the tenant attribution report** (``--tenants``): per-tenant
  arrivals/completions/sheds/tokens/chip-seconds/cost and shadow-audit
  divergence, rebuilt from the journal's tenant-stamped lifecycle events
  by the SAME renderer ``GET /debug/tenants`` uses live
  (rag_llm_k8s_tpu/obs/tenants.py, same jax-free contract);
- **the replay diff** (``--replay-diff OTHER``): event-by-event
  comparison of two journals' scheduler decision streams — the first
  divergent decision, per-event-type count deltas, occupancy deltas —
  via rag_llm_k8s_tpu/sim/replay.py (same jax-free contract). This is
  how a ``make replay-smoke`` failure or a live-vs-simulated run is
  triaged (docs/REPLAY.md);
- **the restore report** (``--restore-report``): the warm-restart
  post-mortem over a flight-WAL directory copied off the pod's PVC —
  per epoch (one per process incarnation), what died in flight and what
  the next incarnation's restore pass resumed, rehydrated, or skipped
  (sim/replay.py ``build_restore_report``, same jax-free contract;
  docs/RESILIENCE.md "Crash-safe lifecycle").

No live pod, no jax, no third-party deps — a bundle is self-contained by
contract (docs/OBSERVABILITY.md "Engine flight recorder").

Usage:
    python scripts/flightview.py BUNDLE.json            # ASCII render
    python scripts/flightview.py BUNDLE.json --json     # structured form
    python scripts/flightview.py BUNDLE.json --request 7
    python scripts/flightview.py BUNDLE.json --goodput [--chip-hour-usd X]
    python scripts/flightview.py BUNDLE.json --quality
    python scripts/flightview.py BUNDLE.json --tenants [--chip-hour-usd X]
    python scripts/flightview.py RECORDED.json --replay-diff REPLAYED.json
    python scripts/flightview.py WAL_DIR/ --restore-report

Input shapes accepted: a full incident bundle (``{"journal": [...],
"trigger": ..., ...}``), a journal-only dump (``{"journal": [...]}``), or
a plain JSON list of events. Events newer than this tool's known
``schema_version`` are refused loudly rather than misread.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Dict, List, Optional

# keep in sync with rag_llm_k8s_tpu/obs/flight.py — flightview must run
# standalone on a laptop holding nothing but the bundle file, so the
# constant is duplicated here ON PURPOSE (the round-trip smoke in
# tests/test_flight.py fails if the two drift apart)
SCHEMA_VERSION = 1


def load_events(doc) -> List[Dict]:
    """Extract the event list from any accepted input shape."""
    if isinstance(doc, list):
        events = doc
    elif isinstance(doc, dict):
        ver = doc.get("schema_version", SCHEMA_VERSION)
        if int(ver) > SCHEMA_VERSION:
            raise SystemExit(
                f"flightview: bundle schema_version {ver} is newer than "
                f"this tool understands ({SCHEMA_VERSION}) — update the repo"
            )
        events = doc.get("journal", [])
    else:
        raise SystemExit("flightview: unrecognized input shape")
    return sorted(events, key=lambda e: e.get("seq", 0))


def _attrs(e: Dict) -> Dict:
    return {
        k: v for k, v in e.items() if k not in ("seq", "t", "type", "rid")
    }


def build_view(events: List[Dict],
               request_id: Optional[int] = None) -> Dict:
    """The structured form: per-request timelines + occupancy summary."""
    requests: Dict[int, List[Dict]] = {}
    t0 = events[0]["t"] if events else 0.0
    for e in events:
        rid = e.get("rid")
        if rid is None or (request_id is not None and rid != request_id):
            continue
        requests.setdefault(int(rid), []).append(e)

    timelines = {}
    for rid, evs in sorted(requests.items()):
        base = evs[0]["t"]
        prev = base
        rows = []
        for e in evs:
            rows.append({
                "seq": e.get("seq"),
                "type": e["type"],
                "t_ms": round((e["t"] - base) * 1e3, 3),
                "dt_ms": round((e["t"] - prev) * 1e3, 3),
                "attrs": _attrs(e),
            })
            prev = e["t"]
        types = [r["type"] for r in rows]
        timelines[str(rid)] = {
            "events": rows,
            "complete": "complete" in types,
            # only real reset recoveries: a preempt_resume is scheduled
            # backpressure (no reset happened) and a gave_up is the one
            # case the client did NOT survive
            "resets_survived": sum(
                1 for r in rows
                if r["type"] == "resubmit"
                and r["attrs"].get("outcome") == "resubmitted"
            ),
            "span_ms": round((evs[-1]["t"] - base) * 1e3, 3),
        }

    windows = [e for e in events if e["type"] == "sync_window_open"]
    active = [int(e.get("active", 0)) for e in windows]
    closes = [e for e in events if e["type"] == "sync_window_close"]
    # unified ragged sync windows (ISSUE 16): every mixed window journals
    # one window_budget (the planner's decode/prefill token split) and one
    # prefill_chunk_sched per chunk it granted
    budgets = [e for e in events if e["type"] == "window_budget"]
    chunks = [e for e in events if e["type"] == "prefill_chunk_sched"]
    occupancy = {
        "windows": len(windows),
        "active_mean": round(sum(active) / len(active), 2) if active else 0.0,
        "active_max": max(active) if active else 0,
        "mixed_windows": len(budgets),
        "prefill_chunks": len(chunks),
        "prefill_chunk_tokens": sum(int(e.get("tokens", 0)) for e in chunks),
        "rows_done": sum(int(e.get("done", 0)) for e in closes),
        "resets": sum(1 for e in events if e["type"] == "reset"),
        "preemptions": sum(1 for e in events if e["type"] == "preempt"),
        "sheds": sum(1 for e in events if e["type"] == "shed"),
        "deadline_expiries": sum(
            1 for e in events if e["type"] == "deadline"
        ),
        "journal_span_ms": round(
            (events[-1]["t"] - t0) * 1e3, 3
        ) if events else 0.0,
    }
    return {
        "schema_version": SCHEMA_VERSION,
        "requests": timelines,
        "occupancy": occupancy,
    }


def render_ascii(view: Dict, meta: Optional[Dict] = None) -> str:
    lines: List[str] = []
    if meta:
        lines.append(
            f"incident {meta.get('id', '?')}  trigger={meta.get('trigger')}"
            f"  ts={meta.get('ts')}"
        )
        lines.append("")
    for rid, tl in view["requests"].items():
        status = "complete" if tl["complete"] else "INCOMPLETE"
        lines.append(
            f"request {rid}  [{status}  span={tl['span_ms']:.1f}ms"
            f"  resets_survived={tl['resets_survived']}]"
        )
        for r in tl["events"]:
            attrs = " ".join(f"{k}={v}" for k, v in r["attrs"].items())
            lines.append(
                f"  +{r['t_ms']:>10.3f}ms  (Δ{r['dt_ms']:>9.3f})  "
                f"{r['type']:<18} {attrs}"
            )
        lines.append("")
    occ = view["occupancy"]
    lines.append("scheduler occupancy")
    lines.append(
        f"  windows={occ['windows']}  active mean={occ['active_mean']}"
        f" max={occ['active_max']}  rows done={occ['rows_done']}"
    )
    if occ.get("mixed_windows"):
        lines.append(
            f"  mixed windows={occ['mixed_windows']}  prefill chunks="
            f"{occ['prefill_chunks']}  chunk tokens="
            f"{occ['prefill_chunk_tokens']}"
        )
    lines.append(
        f"  resets={occ['resets']}  preemptions={occ['preemptions']}"
        f"  sheds={occ['sheds']}  deadline expiries="
        f"{occ['deadline_expiries']}  journal span="
        f"{occ['journal_span_ms']:.1f}ms"
    )
    return "\n".join(lines)


def _load_obs_module(name: str):
    """Load an obs/ module DIRECTLY by file path: importing the package
    would execute ``rag_llm_k8s_tpu.obs.__init__`` (which pulls tracing →
    jax), and flightview must run on a laptop holding nothing but the
    bundle. goodput.py and shadow.py are stdlib-only by contract."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir,
        "rag_llm_k8s_tpu", "obs", f"{name}.py",
    )
    spec = importlib.util.spec_from_file_location(f"_flightview_{name}", path)
    if spec is None or spec.loader is None:
        raise SystemExit(f"flightview: cannot load {name} module at {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_goodput_module():
    return _load_obs_module("goodput")


def build_goodput_report(events: List[Dict],
                         chip_hour_usd: float = 0.0) -> Dict:
    """The offline half of the same-report contract: rebuild the ledger
    state from ``goodput_window``/``complete`` events and render with the
    exact function ``GET /debug/goodput`` uses live."""
    gp = _load_goodput_module()
    return gp.render_report(
        gp.state_from_events(events), chip_hour_usd=chip_hour_usd
    )


def _load_sim_module(name: str):
    """Load a sim/ module by file path — same laptop contract as
    ``_load_obs_module`` (the modules are stdlib-only by SIM-PURITY and
    load their own siblings by path)."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir,
        "rag_llm_k8s_tpu", "sim", f"{name}.py",
    )
    spec = importlib.util.spec_from_file_location(f"_flightview_{name}", path)
    if spec is None or spec.loader is None:
        raise SystemExit(f"flightview: cannot load {name} module at {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def build_replay_diff(events_a: List[Dict], events_b: List[Dict]) -> Dict:
    """Decision-stream comparison of two journals (recorded vs replayed
    or simulated) — sim/replay.py's ``diff_journals`` payload."""
    rp = _load_sim_module("replay")
    return rp.diff_journals(events_a, events_b)


def render_replay_diff_ascii(diff: Dict, name_a: str, name_b: str) -> str:
    lines = [
        "replay diff  (A = recorded reference, B = replay/simulation)",
        f"  A: {name_a}",
        f"  B: {name_b}",
        f"  decision streams: identical={diff['identical']}"
        f"  ({diff['decisions'][0]} vs {diff['decisions'][1]} decisions)",
    ]
    fd = diff.get("first_divergence")
    if fd is not None:
        lines.append(f"  first divergent decision (index {fd['index']}):")
        lines.append(f"    A: {json.dumps(fd['a'], sort_keys=True)}")
        lines.append(f"    B: {json.dumps(fd['b'], sort_keys=True)}")
    deltas = {
        t: v for t, v in diff["event_counts"].items() if v["delta"] != 0
    }
    lines.append("  event counts (A / B / delta):")
    for t, v in diff["event_counts"].items():
        mark = "  <-- " if v["delta"] else ""
        lines.append(
            f"    {t:<20} {v['a']:>6} {v['b']:>6} {v['delta']:>+5}{mark}"
        )
    if not deltas:
        lines.append("    (no count deltas)")
    occ = diff["occupancy"]
    lines.append(
        f"  occupancy: windows {occ['a']['windows']} vs "
        f"{occ['b']['windows']};  mean active rows "
        f"{occ['a']['mean_active']} vs {occ['b']['mean_active']} "
        f"(delta {occ['mean_active_delta']:+})"
    )
    rd = diff["requests_diverged"]
    if rd:
        head = ", ".join(str(r) for r in rd[:16])
        more = f" (+{len(rd) - 16} more)" if len(rd) > 16 else ""
        lines.append(f"  requests whose decision chains diverge: {head}{more}")
    else:
        lines.append("  per-request decision chains: all identical")
    return "\n".join(lines)


def build_restore_report(path: str) -> Dict:
    """The warm-restart post-mortem (``--restore-report``): per WAL epoch,
    what that incarnation did, what it left in flight at death, and what
    the next incarnation's restore pass did about it (resumed /
    rehydrated / skipped) — sim/replay.py's ``build_restore_report`` over
    ``obs/flight.py``'s ``scan_wal``. ``path`` may be a WAL *directory*
    (the usual case: copied off the pod's PVC) or a single journal/bundle
    file (rendered as one epoch)."""
    rp = _load_sim_module("replay")
    if os.path.isdir(path):
        fl = _load_obs_module("flight")
        epochs = fl.scan_wal(path)
        if not epochs:
            raise SystemExit(
                f"flightview: no WAL segments (wal_*.jsonl) under {path}"
            )
    else:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise SystemExit(f"flightview: cannot read {path}: {e}")
        epochs = {0: load_events(doc)}
    return rp.build_restore_report(epochs)


def render_restore_ascii(report: Dict) -> str:
    lines = ["restore report  (one section per WAL epoch = one "
             "process incarnation)"]
    for ep in report["epochs"]:
        lines.append("")
        lines.append(
            f"epoch {ep['epoch']}  events={ep['events']}"
            f"  arrivals={ep['arrivals']}  completes={ep['completes']}"
        )
        for d in ep["drain"]:
            attrs = " ".join(
                f"{k}={v}" for k, v in d.items() if k != "phase"
            )
            lines.append(f"  drain {d.get('phase'):<9} {attrs}")
        inflight = ep["inflight_at_end"]
        if inflight:
            lines.append(
                f"  in flight at death ({len(inflight)}):"
            )
            for r in inflight:
                syn = "  [synthetic prompt]" if r["synthetic_prompt"] else ""
                lines.append(
                    f"    rid={r['rid']:<6} prompt_len={r['prompt_len']:<6}"
                    f" emitted={r['n_emitted']}{syn}"
                )
        else:
            lines.append("  in flight at death: none (clean exit)")
        if ep["restored"]:
            lines.append(f"  resumed here ({len(ep['restored'])}):")
            for r in ep["restored"]:
                # the restore event precedes the resumed submit, so the
                # NEW rid may be unknown (None) — the original identity
                # is the meaningful one
                lines.append(
                    f"    epoch {r['orig_epoch']} rid={r['orig_rid']}"
                    f"  folded {r['n_emitted']} tokens"
                )
        if ep["rehydrated"]:
            toks = sum(r["tokens"] for r in ep["rehydrated"])
            lines.append(
                f"  cache rehydrated: {len(ep['rehydrated'])} segments,"
                f" {toks} tokens pre-staged"
            )
        if ep["skipped"]:
            lines.append(f"  skipped ({len(ep['skipped'])}):")
            for r in ep["skipped"]:
                lines.append(
                    f"    orig_rid={r['orig_rid']}  reason={r['reason']}"
                )
    return "\n".join(lines)


def build_quality_report(events: List[Dict]) -> Dict:
    """The offline half of the quality same-report contract: rebuild the
    auditor state from ``shadow_audit`` events and render with the exact
    function ``GET /debug/quality`` uses live (obs/shadow.py)."""
    sh = _load_obs_module("shadow")
    return sh.render_report(sh.state_from_events(events))


def build_tenant_report(events: List[Dict],
                        chip_hour_usd: float = 0.0) -> Dict:
    """The offline half of the tenant same-report contract: fold the
    journal's arrival/admit/complete/shed/shadow_audit events through the
    exact renderer ``GET /debug/tenants`` serves live (obs/tenants.py,
    stdlib-only by contract) — the two reports are byte-identical over
    the same events."""
    tn = _load_obs_module("tenants")
    return tn.render_report(
        tn.state_from_events(events), chip_hour_usd=chip_hour_usd
    )


def render_tenant_ascii(report: Dict) -> str:
    tot = report["totals"]
    lines = [
        "tenant attribution report",
        f"  events={report['events']}  wall={report['wall_s']:.3f}s"
        f"  tenants={tot['tenants']}",
        f"  totals: arrivals={tot['arrivals']}  admitted={tot['admitted']}"
        f"  completed={tot['completed']}  sheds={tot['sheds']}"
        f"  tokens={tot['tokens']}  chip_s={tot['chip_s']:.4f}"
        f"  cost_usd={tot['cost_usd']:.6f}",
        "  per tenant (sorted by chip-seconds):",
    ]
    for row in report["tenants"]:
        lines.append(
            f"    {row['tenant']:<16} arr={row['arrivals']:<5}"
            f" done={row['completed']:<5} shed={row['sheds']:<4}"
            f" tokens={row['tokens']:<7} chip_s={row['chip_s']:<10.4f}"
            f" share={row['chip_share']:.4f}"
            f" cost={row['cost_usd']:.6f}"
            f" tok/chip_s={row['tokens_per_chip_s']}"
        )
        if row["audits"]:
            lines.append(
                f"      audits={row['audits']}  diverged={row['diverged']}"
            )
    return "\n".join(lines)


def _pct(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[idx]


def build_router_report(events: List[Dict]) -> Dict:
    """The router's offline scorecard, rebuilt from ``route_decision`` /
    ``migrate_begin`` / ``migrate_done`` events: did affinity routing
    actually hit (fraction of decisions whose chosen replica already held
    some of the request's chunks), how the disagg/unified split landed
    per replica, and what migration cost (export and import device time
    from the events' own duration stamps; end-to-end hand-off latency
    from the begin→done timestamp pair per request)."""
    decisions = 0
    modes: Dict[str, int] = {}
    hits = 0
    affinities: List[float] = []
    per_replica: Dict[str, Dict[str, int]] = {}
    export_ms: List[float] = []
    import_ms: List[float] = []
    begin_t: Dict[int, float] = {}
    e2e_ms: List[float] = []
    migrated_blocks = 0
    for e in events:
        et = e.get("type")
        a = _attrs(e)
        if et == "route_decision":
            decisions += 1
            modes[a.get("mode", "?")] = modes.get(a.get("mode", "?"), 0) + 1
            if a.get("affinity_hit"):
                hits += 1
            affinities.append(float(a.get("affinity", 0.0)))
            for role_key in ("prefill", "decode"):
                name = a.get(role_key)
                if name:
                    pr = per_replica.setdefault(
                        name, {"prefill": 0, "decode": 0}
                    )
                    pr[role_key] += 1
        elif et == "migrate_begin":
            if "duration_ms" in a:
                export_ms.append(float(a["duration_ms"]))
            migrated_blocks += int(a.get("blocks", 0))
            if e.get("rid") is not None and e.get("t") is not None:
                begin_t[e["rid"]] = float(e["t"])
        elif et == "migrate_done":
            if "duration_ms" in a:
                import_ms.append(float(a["duration_ms"]))
            t0 = begin_t.pop(e.get("rid"), None)
            if t0 is not None and e.get("t") is not None:
                e2e_ms.append((float(e["t"]) - t0) * 1e3)
    return {
        "decisions": decisions,
        "modes": modes,
        "affinity": {
            "hit_rate": round(hits / decisions, 6) if decisions else 0.0,
            "mean": round(sum(affinities) / len(affinities), 6)
            if affinities else 0.0,
            "p50": round(_pct(affinities, 0.50), 6),
        },
        "per_replica": per_replica,
        "migrations": {
            "begun": len(export_ms),
            "completed": len(import_ms),
            # an unmatched begin is a hand-off that died mid-flight (the
            # chaos path: decode import reset, request re-prefilled)
            "unmatched": len(begin_t),
            "blocks_moved": migrated_blocks,
            "export_ms": {"p50": round(_pct(export_ms, 0.50), 3),
                          "p95": round(_pct(export_ms, 0.95), 3)},
            "import_ms": {"p50": round(_pct(import_ms, 0.50), 3),
                          "p95": round(_pct(import_ms, 0.95), 3)},
            "handoff_ms": {"p50": round(_pct(e2e_ms, 0.50), 3),
                           "p95": round(_pct(e2e_ms, 0.95), 3)},
        },
    }


def render_router_ascii(report: Dict) -> str:
    aff = report["affinity"]
    mig = report["migrations"]
    lines = [
        "router report",
        f"  decisions={report['decisions']}  modes=" + "  ".join(
            f"{k}={v}" for k, v in sorted(report["modes"].items())
        ),
        f"  affinity: hit_rate={aff['hit_rate']:.4f}"
        f"  mean={aff['mean']:.4f}  p50={aff['p50']:.4f}",
        "  per replica (times chosen):",
    ]
    for name, v in sorted(report["per_replica"].items()):
        lines.append(
            f"    {name:<20} prefill={v['prefill']:<6}"
            f" decode={v['decode']}"
        )
    lines.append(
        f"  migrations: begun={mig['begun']}  completed={mig['completed']}"
        f"  unmatched={mig['unmatched']}  blocks={mig['blocks_moved']}"
    )
    lines.append(
        f"    export_ms  p50={mig['export_ms']['p50']}"
        f"  p95={mig['export_ms']['p95']}"
    )
    lines.append(
        f"    import_ms  p50={mig['import_ms']['p50']}"
        f"  p95={mig['import_ms']['p95']}"
    )
    lines.append(
        f"    handoff_ms p50={mig['handoff_ms']['p50']}"
        f"  p95={mig['handoff_ms']['p95']}"
    )
    return "\n".join(lines)


def render_quality_ascii(report: Dict) -> str:
    a = report["audits"]
    lines = [
        "shadow quality report",
        f"  audits: clean={a['clean']}  diverged={a['diverged']}"
        f"  skipped={a['skipped']}  failed={a['failed']}"
        f"  divergence_rate={report['divergence_rate']:.6f}",
        f"  tokens compared: {report['tokens_compared']}",
    ]
    if report["skips"]:
        lines.append("  skips: " + "  ".join(
            f"{k}={v}" for k, v in sorted(report["skips"].items())
        ))
    lines.append("  attribution (audits per active approximation):")
    for approx, v in report["attribution"].items():
        lines.append(
            f"    {approx:<16} clean={v['clean']:<6} diverged={v['diverged']}"
        )
    le = report["logit_err"]
    lines.append(
        f"  logit_err: p50={le['p50']}  p99={le['p99']}  max={le['max']}"
    )
    fd = report["first_divergence_token"]
    lines.append(f"  first divergence token: p50={fd['p50']}")
    lines.append("  logit_err histogram:")
    for lbl, n in le["hist"].items():
        if n:
            lines.append(f"    {lbl:<10} {n}")
    return "\n".join(lines)


def render_goodput_ascii(report: Dict) -> str:
    lines = [
        "goodput report",
        f"  wall={report['wall_s']:.3f}s  busy={report['busy_s']:.3f}s"
        f"  idle={report['idle_s']:.3f}s  busy_frac={report['busy_frac']:.3f}",
        "  chip-time attribution (frac of busy; idle of wall):",
    ]
    for cat, v in report["categories"].items():
        lines.append(
            f"    {cat:<16} {v['chip_s']:>10.4f}s  frac={v['frac']:.4f}"
        )
    lines.append("  executables (roofline):")
    for kind, v in report["kinds"].items():
        lines.append(
            f"    {kind:<11} windows={v['windows']:<5} busy={v['busy_s']:.4f}s"
            f"  tokens={v['tokens']:<7} mfu={v['mfu']:.5f}"
            f"  bw={v['bw_util']:.5f}  bound={v['bound']}"
        )
    cost = report["cost"]
    pq = cost["per_query_chip_ms"]
    lines.append(
        f"  cost: chip_hour_usd={cost['chip_hour_usd']}"
        f"  wall_usd={cost['wall_usd']}"
        f"  tokens_per_usd={cost['tokens_per_usd']}"
    )
    lines.append(
        f"  per-query chip_ms: p50={pq['p50']}  p95={pq['p95']}  n={pq['n']}"
    )
    if "per_query_usd" in cost:
        pu = cost["per_query_usd"]
        lines.append(
            f"  per-query usd:     p50={pu['p50']}  p95={pu['p95']}"
        )
    cons = report["conservation"]
    lines.append(
        f"  conservation: attributed={cons['attributed_s']:.4f}s"
        f"  busy={cons['busy_s']:.4f}s  ratio={cons['ratio']:.4f}"
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bundle", help="incident bundle / journal dump (JSON)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the structured view instead of ASCII")
    ap.add_argument("--request", type=int, default=None,
                    help="render only this request id's lifecycle")
    ap.add_argument("--goodput", action="store_true",
                    help="render the goodput/cost report rebuilt from the "
                         "journal's goodput_window events instead of the "
                         "lifecycle view")
    ap.add_argument("--quality", action="store_true",
                    help="render the shadow-audit quality report rebuilt "
                         "from the journal's shadow_audit events instead "
                         "of the lifecycle view")
    ap.add_argument("--tenants", action="store_true",
                    help="render the per-tenant attribution report rebuilt "
                         "from the journal's arrival/complete/shed/"
                         "shadow_audit events instead of the lifecycle view")
    ap.add_argument("--router", action="store_true",
                    help="render the disaggregation router scorecard "
                         "rebuilt from the journal's route_decision/"
                         "migrate_begin/migrate_done events: affinity "
                         "hit rate, per-replica routing split, migration "
                         "latency percentiles")
    ap.add_argument("--chip-hour-usd", type=float, default=0.0,
                    help="chip rental price for the --goodput/--tenants "
                         "cost figures (defaults to 0: attribution only, "
                         "no dollars)")
    ap.add_argument("--replay-diff", metavar="OTHER", default=None,
                    help="compare BUNDLE's scheduler decision stream "
                         "against OTHER's (a replayed or simulated "
                         "journal): first divergence, per-event-type "
                         "count deltas, occupancy deltas")
    ap.add_argument("--restore-report", action="store_true",
                    help="render the warm-restart post-mortem: per WAL "
                         "epoch, what died in flight and what the next "
                         "incarnation resumed/rehydrated/skipped. BUNDLE "
                         "may be a WAL directory (wal_*.jsonl) or a "
                         "journal file")
    args = ap.parse_args(argv)
    if args.restore_report:
        # dispatched before the generic json.load: the input is usually a
        # WAL *directory*, not a bundle file
        report = build_restore_report(args.bundle)
        if args.as_json:
            print(json.dumps(report, indent=1))
        else:
            print(render_restore_ascii(report))
        return 0
    try:
        with open(args.bundle) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"flightview: cannot read {args.bundle}: {e}", file=sys.stderr)
        return 2
    events = load_events(doc)
    if args.replay_diff is not None:
        try:
            with open(args.replay_diff) as f:
                doc_b = json.load(f)
        except (OSError, ValueError) as e:
            print(f"flightview: cannot read {args.replay_diff}: {e}",
                  file=sys.stderr)
            return 2
        diff = build_replay_diff(events, load_events(doc_b))
        if args.as_json:
            print(json.dumps(diff, indent=1))
        else:
            print(render_replay_diff_ascii(
                diff, args.bundle, args.replay_diff
            ))
        return 0 if diff["identical"] else 1
    if args.router:
        report = build_router_report(events)
        if args.as_json:
            print(json.dumps(report, indent=1))
        else:
            print(render_router_ascii(report))
        return 0
    if args.quality:
        report = build_quality_report(events)
        if args.as_json:
            print(json.dumps(report, indent=1))
        else:
            print(render_quality_ascii(report))
        return 0
    if args.tenants:
        report = build_tenant_report(
            events, chip_hour_usd=args.chip_hour_usd
        )
        if args.as_json:
            print(json.dumps(report, indent=1))
        else:
            print(render_tenant_ascii(report))
        return 0
    if args.goodput:
        report = build_goodput_report(
            events, chip_hour_usd=args.chip_hour_usd
        )
        if args.as_json:
            print(json.dumps(report, indent=1))
        else:
            print(render_goodput_ascii(report))
        return 0
    view = build_view(events, request_id=args.request)
    if args.as_json:
        print(json.dumps(view, indent=1))
    else:
        meta = doc if isinstance(doc, dict) and "trigger" in doc else None
        print(render_ascii(view, meta))
    return 0


if __name__ == "__main__":
    sys.exit(main())
