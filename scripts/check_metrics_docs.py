#!/usr/bin/env python
"""Lint gate: every metric name registered in code is documented.

Scans the package (and bench.py) for metric registrations — registry
``counter/gauge/histogram/labeled_*`` calls and the legacy facade's
``inc``/``observe`` string literals — and fails if any discovered name is
missing from the docs/OBSERVABILITY.md table. Run by ``make lint``.

Zero third-party dependencies on purpose: this must run in any
environment the tier-1 gate runs in.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# registry registrations + the legacy facade's literal counter names
_REGISTER_RE = re.compile(
    r"\.(?:counter|gauge|histogram|labeled_histogram|labeled_counter|"
    r"labeled_gauge)\(\s*"
    r"['\"]([A-Za-z_][A-Za-z0-9_]*)['\"]"
)
_FACADE_RE = re.compile(
    r"\.(?:inc|observe)\(\s*['\"]([A-Za-z_][A-Za-z0-9_]*)['\"]"
)


def scan_sources() -> dict:
    """{metric_name: first 'path:line' registering it}."""
    roots = [os.path.join(REPO, "rag_llm_k8s_tpu"), os.path.join(REPO, "bench.py")]
    found: dict = {}
    files = []
    for root in roots:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, _, names in os.walk(root):
            files.extend(
                os.path.join(dirpath, n) for n in names if n.endswith(".py")
            )
    for path in sorted(files):
        with open(path, encoding="utf-8") as f:
            text = f.read()
        rel = os.path.relpath(path, REPO)
        for rx in (_REGISTER_RE, _FACADE_RE):
            for m in rx.finditer(text):  # \s* spans newlines: multi-line calls
                lineno = text.count("\n", 0, m.start()) + 1
                found.setdefault(m.group(1), f"{rel}:{lineno}")
    return found


def main() -> int:
    if not os.path.exists(DOC):
        print(f"check_metrics_docs: missing {DOC}", file=sys.stderr)
        return 1
    with open(DOC, encoding="utf-8") as f:
        doc = f.read()
    found = scan_sources()
    if not found:
        print("check_metrics_docs: no metric registrations found — "
              "the scanner regexes are broken", file=sys.stderr)
        return 1
    missing = {
        name: site for name, site in sorted(found.items())
        if f"`{name}`" not in doc and name not in doc
    }
    if missing:
        print("check_metrics_docs: metric names registered in code but "
              "absent from docs/OBSERVABILITY.md:", file=sys.stderr)
        for name, site in missing.items():
            print(f"  {name}  (registered at {site})", file=sys.stderr)
        return 1
    print(f"check_metrics_docs: OK ({len(found)} metric names documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
