#!/usr/bin/env python
"""Thin shim: the metrics↔docs gate moved into ragcheck (PR 10).

The source-scanning logic that lived here is now ragcheck's METRIC-DRIFT
rule (scripts/ragcheck/rules/metric_drift.py), which also checks label-set
consistency and label-value cardinality. This shim keeps ``make lint`` and
any scripted invocation of the old path working by running just that rule;
``make analyze`` runs the full suite. Zero third-party dependencies, as
before: this must run in any environment the tier-1 gate runs in.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts.ragcheck.core import gate, load_baseline, run_analysis  # noqa: E402
from scripts.ragcheck.rules.metric_drift import MetricDriftRule  # noqa: E402

BASELINE = os.path.join(REPO, "scripts", "ragcheck", "baseline.json")


def main() -> int:
    # baseline-aware, same as `make analyze`: a justified baselined
    # METRIC-DRIFT entry must not turn `make lint` red inside the same CI
    # run that declared it accepted (stale entries of THIS rule still fail
    # here — the ratchet is rule-agnostic)
    _, findings = run_analysis(REPO, rules=[MetricDriftRule()])
    baseline = load_baseline(BASELINE)
    new, stale = gate(findings, baseline)
    stale = [fp for fp in stale if fp.startswith(f"{MetricDriftRule.id}::")]
    if new or stale:
        print(
            "check_metrics_docs (now ragcheck METRIC-DRIFT) failed:",
            file=sys.stderr,
        )
        for f in new:
            print(f"  {f.render()}", file=sys.stderr)
        for fp in stale:
            print(f"  stale baseline entry: {fp}", file=sys.stderr)
        return 1
    print("check_metrics_docs: OK (ragcheck METRIC-DRIFT clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
