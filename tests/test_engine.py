"""Engine tests: greedy-decode correctness vs a naive full-reforward oracle,
batching invariance, sampling semantics."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import DTypePolicy, EngineConfig, LlamaConfig, SamplingConfig
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.sampling import sample_token, top_p_filter
from rag_llm_k8s_tpu.models.llama import LlamaModel, init_llama_params, make_kv_cache

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
SMALL_ENGINE = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4)


@pytest.fixture(scope="module")
def tiny_engine():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    eng = InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=SMALL_ENGINE, dtypes=FP32
    )
    return cfg, params, eng


def naive_greedy(cfg, params, prompt, n_steps):
    """Oracle: re-run a full forward over the whole sequence for every token."""
    model = LlamaModel(cfg, FP32)
    seq = list(prompt)
    for _ in range(n_steps):
        S = len(seq)
        cache = make_kv_cache(cfg, 1, S, jnp.float32)
        window = jnp.zeros((1,), jnp.int32), jnp.full((1,), S, jnp.int32)
        pos = jnp.arange(S)[None, :]
        logits, _ = model.apply(
            {"params": params}, jnp.asarray([seq], jnp.int32), pos, cache, *window, jnp.int32(0)
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        if nxt in cfg.eos_token_ids:
            break
        seq.append(nxt)
    return seq[len(prompt):]


class TestGreedyDecode:
    def test_matches_full_reforward_oracle(self, tiny_engine):
        cfg, params, eng = tiny_engine
        prompt = [3, 17, 42, 7, 99]
        got = eng.generate([prompt])[0]
        want = naive_greedy(cfg, params, prompt, GREEDY.max_new_tokens)
        assert got == want

    def test_batch_invariance(self, tiny_engine):
        """A prompt's greedy continuation must not depend on its batchmates."""
        cfg, params, eng = tiny_engine
        p1, p2 = [3, 17, 42, 7, 99], [5, 5, 8]
        solo = eng.generate([p1])[0]
        batched = eng.generate([p1, p2])
        assert batched[0] == solo

    def test_different_length_prompts_batch(self, tiny_engine):
        _, _, eng = tiny_engine
        outs = eng.generate([[1, 2, 3], [4] * 10, [7]])
        assert len(outs) == 3
        assert all(len(o) <= GREEDY.max_new_tokens for o in outs)

    def test_executable_reuse(self, tiny_engine):
        _, _, eng = tiny_engine
        n0 = len(eng._compiled)
        eng.generate([[1, 2, 3]])
        n1 = len(eng._compiled)
        eng.generate([[9, 9, 9, 9]])  # same bucket -> same executable
        assert len(eng._compiled) == n1
        assert n1 >= n0

    def test_max_new_tokens_respected(self, tiny_engine):
        _, _, eng = tiny_engine
        outs = eng.generate([[1, 2, 3]], max_new_tokens=3)
        assert len(outs[0]) <= 3


class TestSampling:
    def test_top_p_keeps_nucleus(self):
        # probs ~ [0.6, 0.3, 0.08, 0.02]; top_p=0.7 keeps exactly the first two
        logits = jnp.log(jnp.array([[0.6, 0.3, 0.08, 0.02]]))
        filtered = top_p_filter(logits, 0.7)
        assert filtered[0, 0] > -1e8 and filtered[0, 1] > -1e8
        assert filtered[0, 2] < -1e8 and filtered[0, 3] < -1e8

    def test_top_p_always_keeps_one(self):
        logits = jnp.log(jnp.array([[0.97, 0.01, 0.01, 0.01]]))
        filtered = top_p_filter(logits, 0.0001)
        assert filtered[0, 0] > -1e8
        assert np.sum(np.asarray(filtered[0]) > -1e8) == 1

    def test_greedy_is_argmax(self):
        logits = jnp.array([[0.1, 5.0, 0.2], [9.0, 0.0, 0.1]])
        tok = sample_token(jax.random.PRNGKey(0), logits, SamplingConfig(do_sample=False))
        assert tok.tolist() == [1, 0]

    def test_temperature_sampling_is_seeded_and_plausible(self):
        logits = jnp.array([[0.0, 10.0, 0.0, 0.0]] * 4)
        s = SamplingConfig(temperature=0.7, top_p=0.9)
        t1 = sample_token(jax.random.PRNGKey(1), logits, s)
        t2 = sample_token(jax.random.PRNGKey(1), logits, s)
        assert t1.tolist() == t2.tolist()  # deterministic given seed
        assert t1.tolist() == [1, 1, 1, 1]  # overwhelming mass on token 1

    def test_sampled_tokens_stay_inside_nucleus(self):
        """Contract: sample_token never emits a token the top_p_filter mask
        excludes, across many seeds and both batch rows."""
        r = np.random.default_rng(0)
        logits = jnp.asarray(r.standard_normal((2, 500)) * 3, jnp.float32)
        s = SamplingConfig(temperature=0.7, top_p=0.9)
        exact_kept = np.asarray(top_p_filter(logits / s.temperature, s.top_p)) > -1e8
        for seed in range(50):
            toks = np.asarray(sample_token(jax.random.PRNGKey(seed), logits, s))
            for b in range(2):
                assert exact_kept[b, toks[b]], (seed, b, int(toks[b]))

    def test_wide_flat_nucleus_spreads_draws(self):
        """A uniform distribution keeps ~top_p of the vocab in the nucleus;
        draws must spread across it, not collapse onto a few tokens."""
        V = 4096  # uniform: nucleus at 0.9 is ~3686 tokens
        logits = jnp.zeros((1, V), jnp.float32)
        s = SamplingConfig(temperature=1.0, top_p=0.9)
        toks = [int(sample_token(jax.random.PRNGKey(i), logits, s)[0]) for i in range(20)]
        assert all(0 <= t < V for t in toks)
        assert len(set(toks)) > 10

    def test_eos_truncation(self, tiny_engine):
        """Post-EOS tokens are trimmed host-side; outputs never contain EOS."""
        cfg, _, eng = tiny_engine
        outs = eng.generate([[1, 2], [3]], max_new_tokens=5)
        for o in outs:
            assert all(t not in cfg.eos_token_ids for t in o)


class TestShardedEngine:
    def test_generate_with_tp_sharded_params(self, mesh_tp8):
        """TP-sharded params produce the same greedy tokens as replicated."""
        cfg = dataclasses.replace(
            LlamaConfig.tiny(), num_heads=8, num_kv_heads=8, head_dim=8, hidden_size=64
        )
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        eng_ref = InferenceEngine(
            cfg, params, sampling=GREEDY, engine_config=SMALL_ENGINE, dtypes=FP32
        )
        want = eng_ref.generate([[3, 1, 4, 1, 5]])[0]

        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        sharded = shard_llama_params(params, mesh_tp8)
        eng = InferenceEngine(
            cfg, sharded, sampling=GREEDY, engine_config=SMALL_ENGINE, dtypes=FP32,
            mesh=mesh_tp8,
        )
        got = eng.generate([[3, 1, 4, 1, 5]])[0]
        assert got == want


class TestSubBatchRNG:
    def test_sub_batches_sample_independently(self, tiny_engine):
        """A pinned seed must not make every sequential sub-batch draw the
        same randomness: 8 identical prompts through a cap-4 engine land in
        two sub-batches, whose sampled continuations should differ (the old
        bug replayed one PRNGKey per sub-batch, duplicating outputs)."""
        cfg, params, _ = tiny_engine
        eng = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=True, temperature=1.0, top_p=1.0,
                                    max_new_tokens=8),
            engine_config=SMALL_ENGINE, dtypes=FP32,
        )
        prompts = [[3, 17, 42]] * 8  # cap=4 -> exactly two sub-batches
        outs = eng.generate(prompts, seed=123)
        first, second = outs[:4], outs[4:]
        assert first != second

        # and the pinned seed is still fully reproducible end-to-end
        outs2 = eng.generate(prompts, seed=123)
        assert outs == outs2


class TestChunkedPrefill:
    """Prompts over the largest bucket prefill through the cache in chunks —
    same tokens out as a single-shot engine whose bucket fits the prompt."""

    def test_long_prompt_matches_big_bucket_oracle(self, tiny_engine):
        cfg, params, _ = tiny_engine
        rng = np.random.RandomState(0)
        prompt = rng.randint(2, cfg.vocab_size, 40).tolist()  # > largest bucket 32
        eng = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(prompt_buckets=(16, 32), max_batch_size=4),
            dtypes=FP32,
        )
        got = eng.generate([prompt])[0]
        assert (1, 64, GREEDY.max_new_tokens, 32) in eng._compiled  # chunked exe

        eng_big = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(prompt_buckets=(64,), max_batch_size=4),
            dtypes=FP32,
        )
        want = eng_big.generate([prompt])[0]
        assert got == want and len(got) > 0

    def test_mixed_batch_long_and_short(self, tiny_engine):
        cfg, params, _ = tiny_engine
        rng = np.random.RandomState(1)
        long_p = rng.randint(2, cfg.vocab_size, 50).tolist()
        short_p = [3, 17, 42]
        eng = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(prompt_buckets=(16, 32), max_batch_size=4),
            dtypes=FP32,
        )
        got = eng.generate([long_p, short_p])
        eng_big = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(prompt_buckets=(64,), max_batch_size=4),
            dtypes=FP32,
        )
        want = eng_big.generate([long_p, short_p])
        assert got == want

    def test_over_cap_truncates_loudly(self, tiny_engine, caplog):
        import logging

        cfg, params, _ = tiny_engine
        rng = np.random.RandomState(2)
        prompt = rng.randint(2, cfg.vocab_size, 48).tolist()
        eng = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(16, 32), max_batch_size=4, max_chunked_prompt=32
            ),
            dtypes=FP32,
        )
        with caplog.at_level(logging.WARNING, "rag_llm_k8s_tpu.engine.engine"):
            got = eng.generate([prompt])[0]
        assert any("max_chunked_prompt" in r.message for r in caplog.records)
        # behavior after the loud warning: the most recent cap tokens serve
        want = eng.generate([prompt[-32:]])[0]
        assert got == want

    def test_cap_not_multiple_of_bucket_enforced_exactly(self, tiny_engine):
        """A cap that is not a bucket multiple must truncate to the cap
        itself, not to the rounded-up chunked length."""
        cfg, params, _ = tiny_engine
        rng = np.random.RandomState(3)
        prompt = rng.randint(2, cfg.vocab_size, 50).tolist()
        eng = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(16, 32), max_batch_size=4, max_chunked_prompt=40
            ),
            dtypes=FP32,
        )
        got = eng.generate([prompt])[0]
        want = eng.generate([prompt[-40:]])[0]  # exactly the stated contract
        assert got == want

    def test_chunked_max_new_is_bounded(self, tiny_engine):
        """Adversarial max_new_tokens on the chunked path must clamp to the
        decode budget (max_seq_len - largest bucket), not allocate freely."""
        cfg, params, _ = tiny_engine
        rng = np.random.RandomState(4)
        prompt = rng.randint(2, cfg.vocab_size, 40).tolist()
        eng = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=48
            ),
            dtypes=FP32,
        )
        out = eng.generate([prompt], max_new_tokens=10_000)[0]
        assert len(out) <= 48 - 32  # budget = max_seq_len - largest bucket
        assert all(k[2] <= 16 for k in eng._compiled)  # no runaway executable


class TestFusedProjections:
    def test_fusion_applied_and_greedy_identical(self, tiny_engine):
        """With tp=1 the engine fuses q/k/v and gate/up into single matmuls;
        tokens must be bit-identical to an engine with fusion disabled."""
        cfg, params, eng_fused = tiny_engine  # module engine: fusion on
        attn = eng_fused.params["layers"]["attn"]
        assert "wqkv" in attn and "wq" not in attn  # actually fused

        eng_plain = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(prompt_buckets=(16, 32), max_batch_size=4,
                                       fuse_matmuls=False),
            dtypes=FP32,
        )
        assert "wq" in eng_plain.params["layers"]["attn"]
        prompts = [[3, 17, 42, 7, 99], [5, 5, 8], [11] * 12]
        assert eng_fused.generate(prompts) == eng_plain.generate(prompts)

    def test_tp_mesh_keeps_unfused_layout(self, mesh_tp8):
        import dataclasses

        cfg = dataclasses.replace(
            LlamaConfig.tiny(), num_heads=8, num_kv_heads=8, head_dim=8, hidden_size=64
        )
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        eng = InferenceEngine(
            cfg, shard_llama_params(params, mesh_tp8), sampling=GREEDY,
            engine_config=SMALL_ENGINE, dtypes=FP32, mesh=mesh_tp8,
        )
        assert "wq" in eng.params["layers"]["attn"]  # fused layout can't shard


class TestTopPBisection:
    """The sort-free nucleus filter must keep the same token set as the
    full-sort oracle (modulo boundary ties, which random fp32 logits make
    ~impossible)."""

    def test_matches_sort_oracle(self):
        from rag_llm_k8s_tpu.engine.sampling import top_p_filter, top_p_filter_sort

        key = jax.random.PRNGKey(0)
        for p in (0.1, 0.5, 0.9, 0.99):
            for shape in ((4, 128), (2, 4096), (1, 128256)):
                logits = jax.random.normal(jax.random.fold_in(key, shape[-1]),
                                           shape, jnp.float32) * 3.0
                got = top_p_filter(logits, p) > -1e8
                want = top_p_filter_sort(logits, p) > -1e8
                if bool(jnp.all(got == want)):
                    continue
                # fp32 softmax rounds distinct logits onto equal probs near
                # the nucleus boundary: the two filters may disagree ONLY
                # inside that ulp band, and the kept mass must still reach p
                probs = jax.nn.softmax(logits, axis=-1)
                boundary = jnp.min(
                    jnp.where(want, probs, jnp.inf), axis=-1, keepdims=True
                )
                band = jnp.abs(probs - boundary) <= boundary * 1e-3
                assert bool(jnp.all((got == want) | band)), (p, shape)
                mass = jnp.sum(jnp.where(got, probs, 0.0), axis=-1)
                assert bool(jnp.all(mass >= p - 1e-5)), (p, shape)

    def test_peaked_and_flat_distributions(self):
        from rag_llm_k8s_tpu.engine.sampling import top_p_filter, top_p_filter_sort

        V = 1024
        peaked = jnp.zeros((1, V)).at[0, 7].set(30.0)  # one token has ~all mass
        flat = jnp.zeros((1, V))  # exact ties everywhere: keep-all superset
        for p in (0.5, 0.9):
            got = top_p_filter(peaked, p) > -1e8
            want = top_p_filter_sort(peaked, p) > -1e8
            assert bool(jnp.all(got == want))
            # flat: every token ties at the boundary — bisection keeps all
            # (documented superset); mass kept must still be >= top_p
            kept = top_p_filter(flat, p) > -1e8
            probs = jax.nn.softmax(flat, axis=-1)
            assert float(jnp.sum(jnp.where(kept, probs, 0.0))) >= p
