"""bge-m3 encoder tests: CLS-pooled unit vectors + parity vs HF XLM-R torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import DTypePolicy, EncoderConfig
from rag_llm_k8s_tpu.models.bge_m3 import BgeM3Encoder, init_encoder_params, xlmr_position_ids
from rag_llm_k8s_tpu.models.loader import convert_xlmr_state_dict

FP32 = DTypePolicy.fp32()


class TestEncoder:
    def test_output_is_unit_norm(self):
        cfg = EncoderConfig.tiny()
        params = init_encoder_params(jax.random.PRNGKey(0), cfg, FP32)
        model = BgeM3Encoder(cfg, FP32)
        tokens = jnp.array([[0, 5, 6, 7, 2, 1, 1, 1]], jnp.int32)  # right-padded
        mask = (tokens != cfg.pad_token_id).astype(jnp.int32)
        out = model.apply({"params": params}, tokens, mask)
        assert out.shape == (1, cfg.hidden_size)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=-1), 1.0, rtol=1e-5)

    def test_padding_invariance(self):
        """Extra right-padding must not change the embedding."""
        cfg = EncoderConfig.tiny()
        params = init_encoder_params(jax.random.PRNGKey(0), cfg, FP32)
        model = BgeM3Encoder(cfg, FP32)
        t1 = jnp.array([[0, 5, 6, 2]], jnp.int32)
        t2 = jnp.array([[0, 5, 6, 2, 1, 1, 1, 1]], jnp.int32)
        m1 = (t1 != 1).astype(jnp.int32)
        m2 = (t2 != 1).astype(jnp.int32)
        e1 = model.apply({"params": params}, t1, m1)
        e2 = model.apply({"params": params}, t2, m2)
        np.testing.assert_allclose(np.asarray(e1), np.asarray(e2), rtol=1e-4, atol=1e-5)

    def test_position_ids(self):
        tokens = jnp.array([[0, 5, 6, 1, 1]], jnp.int32)
        pos = xlmr_position_ids(tokens, pad_id=1)
        assert pos.tolist() == [[2, 3, 4, 1, 1]]


class TestXlmrParity:
    def test_tiny_parity_vs_hf(self):
        torch = pytest.importorskip("torch")
        from transformers import XLMRobertaConfig, XLMRobertaModel

        cfg = EncoderConfig.tiny(vocab_size=100)
        hf_cfg = XLMRobertaConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            intermediate_size=cfg.intermediate_size,
            max_position_embeddings=cfg.max_position_embeddings,
            type_vocab_size=cfg.type_vocab_size,
            layer_norm_eps=cfg.layer_norm_eps,
            pad_token_id=cfg.pad_token_id,
            hidden_act="gelu",
        )
        torch.manual_seed(0)
        hf = XLMRobertaModel(hf_cfg, add_pooling_layer=False).eval()

        params = convert_xlmr_state_dict(dict(hf.state_dict()), cfg, FP32)
        model = BgeM3Encoder(cfg, FP32)

        tokens_np = np.array(
            [[0, 10, 11, 12, 13, 2, 1, 1], [0, 20, 21, 2, 1, 1, 1, 1]], np.int64
        )
        mask_np = (tokens_np != cfg.pad_token_id).astype(np.int64)
        with torch.no_grad():
            hf_out = hf(
                input_ids=torch.tensor(tokens_np), attention_mask=torch.tensor(mask_np)
            ).last_hidden_state.numpy()
        hf_cls = hf_out[:, 0, :]
        hf_embed = hf_cls / np.linalg.norm(hf_cls, axis=-1, keepdims=True)

        ours = model.apply(
            {"params": params},
            jnp.asarray(tokens_np, jnp.int32),
            jnp.asarray(mask_np, jnp.int32),
        )
        np.testing.assert_allclose(np.asarray(ours), hf_embed, rtol=1e-3, atol=1e-4)


class TestRunnerClamp:
    def test_bucket_clamp_preserves_eos(self):
        """A sequence over the runner's largest length bucket is clamped to
        the bucket WITH its trailing EOS restored — the clamp must not undo
        the server-level EOS-preserving truncation."""
        from rag_llm_k8s_tpu.engine.encoder import EncoderRunner

        cfg = EncoderConfig.tiny()
        params = init_encoder_params(jax.random.PRNGKey(0), cfg, FP32)
        runner = EncoderRunner(
            cfg, params, dtypes=FP32, length_buckets=(8,), max_batch=2, eos_id=2
        )
        long_ids = [0] + [5] * 20 + [2]  # 22 ids, bucket is 8
        short_ids = [0, 5, 6, 2]
        clamped = runner.encode([long_ids])
        # oracle: what the model gives for the explicitly clamped+EOS sequence
        model = BgeM3Encoder(cfg, FP32)
        want_ids = jnp.array([[0, 5, 5, 5, 5, 5, 5, 2]], jnp.int32)
        want = model.apply({"params": params}, want_ids, jnp.ones_like(want_ids))
        np.testing.assert_allclose(clamped, np.asarray(want), rtol=1e-4, atol=1e-5)
        # short sequences are untouched
        got_short = runner.encode([short_ids])
        want_short_ids = jnp.array([[0, 5, 6, 2, 1, 1, 1, 1]], jnp.int32)
        mask = (want_short_ids != 1).astype(jnp.int32)
        want_short = model.apply({"params": params}, want_short_ids, mask)
        np.testing.assert_allclose(got_short, np.asarray(want_short), rtol=1e-4, atol=1e-5)


class TestFlashEncoderParity:
    """The flash (bidirectional Pallas) encoder path must match the dense
    XLA oracle on right-padded batches — it is the INGEST hot path on TPU
    (the dense path materializes fp32 [B,H,S,S] scores: 8.6 GB/layer at
    the (32, 2048) ingest shape)."""

    def test_flash_interpret_matches_xla(self):
        import numpy as np

        from rag_llm_k8s_tpu.core.config import DTypePolicy, EncoderConfig
        from rag_llm_k8s_tpu.models.bge_m3 import BgeM3Encoder, init_encoder_params

        fp32 = DTypePolicy.fp32()
        cfg = EncoderConfig.tiny(vocab_size=128)
        params = init_encoder_params(jax.random.PRNGKey(0), cfg, fp32)
        tokens = np.full((3, 32), cfg.pad_token_id, np.int32)
        mask = np.zeros((3, 32), np.int32)
        for i, L in enumerate((32, 17, 5)):  # full, ragged, short
            tokens[i, :L] = 5 + np.arange(L)
            mask[i, :L] = 1
        outs = {}
        for impl in ("xla", "flash_interpret"):
            model = BgeM3Encoder(cfg, fp32, attn_impl=impl)
            outs[impl] = np.asarray(
                model.apply({"params": params}, jnp.asarray(tokens), jnp.asarray(mask))
            )
        np.testing.assert_allclose(
            outs["flash_interpret"], outs["xla"], rtol=2e-5, atol=2e-5
        )
