"""Journal-replay harness tests (ISSUE 17, docs/REPLAY.md).

Four layers, cheapest first:

- the pure decision core (``sim/policy.py``) — arithmetic pins;
- the trace generator (``sim/tracegen.py``) — seeded determinism;
- journal plumbing — export/load round-trip over EVERY ``flight.EVENTS``
  entry, the forward-compat unknown-event skip, decision-stream diffing;
- the fidelity contract itself: record a live run (real engine, CPU)
  under the lockstep driver, ``extract_trace`` it, re-drive it, and the
  decision streams are IDENTICAL — including under a chaos-reset
  recording — plus the pure-host simulator's own fixed point, speedup,
  and calibrated-model fidelity band.

``make replay-smoke`` runs the ``TestReplaySmoke`` class alone.
"""

import json
import logging

import jax
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    FlightConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight, goodput, shadow
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.sim import policy, replay, simulator, tracegen

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
ENG = EngineConfig(
    prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64,
    kv_paged=True, kv_block_size=16,
)
CFG = LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params():
    return init_llama_params(jax.random.PRNGKey(0), CFG, FP32)


def make_engine(params, engine_config=ENG):
    return ContinuousEngine(
        CFG, params, sampling=GREEDY, engine_config=engine_config,
        dtypes=FP32,
    )


#: seven requests over four slots: one admission wave, staggered tail
#: arrivals, and an idle clock-jump (rid 107 at t_step 5)
TRACE = {"arrivals": [
    {"rid": 101 + i, "t_step": [0, 0, 0, 0, 2, 3, 5][i],
     "ids": [3 + i, 17, 42, 7 + i], "prompt_len": 4, "max_new": 8,
     "seed": None}
    for i in range(7)
]}


def record(params, trace, engine_config=ENG, fault=None):
    """Drive ``trace`` against a fresh real engine under the lockstep
    driver, journaling to the flight recorder; returns (journal,
    results)."""
    eng = make_engine(params, engine_config)
    flight.configure(enabled=True, capacity=8192)
    flight.recorder().clear()
    if fault is not None:
        faults.arm(fault, times=1)
    drv = replay.LockstepDriver(eng, emit=flight.emit)
    results = drv.drive(trace)
    return flight.recorder().snapshot(), results


# ---------------------------------------------------------------------------
# the decision core
# ---------------------------------------------------------------------------


class TestPolicy:
    def test_block_arithmetic(self):
        assert policy.blocks_for(0, 16) == 0
        assert policy.blocks_for(1, 16) == 1
        assert policy.blocks_for(16, 16) == 1
        assert policy.blocks_for(17, 16) == 2
        assert policy.admission_blocks(0, 16) == 1  # BOS floor
        assert policy.window_blocks(30, 4, 16, max_blocks_per_row=4) == 3
        assert policy.window_blocks(62, 4, 16, max_blocks_per_row=4) == 4

    def test_admission_verdict(self):
        assert policy.admission_verdict(10, 8, False, 64) == ("never", 0)
        assert policy.admission_verdict(4, 8, True, 64) == ("ok", 0)
        # +1 headroom, capped at the row table size
        assert policy.admission_verdict(4, 8, False, 64) == ("check", 5)
        assert policy.admission_verdict(4, 8, False, 4) == ("check", 4)

    def test_bucket_and_budget(self):
        assert policy.bucket_len(5, (16, 32)) == 16
        assert policy.bucket_len(17, (16, 32)) == 32
        assert policy.bucket_len(99, (16, 32)) == 32  # clamp to largest
        assert policy.clamp_max_new(100, 16, 64) == 48
        assert policy.clamp_max_new(0, 16, 64) == 1

    def test_admission_chunks_pow2_and_order(self):
        chunks = policy.admission_chunks(
            [(0, 16), (1, 32), (2, 16), (3, 16)], max_batch=4
        )
        # bucket insertion order (16 first), pow2 sizes, arrival order
        assert chunks == [(16, [0, 2]), (16, [3]), (32, [1])]
        # max_batch caps the pow2
        chunks = policy.admission_chunks(
            [(i, 16) for i in range(8)], max_batch=2
        )
        assert [len(m) for _, m in chunks] == [2, 2, 2, 2]

    def test_grow_shortfall_orders_oldest_first(self):
        rows = [(7, 1, 30, 1), (3, 0, 30, 1), (9, 2, 10, 1)]
        short = policy.grow_shortfall(rows, 4, None, 16, 8)
        # row 2 needs nothing (10+4 = 14 < 16 → 1 block, already held);
        # the others need blocks_for(34) = 3, holding 1 → missing 2 —
        # ordered oldest admission first (seq 3 before seq 7)
        assert short == [(3, 0, 2, 1), (7, 1, 2, 1)]

    def test_preempt_victim_is_newest(self):
        assert policy.preempt_victim([(3, 0), (9, 2), (7, 1)]) == (9, 2)

    def test_reclaim_registration_cold_then_oldest(self):
        tiers = {"a": "hot", "b": "warm", "c": "warm"}
        gens = {"a": 1, "b": 5, "c": 2}
        assert policy.reclaim_registration(["a", "b", "c"], tiers, gens) == "c"
        assert policy.reclaim_registration([], {}, {}) is None

    def test_plan_mixed_window_budget_split(self):
        adm = [(1, 100, 0), (2, 100, 90), (3, 50, 0)]
        sched = policy.plan_mixed_window(
            adm, window_budget=40, n_decode=8, chunk_tokens=16
        )
        # 32 tokens of budget: 16 to rid1, 10 (final) to rid2, 6 to rid3
        assert sched == [
            (1, 0, 16, False), (2, 90, 10, True), (3, 0, 6, False),
        ]
        assert policy.plan_mixed_window(adm, 8, 8, 16) == []

    def test_resume_fits(self):
        assert policy.resume_fits(10, 5, 32)
        assert not policy.resume_fits(10, 0, 32)   # nothing emitted
        assert not policy.resume_fits(30, 5, 32)   # would truncate


# ---------------------------------------------------------------------------
# the trace generator
# ---------------------------------------------------------------------------


class TestTraceGen:
    def test_seeded_determinism(self):
        a = tracegen.generate(150, seed=11, emit_ids=True)
        b = tracegen.generate(150, seed=11, emit_ids=True)
        assert a == b
        assert a != tracegen.generate(150, seed=12, emit_ids=True)

    def test_shape_and_clocks(self):
        t = tracegen.generate(100, seed=5, step_period_s=0.02)
        arr = t["arrivals"]
        assert len(arr) == 100
        ts = [a["t"] for a in arr]
        assert ts == sorted(ts)
        assert all(a["t_step"] == int(a["t"] / 0.02) for a in arr)
        assert all(
            tracegen.generate(1, seed=0)["arrivals"][0].keys()
            >= {"rid", "t", "t_step", "prompt_len", "max_new",
                "session", "tenant"}
        for _ in (0,))

    def test_hot_chunk_skew(self):
        t = tracegen.generate(300, seed=9, emit_ids=True, hot_chunks=32,
                              chunk_len=16, zipf_a=1.2)
        # rank-0 chunk tokens (ids 1000..1015) must dominate rank-20's
        hot = sum(
            1 for a in t["arrivals"] for x in a["ids"] if 1000 <= x < 1016
        )
        cold = sum(
            1 for a in t["arrivals"]
            for x in a["ids"] if 1320 <= x < 1336
        )
        assert hot > 4 * max(cold, 1)

    def test_sessions_accumulate_history(self):
        t = tracegen.generate(300, seed=13)
        by_session = {}
        for a in t["arrivals"]:
            by_session.setdefault(a["session"], []).append(a["prompt_len"])
        multi = [v for v in by_session.values() if len(v) >= 3]
        assert multi, "no multi-turn sessions generated"
        # follow-up turns trend longer (history folds forward); compare
        # aggregate first-turn vs later-turn means to ride out noise
        first = [v[0] for v in multi]
        later = [x for v in multi for x in v[2:]]
        assert sum(later) / len(later) > sum(first) / len(first)

    def test_describe(self):
        d = tracegen.describe(tracegen.generate(50, seed=2))
        assert d["requests"] == 50
        assert set(d["tenants"]) <= {"free", "pro"}
        assert d["sessions"] >= 1 and d["prompt_len"]["p50"] >= 16


# ---------------------------------------------------------------------------
# journal plumbing: export/load, forward compat, diffing
# ---------------------------------------------------------------------------


class TestJournalRoundTrip:
    def test_every_event_type_survives_export_parse_replay(self, tmp_path):
        """Each ``flight.EVENTS`` entry: emit → export_journal →
        load_journal → parse_journal keeps it, and both offline state
        reconstructions (goodput, shadow) accept the full journal."""
        flight.configure(enabled=True, capacity=2048)
        flight.recorder().clear()
        for i, etype in enumerate(flight.EVENTS):
            flight.emit(etype, i, n=1)
        path = str(tmp_path / "all_events.json")
        flight.export_journal(path, meta={"trigger": "test"})
        events = flight.load_journal(path)
        parsed = replay.parse_journal(events)
        assert parsed["skipped"] == {}
        assert [e["type"] for e in parsed["events"]] == list(flight.EVENTS)
        # the replay parser's order is the recorder's seq order
        assert [e["rid"] for e in parsed["events"]] == list(
            range(len(flight.EVENTS))
        )
        # offline reconstructions consume the same journal unchanged
        goodput.render_report(goodput.state_from_events(events))
        shadow.render_report(shadow.state_from_events(events))

    def test_unknown_event_type_skipped_with_warning(self, caplog):
        """Forward-compat pin: a journal recorded by a NEWER build (an
        event type this build has never heard of) replays on the known
        subset — warned, never raised."""
        flight.configure(enabled=True, capacity=64)
        flight.recorder().clear()
        flight.emit("admit", 1, slot=0, prompt_len=4, bucket=16, tok0=5)
        events = flight.recorder().snapshot()
        events.append({"seq": 10 ** 9, "t": 0.0,
                       "type": "warp_drive_engaged", "rid": 1})
        events.append("not even a dict")
        with caplog.at_level(logging.WARNING,
                             logger="rag_llm_k8s_tpu.sim.replay"):
            parsed = replay.parse_journal(events)
        assert parsed["skipped"] == {
            "warp_drive_engaged": 1, "<malformed>": 1,
        }
        assert [e["type"] for e in parsed["events"]] == ["admit"]
        assert any("warp_drive_engaged" in r.message for r in caplog.records)
        # the trace extractor and differ ride the same tolerant parser
        replay.extract_trace(events)
        assert replay.diff_journals(events, events)["identical"]

    def test_load_journal_warns_on_newer_schema(self, tmp_path, caplog):
        path = str(tmp_path / "future.json")
        with open(path, "w") as f:
            json.dump({"schema_version": flight.SCHEMA_VERSION + 1,
                       "journal": [{"seq": 1, "type": "admit", "rid": 1}]},
                      f)
        with caplog.at_level(logging.WARNING):
            events = flight.load_journal(path)
        assert len(events) == 1
        assert any("schema_version" in r.message for r in caplog.records)

    def test_load_journal_shapes(self, tmp_path):
        bare = str(tmp_path / "bare.json")
        with open(bare, "w") as f:
            json.dump([{"seq": 1, "type": "admit"}], f)
        assert flight.load_journal(bare) == [{"seq": 1, "type": "admit"}]
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump({"nope": 1}, f)
        with pytest.raises(ValueError):
            flight.load_journal(bad)


class TestDecisionDiff:
    def _j(self, *types, extra=None):
        out = []
        for i, t in enumerate(types):
            e = {"seq": i, "t": 0.1 * i, "type": t, "rid": 1,
                 "duration_ms": 5.0 * i}
            if extra and i in extra:
                e.update(extra[i])
            out.append(e)
        return out

    def test_timing_attrs_stripped(self):
        a = self._j("admit", "eos")
        b = [dict(e, t=e["t"] + 99, duration_ms=0.001, seq=e["seq"] + 7)
             for e in a]
        d = replay.diff_journals(a, b)
        assert d["identical"] and d["requests_identical"]

    def test_first_divergence_located(self):
        a = self._j("admit", "eos", "complete")
        b = self._j("admit", "eos", "complete", extra={1: {"n_tokens": 9}})
        d = replay.diff_journals(a, b)
        assert not d["identical"]
        assert d["first_divergence"]["index"] == 1
        assert d["first_divergence"]["b"]["n_tokens"] == 9
        assert d["requests_diverged"] == [1]

    def test_length_mismatch_diverges_at_tail(self):
        a = self._j("admit", "eos")
        b = self._j("admit")
        d = replay.diff_journals(a, b)
        assert d["first_divergence"]["index"] == 1
        assert d["first_divergence"]["b"] is None
        assert d["event_counts"]["eos"]["delta"] == -1

    def test_measurements_are_not_decisions(self):
        a = self._j("admit") + [
            {"seq": 5, "type": "goodput_window", "kind": "decode",
             "dur_ms": 3.0}
        ]
        b = self._j("admit") + [
            {"seq": 5, "type": "goodput_window", "kind": "decode",
             "dur_ms": 9999.0}
        ]
        assert replay.diff_journals(a, b)["identical"]


# ---------------------------------------------------------------------------
# the fidelity contract (real engine on CPU)
# ---------------------------------------------------------------------------


class TestReplaySmoke:
    """``make replay-smoke``: record → extract_trace → re-drive is a
    fixed point of the decision stream."""

    def test_plain_paged_fixed_point(self, params):
        j1, r1 = record(params, TRACE)
        t1 = replay.extract_trace(j1)
        # the lockstep clock round-trips: staggered arrivals stay put
        assert [(a["rid"], a["t_step"]) for a in t1["arrivals"]] == [
            (101, 0), (102, 0), (103, 0), (104, 0),
            (105, 2), (106, 3), (107, 5),
        ]
        assert all(a["ids"] for a in t1["arrivals"])  # arrival_ids on
        j2, r2 = record(params, t1)
        diff = replay.diff_journals(j1, j2)
        assert diff["identical"], diff["first_divergence"]
        assert r1 == r2 and len(r1) == 7  # token streams too, not just shapes

    def test_chaos_reset_fixed_point(self, params):
        """The acceptance pin: a recording that crossed a mid-decode
        fault (reset + resubmit) still replays decision-identical —
        failed steps count on the lockstep clock."""
        j1, r1 = record(params, TRACE, fault="decode_step")
        assert any(e["type"] == "reset" for e in j1)
        assert any(e["type"] == "resubmit" for e in j1)
        j2, r2 = record(params, replay.extract_trace(j1),
                        fault="decode_step")
        diff = replay.diff_journals(j1, j2)
        assert diff["identical"], diff["first_divergence"]
        assert r1 == r2

    def test_interleave_fixed_point(self, params):
        """Chunked-prefill mode: the mixed-window planner's decisions
        (window_budget / prefill_chunk_sched) replay exactly too."""
        import dataclasses
        eng_i = dataclasses.replace(ENG, interleave_prefill=True)
        j1, r1 = record(params, TRACE, engine_config=eng_i)
        assert any(e["type"] == "window_budget" for e in j1)
        j2, r2 = record(params, replay.extract_trace(j1),
                        engine_config=eng_i)
        diff = replay.diff_journals(j1, j2)
        assert diff["identical"], diff["first_divergence"]
        assert r1 == r2

    def test_simulated_goodput_lands_in_band(self, params):
        """Simulate the recorded trace through a step model CALIBRATED
        on the recording: the simulator's busy chip-time must land
        within ±25% of the recording's (the bench leg's fidelity band,
        measured here on the CPU engine's own journal)."""
        j1, _ = record(params, TRACE)
        trace = replay.extract_trace(j1)
        model = simulator.CalibratedStepModel.from_journal(j1)
        res = simulator.simulate(
            trace, step_model=model,
            buckets=ENG.prompt_buckets, max_batch_size=ENG.max_batch_size,
            max_seq_len=ENG.max_seq_len, block_size=ENG.kv_block_size,
        )
        rec_busy = sum(
            e.get("dur_ms", 0.0) for e in j1
            if e.get("type") == "goodput_window"
        ) / 1e3
        sim_busy = res["report"]["busy_s"]
        assert rec_busy > 0
        assert abs(sim_busy - rec_busy) / rec_busy <= 0.25, (
            f"simulated busy {sim_busy:.4f}s vs recorded "
            f"{rec_busy:.4f}s — outside the ±25% fidelity band"
        )


# ---------------------------------------------------------------------------
# the pure-host simulator
# ---------------------------------------------------------------------------


class TestSimulator:
    BUCKETS = (64, 128, 256, 512)

    def _run(self, trace, **kw):
        args = dict(max_batch_size=8, max_seq_len=1024,
                    buckets=self.BUCKETS, chip_hour_usd=3.2)
        args.update(kw)
        return simulator.simulate(trace, **args)

    def test_deterministic_and_fixed_point(self):
        trace = tracegen.generate(60, seed=21)
        r1, r2 = self._run(trace), self._run(trace)
        assert replay.diff_journals(r1["journal"], r2["journal"])["identical"]
        assert r1["results"] == r2["results"]
        # the simulator's own journal re-extracts and re-simulates to
        # the same decision stream (the harness composes with itself)
        t2 = replay.extract_trace(r1["journal"])
        r3 = self._run(t2)
        assert replay.diff_journals(
            r1["journal"], r3["journal"]
        )["identical"]

    def test_renderers_consume_synthetic_journal(self, tmp_path):
        from scripts import flightview
        res = self._run(tracegen.generate(30, seed=4))
        path = str(tmp_path / "sim.json")
        flight.export_journal(path, events=res["journal"],
                              meta={"source": "simulator"})
        assert flightview.main([path]) == 0
        assert flightview.main([path, "--goodput"]) == 0
        rep = res["report"]
        assert rep["busy_frac"] > 0
        assert rep["cost"]["per_query_chip_ms"]["n"] == 30
        assert rep["cost"]["chip_hour_usd"] == 3.2

    def test_faster_than_real_time(self):
        """The acceptance floor: ≥100× virtual-over-wall speedup (the
        bench leg reports the real figure; roofline-modeled TPU windows
        against host dict math clears 100× with a wide margin)."""
        res = self._run(tracegen.generate(300, seed=31))
        assert not res["errors"]
        assert res["speedup_x"] >= 100, res["speedup_x"]

    def test_preemption_under_tight_pool(self):
        """An undersized pool produces preempt → resubmit →
        re-admission chains, driven by the SAME policy ordering the
        live engine uses — and every request still completes."""
        trace = tracegen.generate(24, seed=8, prompt_len_range=(64, 480),
                                  max_new_range=(32, 64))
        res = self._run(trace, pool_blocks=60, decode_sync_steps=4)
        types = [e["type"] for e in res["journal"]]
        assert "preempt" in types and "resubmit" in types
        assert not res["errors"]
        assert len(res["results"]) == 24

    def test_oracle_output_lengths(self):
        trace = {"arrivals": [
            {"rid": 1, "t_step": 0, "prompt_len": 40, "max_new": 32,
             "n_out": 5},
            {"rid": 2, "t_step": 0, "prompt_len": 40, "max_new": 32},
        ]}
        res = self._run(trace)
        assert len(res["results"][1]) == 5   # recorded length wins
        assert len(res["results"][2]) == 32  # budget otherwise

    def test_never_admissible_prompt_errors(self):
        trace = {"arrivals": [
            {"rid": 7, "t_step": 0, "prompt_len": 600, "max_new": 4},
        ]}
        res = self._run(trace, pool_blocks=8, max_seq_len=1024)
        assert "7" in str(list(res["errors"].keys()))
        assert res["results"] == {}

    def test_calibrated_model_fit(self):
        events = [
            {"type": "goodput_window", "kind": "decode",
             "dur_ms": 2.0 + 0.5 * n, "tokens": n}
            for n in (2, 4, 8, 16)
        ] + [
            {"type": "goodput_window", "kind": "prefill",
             "dur_ms": 30.0, "tokens": 64},
            {"type": "goodput_window", "kind": "decode",
             "dur_ms": 1.5, "tokens": 0, "preempt_rework": 1.5},
        ]
        m = simulator.CalibratedStepModel.from_journal(events)
        a, b = m.coeffs["decode"]
        assert abs(a - 2.0) < 1e-6 and abs(b - 0.5) < 1e-6
        assert m.decode(1, 10, 0) == pytest.approx(7.0 / 1e3)
        assert m.prefill(64, 1, 64) == pytest.approx(30.0 / 1e3)
        assert m.stall() == pytest.approx(1.5 / 1e3)
        # unseen kind falls back, empty model falls back to default
        assert m._pred_ms("mixed", 10) > 0
        assert simulator.CalibratedStepModel({})._pred_ms("decode", 5) == \
            simulator.CalibratedStepModel.DEFAULT_MS


# ---------------------------------------------------------------------------
# flightview --replay-diff
# ---------------------------------------------------------------------------


class TestFlightviewReplayDiff:
    def test_identical_and_divergent_exit_codes(self, tmp_path, capsys):
        from scripts import flightview
        res = simulator.simulate(
            tracegen.generate(10, seed=1), max_batch_size=4,
            buckets=(64, 128), max_seq_len=512,
        )
        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        c = str(tmp_path / "c.json")
        flight.export_journal(a, events=res["journal"])
        flight.export_journal(b, events=res["journal"])
        mutated = [dict(e) for e in res["journal"]]
        for e in mutated:
            if e["type"] == "admit":
                e["slot"] = 99
                break
        flight.export_journal(c, events=mutated)
        assert flightview.main([a, "--replay-diff", b]) == 0
        out = capsys.readouterr().out
        assert "identical=True" in out
        assert flightview.main([a, "--replay-diff", c, "--json"]) == 1
        diff = json.loads(capsys.readouterr().out)
        assert diff["first_divergence"]["b"]["slot"] == 99

    def test_arrival_ids_config_knob(self):
        assert FlightConfig().arrival_ids is True
        fc = FlightConfig.from_env({"TPU_RAG_FLIGHT_ARRIVAL_IDS": "0"})
        assert fc.arrival_ids is False
        flight.configure(enabled=True, capacity=64, arrival_ids=False)
        try:
            assert flight.arrival_ids() is False
        finally:
            flight.configure(enabled=True, capacity=64, arrival_ids=True)
