"""End-to-end HTTP integration tests: tiny models behind the real Flask app,
exercising every route (survey §4: 'HTTP-level integration tests with a tiny
stand-in model')."""

import io
import zlib

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.server.app import RagService, create_app

FP32 = DTypePolicy.fp32()


class ByteTokenizer:
    """Reversible byte-level stub tokenizer (ids = byte + 3)."""

    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode("utf-8", "replace")


def make_pdf(text: str, compress: bool = False) -> bytes:
    """Minimal single-page PDF with a text content stream."""
    content = f"BT /F1 12 Tf ({text}) Tj ET".encode()
    filt = b""
    if compress:
        content = zlib.compress(content)
        filt = b" /Filter /FlateDecode"
    parts = [b"%PDF-1.4\n"]
    parts.append(b"1 0 obj << /Type /Catalog /Pages 2 0 R >> endobj\n")
    parts.append(b"2 0 obj << /Type /Pages /Kids [3 0 R] /Count 1 >> endobj\n")
    parts.append(
        b"3 0 obj << /Type /Page /Parent 2 0 R /Contents 4 0 R "
        b"/Resources << /Font << /F1 5 0 R >> >> >> endobj\n"
    )
    parts.append(
        b"4 0 obj << /Length %d%s >> stream\n%s\nendstream endobj\n"
        % (len(content), filt, content)
    )
    parts.append(b"5 0 obj << /Type /Font /Subtype /Type1 /BaseFont /Helvetica >> endobj\n")
    parts.append(b"%%EOF")
    return b"".join(parts)


@pytest.fixture(scope="module")
def client(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("srv")
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)

    engine = InferenceEngine(
        llama_cfg,
        init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
        sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
        engine_config=EngineConfig(prompt_buckets=(128, 256), max_batch_size=2),
        dtypes=FP32,
    )
    encoder = EncoderRunner(
        enc_cfg,
        init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32,
        length_buckets=(32, 64),
        max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size, path=str(tmp / "idx"))
    service = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
    service.ready = True
    app = create_app(service)
    return app.test_client()


class TestRoutes:
    def test_upload_pdf_and_index_info(self, client):
        pdf = make_pdf("TPU retrieval systems use interchip links for collectives")
        r = client.post(
            "/upload_pdf",
            data={"file": (io.BytesIO(pdf), "doc.pdf")},
            content_type="multipart/form-data",
        )
        assert r.status_code == 200, r.get_json()
        assert "chunks created" in r.get_json()["message"]

        info = client.get("/index_info").get_json()
        assert info["total_vectors"] >= 1
        assert info["dimension"] == 32
        assert info["sample_chunks"][0]["filename"] == "doc.pdf"

    def test_upload_rejections(self, client):
        r = client.post("/upload_pdf", data={}, content_type="multipart/form-data")
        assert r.status_code == 400
        assert r.get_json()["error"] == "No file part"
        r = client.post(
            "/upload_pdf",
            data={"file": (io.BytesIO(b"x"), "notes.txt")},
            content_type="multipart/form-data",
        )
        assert r.status_code == 400
        assert r.get_json()["error"] == "Invalid file format"

    def test_generate_and_query_alias(self, client):
        # ensure something is indexed
        pdf = make_pdf("flash attention kernels tile queries and keys", compress=True)
        client.post(
            "/upload_pdf",
            data={"file": (io.BytesIO(pdf), "doc2.pdf")},
            content_type="multipart/form-data",
        )
        for route in ("/generate", "/query"):
            r = client.post(route, json={"prompt": "what do kernels tile?"})
            assert r.status_code == 200, r.get_json()
            body = r.get_json()
            assert "generated_text" in body
            assert "context" in body
            assert "Document 'doc" in body["context"]
            assert "score:" in body["context"]
            # chip_ms / goodput_frac: the goodput ledger's per-request
            # attribution (ISSUE 14, additive; cost_usd only when priced)
            assert set(body["timings"]) == {
                "tokenize_ms", "embed_retrieve_ms", "generate_ms",
                "total_ms", "chip_ms", "goodput_frac",
            }

    def test_healthz_and_metrics(self, client):
        assert client.get("/healthz").status_code == 200
        m = client.get("/metrics", headers={"Accept": "application/json"}).get_json()
        assert m["index_vectors"] >= 1
        assert m["engine_generate_calls"] >= 1

    def test_metrics_prometheus_exposition(self, client):
        # the default (no Accept) output must be scrapable text exposition
        r = client.get("/metrics")
        assert r.status_code == 200
        assert r.content_type.startswith("text/plain")
        text = r.get_data(as_text=True)
        lines = [l for l in text.splitlines() if l]
        assert any(l.startswith("# TYPE tpu_rag_") for l in lines)
        samples = {}
        for l in lines:
            if l.startswith("#"):
                continue
            name, val = l.rsplit(" ", 1)
            float(val)  # every sample parses as a number
            samples[name] = float(val)
        assert samples["tpu_rag_index_vectors"] >= 1
        assert samples["tpu_rag_engine_generate_calls"] >= 1

    def test_ingest_idempotent_via_http(self, client):
        pdf = make_pdf("deduplicated content should index once")
        for _ in range(2):
            r = client.post(
                "/upload_pdf",
                data={"file": (io.BytesIO(pdf), "dup.pdf")},
                content_type="multipart/form-data",
            )
            assert r.status_code == 200
        info = client.get("/index_info").get_json()
        dup_chunks = [c for c in info["sample_chunks"] if c["filename"] == "dup.pdf"]
        # store-level check: exactly one vector for the duplicated doc
        assert info["total_vectors"] == info["total_chunks"]

    def test_empty_index_message(self, tmp_path):
        llama_cfg = LlamaConfig.tiny(vocab_size=300)
        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)
        engine = InferenceEngine(
            llama_cfg,
            init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
            sampling=SamplingConfig(do_sample=False, max_new_tokens=4),
            engine_config=EngineConfig(prompt_buckets=(128,)),
            dtypes=FP32,
        )
        encoder = EncoderRunner(
            enc_cfg,
            init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
            dtypes=FP32,
            length_buckets=(32,),
        )
        store = VectorStore(dim=enc_cfg.hidden_size)
        service = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
        service.ready = False
        app = create_app(service)
        c = app.test_client()
        assert c.get("/healthz").status_code == 503  # not warmed yet
        body = c.post("/generate", json={"prompt": "anything"}).get_json()
        assert body["generated_text"] == "No relevant information found in the index."


class TestEmbedTruncation:
    def test_truncation_preserves_eos(self):
        """Over-limit encoder inputs keep their trailing EOS (the bge-m3 CLS
        pipeline expects </s>-terminated sequences; a bare [:limit] cut used
        to drop it)."""

        class EosTokenizer(ByteTokenizer):
            eos_id = 2

            def encode(self, text):
                return [1] + super().encode(text) + [2]

        class RecordingEncoder:
            def __init__(self):
                self.seen = None

            def encode(self, token_lists):
                self.seen = [list(t) for t in token_lists]
                return np.zeros((len(token_lists), 4), np.float32)

        cfg = AppConfig(model=LlamaConfig.tiny(), encoder=EncoderConfig.tiny())
        rec = RecordingEncoder()
        svc = RagService(cfg, None, ByteTokenizer(), rec, EosTokenizer(), None)
        limit = cfg.encoder.max_encode_len

        svc.embed_texts(["x" * (limit * 2), "short"])
        long_ids, short_ids = rec.seen
        assert len(long_ids) == limit
        assert long_ids[-1] == 2  # EOS survives truncation
        assert short_ids[-1] == 2 and short_ids[0] == 1  # untouched


class TestLongPromptRouting:
    def test_over_bucket_prompt_bypasses_scheduler_for_chunked_prefill(self):
        """A /generate prompt beyond the largest bucket must run through the
        chunk-capable one-shot engine, not the fixed-slot scheduler (which
        would loudly truncate it)."""
        llama_cfg = LlamaConfig.tiny(vocab_size=300)
        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)
        engine = InferenceEngine(
            llama_cfg,
            init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
            sampling=SamplingConfig(do_sample=False, max_new_tokens=4),
            engine_config=EngineConfig(prompt_buckets=(128, 512), max_batch_size=2),
            dtypes=FP32,
        )

        class SlotEngineStub:
            # models a ContinuousEngine: fixed slot ladder, no chunking
            buckets = (128, 512)
            engine_config = engine.engine_config
            stats = engine.stats

        class RecordingScheduler:
            def __init__(self):
                self.engine = SlotEngineStub()
                self.submitted = []

            def submit(self, prompt, **kw):
                self.submitted.append(len(prompt))
                return engine.generate([prompt])[0]

        encoder = EncoderRunner(
            enc_cfg,
            init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
            dtypes=FP32, length_buckets=(32,), max_batch=4,
        )
        store = VectorStore(dim=enc_cfg.hidden_size)
        svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(),
                         store, scheduler=RecordingScheduler())
        svc.ready = True
        # seed the index so answer() reaches generation; tiny chunk text
        # keeps the assembled prompt under the bucket for the short case
        vec = encoder.encode([ByteTokenizer().encode("tiny")])[0]
        store.add([vec], [{"filename": "f", "chunk_id": 0, "text": "ok"}])

        svc.answer("hi")  # short: assembled prompt fits -> scheduler path
        assert svc.scheduler.submitted, "short prompt should use the scheduler"

        before = list(svc.scheduler.submitted)
        svc.answer("x" * 1200)  # long: prompt exceeds bucket 512 -> engine path
        assert svc.scheduler.submitted == before  # scheduler NOT used
        assert any(k[3] == 512 for k in engine._compiled)  # chunked exe ran


class TestCoalescedRetrieval:
    """Under concurrency the embed+kNN stage batches into one fused device
    call (RagService.retrieve_coalescer) — results must match the solo path
    exactly, and concurrent /query must return the sequential answers."""

    def _make_service(self, with_scheduler: bool):
        from rag_llm_k8s_tpu.engine.batching import BatchScheduler

        llama_cfg = LlamaConfig.tiny(vocab_size=300)
        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)
        engine = InferenceEngine(
            llama_cfg,
            init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
            sampling=SamplingConfig(do_sample=False, max_new_tokens=4),
            engine_config=EngineConfig(prompt_buckets=(128,), max_batch_size=4),
            dtypes=FP32,
        )
        encoder = EncoderRunner(
            enc_cfg,
            init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
            dtypes=FP32, length_buckets=(32,), max_batch=4,
        )
        store = VectorStore(dim=enc_cfg.hidden_size)
        scheduler = BatchScheduler(engine, max_wait_ms=20.0) if with_scheduler else None
        svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(),
                         store, scheduler=scheduler)
        svc.ready = True
        texts = ["alpha beta gamma", "delta epsilon", "zeta eta theta iota"]
        vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
        store.add(list(vecs), [
            {"filename": "f", "chunk_id": i, "text": t} for i, t in enumerate(texts)
        ])
        return svc

    def test_retrieve_many_matches_solo(self):
        svc = self._make_service(with_scheduler=False)
        queries = ["alpha", "epsilon delta", "theta", "gamma beta alpha"]
        solo = [svc._retrieve(q)[0] for q in queries]
        batched = [r for r, _ in svc._retrieve_many(queries)]
        assert len(batched) == len(solo)
        for s, b in zip(solo, batched):
            assert [r.metadata["chunk_id"] for r in s] == [r.metadata["chunk_id"] for r in b]
            np.testing.assert_allclose(
                [r.distance for r in s], [r.distance for r in b], rtol=1e-5, atol=1e-6
            )
        # the batch used ONE padded executable (B=cap), not one per query
        assert any(k[3] == svc._retrieve_cap for k in svc._fused_retrieve)

    def test_concurrent_queries_match_sequential(self):
        import threading

        svc = self._make_service(with_scheduler=True)
        assert svc.retrieve_coalescer is not None
        queries = ["alpha", "epsilon delta", "theta iota", "gamma"]
        try:
            want = {}
            for q in queries:
                # sequential answers through the full serving path
                want[q] = svc.answer(q)["generated_text"]
            got = {}
            errors = []

            def run(q):
                try:
                    got[q] = svc.answer(q)["generated_text"]
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=run, args=(q,)) for q in queries]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            assert got == want
        finally:
            svc.shutdown()

    def test_shutdown_is_idempotent(self):
        svc = self._make_service(with_scheduler=True)
        svc.shutdown()
        svc.shutdown()

    def test_inflight_hints_balance_and_skip_window(self):
        """The per-stage in-flight counters feed pending_hint: a solo query
        must not wait out the coalescing windows, and every path (success,
        empty index, engine failure) must release its claim."""
        import threading
        import time as _time

        svc = self._make_service(with_scheduler=True)
        try:
            # hints are wired to the live counters
            assert svc.retrieve_coalescer.pending_hint() == 0
            assert svc.scheduler.pending_hint() == 0
            # widen the windows: if a solo query waited them out it would be
            # glaring; the hint must end both waits immediately
            svc.retrieve_coalescer.max_wait_ms = 1500.0
            svc.scheduler.max_wait_ms = 1500.0
            svc.answer("warm")  # executables compiled outside the timed call
            t0 = _time.monotonic()
            out = svc.answer("alpha")
            assert (_time.monotonic() - t0) < 1.0
            assert out["generated_text"]
            assert svc._inflight_retrieve == 0 and svc._inflight_generate == 0

            # error path releases the claims too
            orig = svc.scheduler.submit
            svc.scheduler.submit = lambda *a, **kw: (_ for _ in ()).throw(
                RuntimeError("boom")
            )
            try:
                with pytest.raises(RuntimeError, match="boom"):
                    svc.answer("alpha")
            finally:
                svc.scheduler.submit = orig
            assert svc._inflight_retrieve == 0 and svc._inflight_generate == 0

            # concurrent burst: counters settle back to zero afterwards
            threads = [
                threading.Thread(target=svc.answer, args=(q,))
                for q in ["alpha", "gamma", "theta"]
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert svc._inflight_retrieve == 0 and svc._inflight_generate == 0
        finally:
            svc.shutdown()


class TestSpServing:
    """VERDICT r3 #8: serve a real HTTP /query on a dp=1,sp=2,tp=4 mesh —
    the long-prompt prefill must run as RING attention over the sp axis
    (models/llama.py _attend_ring), and the answer must match the meshless
    engine token-for-token."""

    def test_http_query_over_sp2_tp4_mesh(self, monkeypatch, devices8):
        import dataclasses

        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel import ring_attention as ring_mod
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        llama_cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=300), num_kv_heads=4  # K % tp == 0
        )
        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)
        params = init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32)
        eng_cfg = EngineConfig(prompt_buckets=(512,), max_batch_size=1, max_seq_len=640)
        sampling = SamplingConfig(do_sample=False, max_new_tokens=6)

        ctx = make_mesh(MeshConfig(dp=1, sp=2, tp=4), devices=devices8)
        rings = []
        real_ring = ring_mod.ring_attention

        def spy_ring(*a, **kw):
            rings.append(kw.get("axis_name"))
            return real_ring(*a, **kw)

        monkeypatch.setattr(ring_mod, "ring_attention", spy_ring)
        engine = InferenceEngine(
            llama_cfg, shard_llama_params(params, ctx), sampling=sampling,
            engine_config=eng_cfg, dtypes=FP32, mesh=ctx,
        )
        encoder = EncoderRunner(
            enc_cfg, init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
            dtypes=FP32, length_buckets=(32,), max_batch=4,
        )
        store = VectorStore(dim=enc_cfg.hidden_size)
        svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
        svc.ready = True
        texts = ["ring attention rotates key blocks over the ici links",
                 "sequence parallel prefill shards long prompts"]
        vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
        store.add(list(vecs), [
            {"filename": "f", "chunk_id": i, "text": t} for i, t in enumerate(texts)
        ])
        client = create_app(svc).test_client()

        # long prompt: the assembled RAG prompt (system msg + context) lands
        # in the 512 bucket, so prefill runs S=512 >> sp
        r = client.post("/query", json={"prompt": "how do the key blocks move?"})
        assert r.status_code == 200, r.get_json()
        body = r.get_json()
        assert "generated_text" in body and "context" in body
        assert "sp" in rings, "prefill never went through ring attention"

        # token parity vs the meshless engine on the same assembled prompt
        solo = InferenceEngine(
            llama_cfg, params, sampling=sampling, engine_config=eng_cfg, dtypes=FP32
        )
        svc_solo = RagService(cfg, solo, ByteTokenizer(), encoder, ByteTokenizer(), store)
        svc_solo.ready = True
        want = svc_solo.answer("how do the key blocks move?")["generated_text"]
        assert body["generated_text"] == want


class TestGreedyDefaultSpeculates:
    """VERDICT r4 #8: greedy serving (TPU_RAG_DO_SAMPLE=0) gets speculation
    by DEFAULT (speculative="auto") — and the served /query tokens must be
    identical to a speculative-off server on the same weights."""

    def _serve(self, llama_cfg, enc_cfg, params, enc_params, speculative):
        import dataclasses

        cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)
        # 512: the byte-tokenized RAG prompt is ~470 ids — it must land in
        # a single-shot bucket (chunked prefill correctly skips spec)
        ec = EngineConfig(prompt_buckets=(128, 512), max_batch_size=2, max_seq_len=640)
        if speculative is not None:
            ec = dataclasses.replace(ec, speculative=speculative)
        engine = InferenceEngine(
            llama_cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
            engine_config=ec, dtypes=FP32,
        )
        encoder = EncoderRunner(
            enc_cfg, enc_params, dtypes=FP32, length_buckets=(32, 64), max_batch=4
        )
        store = VectorStore(dim=enc_cfg.hidden_size)
        service = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
        service.ready = True
        return engine, create_app(service).test_client()

    def test_default_engine_speculates_and_matches_off(self):
        llama_cfg = LlamaConfig.tiny(vocab_size=300)
        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        params = init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32)
        enc_params = init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32)
        assert EngineConfig().speculative == "auto"  # the default IS on

        pdf = make_pdf("speculation serves greedy queries by default now")
        answers = {}
        for mode in (None, "off"):  # None = the default config
            engine, c = self._serve(llama_cfg, enc_cfg, params, enc_params, mode)
            r = c.post(
                "/upload_pdf",
                data={"file": (io.BytesIO(pdf), "a.pdf")},
                content_type="multipart/form-data",
            )
            assert r.status_code == 200
            r = c.post("/query", json={"prompt": "what serves greedy queries"})
            assert r.status_code == 200, r.get_data()
            answers[mode] = r.get_json()["generated_text"]
            if mode is None:
                # the default really took the speculative executable
                assert engine.stats.spec_verify_steps >= 1
        assert answers[None] == answers["off"]
