"""KV prefix cache: splice correctness, LRU/budget behavior, slot matching.

The contracts under test (engine/prefix_cache.py, docs/PREFIX_CACHE.md):

- **Splice parity**: prefill over a spliced cached-prefix block + chunked
  suffix produces the same last-token logits as a cold full prefill (atol —
  the chunked kernel reduces in a different order than the fresh-K/V path),
  and greedy generation over either is token-identical.
- **LRU + budget**: entries evict least-recently-used past the HBM budget;
  pinned blocks (the head) never evict.
- **Slot matching**: a block cached at one position slot misses at another
  (RoPE makes K position-dependent); under the default "exact" policy a
  changed left context also misses, while "slot" mode reuses on offset
  alone.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    PrefixCacheConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.prefix_cache import PrefixCache
from rag_llm_k8s_tpu.models.llama import KVCache, init_llama_params, make_kv_cache

FP32 = DTypePolicy.fp32()

PC = PrefixCacheConfig(
    enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
    suffix_buckets=(16,), hbm_budget_mb=64,
)


@pytest.fixture(scope="module")
def tiny_engine():
    cfg = LlamaConfig.tiny(vocab_size=128)
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    engine = InferenceEngine(
        cfg,
        params,
        sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
        engine_config=EngineConfig(
            prompt_buckets=(64,), max_batch_size=2, speculative="off",
            max_seq_len=128, prefix_cache=PC,
        ),
        dtypes=FP32,
    )
    return cfg, engine


def _segments(cfg, rng, tag):
    """Segment keys must identify CONTENT (the service keys chunks by the
    store's content hash); a shared cache with a reused key and different
    tokens would correctly return the old key's KV."""
    head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 7)))
    chunk = list(map(int, rng.integers(3, 120, 11)))
    return [(f"head:{tag}", head), (f"chunk:{tag}", chunk)]


class TestSpliceParity:
    def test_cached_prefix_logits_match_cold_prefill(self, tiny_engine):
        cfg, engine = tiny_engine
        rng = np.random.default_rng(3)
        segments = _segments(cfg, rng, "t1")
        suffix = list(map(int, rng.integers(3, 120, 5)))
        cp = engine.prefix_cache.prefix_for(segments)
        assert cp is not None and cp.length == sum(len(s) for _, s in segments)

        # cached path: splice the prefix planes, chunk-prefill the suffix
        T = 64
        S_suf = 16
        n = cp.length + len(suffix)
        cache = make_kv_cache(cfg, 1, T, jnp.float32)
        planes = tuple(
            jax.lax.dynamic_update_slice(c, b, (0,) * c.ndim)
            for c, b in zip((cache.k, cache.v), cp.planes)
        )
        toks = np.zeros((1, S_suf), np.int32)
        toks[0, : len(suffix)] = suffix
        positions = (cp.length + jnp.arange(S_suf, dtype=jnp.int32))[None, :]
        logits_cached, _ = engine.model_chunked.apply(
            {"params": engine.params}, jnp.asarray(toks), positions,
            KVCache(*planes), jnp.zeros((1,), jnp.int32),
            jnp.full((1,), n, jnp.int32), jnp.int32(cp.length),
            logit_index=jnp.int32(len(suffix) - 1),
        )

        # cold path: one full left-aligned prefill over the same tokens
        full = [t for _, seg in segments for t in seg] + suffix
        assert len(full) == n
        cache2 = make_kv_cache(cfg, 1, T, jnp.float32)
        full_arr = jnp.asarray(np.asarray(full, np.int32)[None, :])
        pos2 = jnp.arange(n, dtype=jnp.int32)[None, :]
        logits_cold, _ = engine.model.apply(
            {"params": engine.params}, full_arr, pos2, cache2,
            jnp.zeros((1,), jnp.int32), jnp.full((1,), n, jnp.int32),
            jnp.int32(0), last_logit_only=True,
        )
        np.testing.assert_allclose(
            np.asarray(logits_cached[0, -1]), np.asarray(logits_cold[0, -1]),
            atol=2e-4,
        )

    def test_generate_prefixed_greedy_matches_cold_generate(self, tiny_engine):
        cfg, engine = tiny_engine
        rng = np.random.default_rng(5)
        segments = _segments(cfg, rng, "t2")
        suffix = list(map(int, rng.integers(3, 120, 6)))
        cp = engine.prefix_cache.prefix_for(segments)
        got = engine.generate_prefixed(suffix, cp)
        full = [t for _, seg in segments for t in seg] + suffix
        want = engine.generate([full])[0]
        assert got == want

    def test_repeat_resolve_hits_and_counts_skipped_tokens(self, tiny_engine):
        cfg, engine = tiny_engine
        rng = np.random.default_rng(7)
        segments = _segments(cfg, rng, "t3")
        engine.prefix_cache.prefix_for(segments)
        before = engine.stats.prefill_tokens_skipped
        cp = engine.prefix_cache.prefix_for(segments)
        assert cp.computed_tokens == 0 and cp.reused_tokens == cp.length
        engine.generate_prefixed([5, 6, 7], cp)
        assert engine.stats.prefill_tokens_skipped == before + cp.length


class TestContinuousAdmitPrefixed:
    def test_prefixed_admission_matches_plain_admit(self, tiny_engine):
        """The continuous engine consumes the same CachedPrefix: suffix-only
        prefill into a left-padded slot row, spliced by the existing
        ``_insert`` — greedy output identical to a plain full-prompt
        admission (validates the start = S - total slot geometry)."""
        from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine

        cfg, engine = tiny_engine
        cont = ContinuousEngine(
            cfg, engine.params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=engine.engine_config, dtypes=FP32,
        )
        rng = np.random.default_rng(9)
        segments = _segments(cfg, rng, "cont")
        suffix = list(map(int, rng.integers(3, 120, 6)))
        cp = engine.prefix_cache.prefix_for(segments)

        def drain(rid, fin):
            outs = {}
            while cont.has_active():
                for r, toks in cont.step():
                    outs[r] = toks
            return fin if fin is not None else outs[rid]

        _, fin = cont.admit_prefixed(1, suffix, cp, max_new=6)
        got = drain(1, fin)
        full = [t for _, seg in segments for t in seg] + suffix
        _, fin2 = cont.admit(2, full, max_new=6)
        want = drain(2, fin2)
        assert got == want
        assert cont.stats.prefill_tokens_skipped == cp.length


class _StubEngine:
    """Host-only engine stand-in: blocks are numpy arrays, so LRU/budget/
    slot-policy logic tests never touch a compile."""

    def __init__(self, block_bytes=1 << 20):
        self.block_bytes = block_bytes

    def prefix_buffer_zero(self):
        return (np.zeros(1, np.int8),)

    def build_segment_kv(self, ids, ctx, off):
        return (np.zeros(self.block_bytes, np.int8),)

    def splice_prefix(self, buf, block, off):
        return buf


def _cfg(**kw):
    base = dict(
        enabled=True, max_prefix_tokens=4096, segment_buckets=(64, 2048),
        suffix_buckets=(128,), hbm_budget_mb=4, assembled_cache_entries=2,
    )
    base.update(kw)
    return PrefixCacheConfig(**base)


class TestLruAndSlots:
    def test_lru_eviction_respects_budget_and_pins(self):
        cache = PrefixCache(_cfg(), _StubEngine())  # 4 MiB budget, 1 MiB blocks
        cache.pin("head")
        head = [("head", list(range(8)))]
        cache.prefix_for(head)
        for i in range(6):
            cache.prefix_for(head + [(f"chunk:{i}", list(range(16)))])
        # budget holds 4 one-MiB blocks; the pinned head always survives
        # (counters' bytes additionally include the stub's tiny assembled
        # memo buffers, which evict before any block does)
        assert cache.entry_bytes <= 4 << 20
        assert cache.counters()["prefix_cache_bytes"] <= (4 << 20) + 64
        assert any(k[0] == "head" for k in cache._entries)
        # oldest chunks evicted, newest present
        assert not any(k[0] == "chunk:0" for k in cache._entries)
        assert any(k[0] == "chunk:5" for k in cache._entries)

    def test_slot_mismatch_is_a_miss(self):
        cache = PrefixCache(_cfg(), _StubEngine(block_bytes=8))
        chunk = ("chunk:x", list(range(16)))
        cache.prefix_for([("head", list(range(8))), chunk])
        m0 = cache.counters()["prefix_cache_misses"]
        # same chunk behind a DIFFERENT-length head: new position slot → miss
        cache.prefix_for([("head2", list(range(9))), chunk])
        assert cache.counters()["prefix_cache_misses"] == m0 + 2

    def test_exact_reuse_requires_matching_context_chain(self):
        chunk2 = ("chunk:2", list(range(16)))
        a = [("chunk:1a", list(range(16))), chunk2]
        b = [("chunk:1b", list(range(16))), chunk2]  # same slot, other chain
        exact = PrefixCache(_cfg(), _StubEngine(block_bytes=8))
        exact.prefix_for(a)
        h0 = exact.counters()["prefix_cache_hits"]
        exact.prefix_for(b)
        assert exact.counters()["prefix_cache_hits"] == h0  # chain mismatch
        slot = PrefixCache(_cfg(reuse="slot"), _StubEngine(block_bytes=8))
        slot.prefix_for(a)
        h0 = slot.counters()["prefix_cache_hits"]
        slot.prefix_for(b)
        assert slot.counters()["prefix_cache_hits"] == h0 + 1  # offset match

    def test_empty_suffix_rejected(self, tiny_engine):
        cfg, engine = tiny_engine
        cp = engine.prefix_cache.prefix_for([("head:empty", [cfg.bos_token_id] * 8)])
        with pytest.raises(ValueError, match="non-empty suffix"):
            engine.generate_prefixed([], cp)

    def test_over_capacity_prefix_falls_back(self):
        cache = PrefixCache(_cfg(max_prefix_tokens=16), _StubEngine())
        assert cache.prefix_for([("head", list(range(32)))]) is None
        # a single segment over the largest bucket also declines
        cache2 = PrefixCache(_cfg(segment_buckets=(8,)), _StubEngine())
        assert cache2.prefix_for([("head", list(range(12)))]) is None
