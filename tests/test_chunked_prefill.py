"""Unified ragged sync windows: chunked prefill interleaved with decode
(ISSUE 16).

The load-bearing contract is BYTE-IDENTICAL streams between the paged
engine with interleaving ON and OFF — greedy AND seeded sampling — across
mixed-length admission groups, mid-flight admission, chaos resets landing
mid-chunk, pool preemption of partially-prefilled admissions, prefixed
batchmates, speculative verify windows and tp=2. Interleaving may only
change WHEN a prompt's prefill compute runs (sliced across windows that
also decode), never which tokens any stream carries. The rest is the
planner's unit surface (budget split arithmetic, decode-lane
reservation), block accounting (zero leaks through preempt / evict /
reset), the mixed window's goodput attribution, and the config knobs.

``TestSmoke`` is the `make interleave-smoke` lane (greedy + seeded
identity plus the mid-chunk reset chaos case).
"""

import dataclasses
import threading

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    PrefixCacheConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import (
    ContinuousEngine,
    ContinuousScheduler,
)
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight, goodput
from rag_llm_k8s_tpu.resilience import faults

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=10)
PAGED = EngineConfig(
    prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64,
    kv_paged=True, kv_block_size=16,
)
# chunk width 8 so the longer prompts below spread across 2-3 windows
INTER = dataclasses.replace(
    PAGED, interleave_prefill=True, prefill_chunk_tokens=8
)
# mixed buckets, including prompts longer than one chunk
PROMPTS = [
    [5, 6, 7, 8, 9, 10, 11],
    [12, 13, 14],
    [3] * 20,
    [9] * 25,
]


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def drain(eng, reqs, seeds=None):
    """admit_many + step-to-completion → {rid: tokens}; asserts zero
    leaked blocks on the way out."""
    results = {}
    outs = eng.admit_many([
        (rid, p, mn, None if seeds is None else seeds[i])
        for i, (rid, p, mn) in enumerate(reqs)
    ])
    for (rid, _, _), res in zip(reqs, outs):
        if isinstance(res, BaseException):
            raise res
        _, fin = res
        if fin is not None:
            results[rid] = fin
    for _ in range(300):
        for rid, toks in eng.step():
            results[rid] = toks
        if not eng.has_active():
            break
    assert eng.kv_pool.blocks_in_use() == 0
    return results


# ---------------------------------------------------------------------------
# byte identity (the correctness gate) — the `make interleave-smoke` lane
# ---------------------------------------------------------------------------


class TestSmoke:
    """`make interleave-smoke`: greedy + seeded streams with interleaving
    ON are byte-identical to the phase-separated scheduler on the tiny
    config, including a chaos reset landing mid-chunk — and mixed windows
    actually ran (the identity must not be vacuous)."""

    def test_greedy_mixed_batch_byte_identity(self, setup):
        cfg, params = setup
        reqs = [(i + 1, p, 10) for i, p in enumerate(PROMPTS)]
        base = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32), reqs,
        )
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        inter = drain(eng, reqs)
        assert inter == base
        st = eng.ledger.state()
        assert "mixed" in st["kinds"], "no mixed window ever ran — vacuous"

    @pytest.mark.parametrize("temp", [0.7, 0.01])
    def test_seeded_sampling_mid_flight_byte_identity(self, setup, temp):
        """Seeded sampling: the final chunk folds ``(row_key, prompt_len)``
        — the exact key the one-shot admission folds — and decode lanes
        continue the same (seed, position) sequence, so sampled streams
        match bit-for-bit, including a request joining mid-flight (its
        chunks ride windows that decode the first request)."""
        cfg, params = setup
        samp = SamplingConfig(
            do_sample=True, temperature=temp, top_p=0.9, max_new_tokens=10
        )

        def run(eng_cfg):
            eng = ContinuousEngine(
                cfg, params, sampling=samp, engine_config=eng_cfg,
                dtypes=FP32,
            )
            results = {}
            _, fin = eng.admit(1, PROMPTS[0], 10, seed=123)
            if fin is not None:
                results[1] = fin
            eng.step()
            _, fin = eng.admit(2, PROMPTS[3], 10, seed=7)  # joins mid-flight
            if fin is not None:
                results[2] = fin
            for _ in range(300):
                for rid, toks in eng.step():
                    results[rid] = toks
                if not eng.has_active():
                    break
            assert eng.kv_pool.blocks_in_use() == 0
            return results

        assert run(INTER) == run(PAGED)

    def test_mid_chunk_reset_recovers_byte_identical(self, setup):
        """Chaos: an injected device fault while an admission is PARTWAY
        through its chunks — the reset drops the partial KV and the queue
        record, returns every block, and the resubmission reproduces the
        phase-separated stream exactly."""
        cfg, params = setup
        reqs = [(1, PROMPTS[3], 10), (2, PROMPTS[1], 10)]
        base = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32), reqs,
        )
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        eng.admit_many([(1, PROMPTS[3], 10, None), (2, PROMPTS[1], 10, None)])
        eng.step()  # first window: the 25-token prompt is now mid-chunk
        assert eng._chunk_admissions, "queue drained in one window — vacuous"
        assert eng._chunk_admissions[1]["progress"] > 0
        faults.arm("decode_step", times=1)
        with pytest.raises(faults.InjectedFault):
            eng.step()
        eng.reset()
        assert eng.kv_pool.blocks_in_use() == 0, "reset leaked blocks"
        assert not eng._chunk_admissions, "reset kept a dead admission"
        assert len(eng.free_slots()) == eng.B, "reset kept a reserved row"
        assert drain(eng, reqs) == base

    def test_mid_chunk_reset_recovers_through_scheduler(self, setup):
        """The same fault through the scheduler's recovery path: the
        in-flight chunked admission resubmits from its prompt and the
        caller never sees the fault."""
        cfg, params = setup
        base = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32),
            [(1, PROMPTS[2], 10)],
        )
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            faults.arm("decode_step", times=1)
            out = sched.submit(PROMPTS[2], max_new_tokens=10, timeout=120)
            assert out == base[1]
            assert faults.armed() == {}, "the fault never fired"
            assert eng.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()


# ---------------------------------------------------------------------------
# window planner: budget split arithmetic
# ---------------------------------------------------------------------------


class TestWindowPlanner:
    def test_budget_slices_admissions_fifo(self, setup):
        """budget=6, chunk=4, nothing decoding: the oldest admission takes
        a full chunk, the leftover budget slices the second — and the
        split is journaled (`window_budget` + per-chunk
        `prefill_chunk_sched` flight events)."""
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(
                INTER, prefill_chunk_tokens=4, window_token_budget=6
            ),
            dtypes=FP32,
        )
        seq0 = flight.recorder().events_emitted
        eng.admit_many([(1, [3] * 10, 4, None), (2, [9] * 6, 4, None)])
        eng.step()
        assert eng._chunk_admissions[1]["progress"] == 4
        assert eng._chunk_admissions[2]["progress"] == 2
        wb = [
            e for e in flight.recorder().snapshot(etype="window_budget")
            if e["seq"] >= seq0
        ]
        assert wb and wb[0]["budget"] == 6
        assert wb[0]["decode_lanes"] == 0
        assert wb[0]["chunk_tokens"] == 6 and wb[0]["chunks"] == 2
        sc = [
            e for e in flight.recorder().snapshot(etype="prefill_chunk_sched")
            if e["seq"] >= seq0
        ]
        assert [(e["rid"], e["tokens"], e["final"]) for e in sc] == [
            (1, 4, 0), (2, 2, 0),
        ]
        while eng.has_active() or eng._chunk_admissions:
            eng.step()
        assert eng.kv_pool.blocks_in_use() == 0

    def test_decode_lanes_come_off_the_budget(self, setup):
        """Every active decode row costs one token of the window budget
        BEFORE admissions slice the rest — decode never stops for
        admission, admission gets the leftovers."""
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(
                INTER, window_token_budget=5
            ),
            dtypes=FP32,
        )
        eng.admit_many([(1, PROMPTS[1], 8, None)])  # 3 tokens: one window
        while eng._chunk_admissions:
            eng.step()
        assert sum(1 for s in eng.slots if s.active) == 1
        eng.admit_many([(2, [3] * 20, 4, None)])
        eng.step()
        # budget 5 - 1 decode lane = 4 chunk tokens, not chunk_tokens=8
        assert eng._chunk_admissions[2]["progress"] == 4
        while eng.has_active() or eng._chunk_admissions:
            eng.step()
        assert eng.kv_pool.blocks_in_use() == 0

    def test_auto_budget_default(self, setup):
        """window_token_budget=0 → max_batch_size + prefill_chunk_tokens:
        a full decode batch still advances AND one full chunk fits."""
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        assert eng.window_budget == PAGED.max_batch_size + 8

    def test_incremental_block_allocation(self, setup):
        """A queued admission holds blocks for exactly its PROGRESS, not
        its prompt — the whole point of incremental admission."""
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        eng.admit_many([(1, [9] * 25, 4, None)])
        eng.step()  # one 8-token chunk → 1 block of 16, not the 2 for 25
        rec = eng._chunk_admissions[1]
        assert rec["progress"] == 8
        assert len(eng._slot_blocks[rec["row"]]) == 1
        assert eng.kv_pool.blocks_in_use() == 1
        while eng.has_active() or eng._chunk_admissions:
            eng.step()
        assert eng.kv_pool.blocks_in_use() == 0


# ---------------------------------------------------------------------------
# block accounting: preempt / evict / reset of partial admissions
# ---------------------------------------------------------------------------


class TestPartialAdmissionAccounting:
    def test_pool_preemption_byte_identity_zero_leaks(self, setup):
        """A pool sized for half the batch's growth forces preemption
        WHILE admissions hold partial prefills: resubmission still
        reproduces the phase-separated streams, zero leaked blocks."""
        cfg, params = setup
        want = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32),
            [(i + 1, p, 40) for i, p in enumerate(PROMPTS)],
        )
        tight = dataclasses.replace(INTER, kv_pool_blocks=8)
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=tight, dtypes=FP32
        )
        sched = ContinuousScheduler(eng)
        try:
            outs = [None] * len(PROMPTS)
            errs = [None] * len(PROMPTS)

            def run(i):
                try:
                    outs[i] = sched.submit(
                        PROMPTS[i], max_new_tokens=40, timeout=300
                    )
                except BaseException as e:  # noqa: BLE001
                    errs[i] = e

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(PROMPTS))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert errs == [None] * len(PROMPTS), errs
            assert outs == [want[i + 1] for i in range(len(PROMPTS))]
            assert eng.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()

    def test_evicting_a_partial_admission_frees_everything(self, setup):
        """Deadline eviction mid-prefill (the scheduler's `_evict_expired`
        calls this): the reserved row, the queue record and every
        partially-written block all release."""
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        eng.admit_many([(1, [9] * 25, 8, None)])
        eng.step()
        assert eng.kv_pool.blocks_in_use() > 0
        eng.evict_requests([1])
        assert 1 not in eng._chunk_admissions
        assert eng.kv_pool.blocks_in_use() == 0
        assert len(eng.free_slots()) == eng.B
        # the engine still serves after the eviction
        assert drain(eng, [(2, PROMPTS[0], 5)])[2]

    def test_reset_drops_queued_admissions(self, setup):
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        eng.admit_many([(1, [9] * 25, 8, None)])
        eng.step()
        eng.reset()
        assert not eng._chunk_admissions
        assert eng.kv_pool.blocks_in_use() == 0
        assert len(eng.free_slots()) == eng.B


# ---------------------------------------------------------------------------
# composition: prefix cache + speculative verify windows
# ---------------------------------------------------------------------------


class TestComposition:
    def test_prefixed_batchmate_byte_identity(self, setup):
        """A prefix-cache admission (splice path) decoding WHILE plain
        admissions chunk through mixed windows: both streams match the
        interleave-off engine."""
        cfg0 = LlamaConfig.tiny(vocab_size=128)
        params = init_llama_params(jax.random.PRNGKey(0), cfg0, FP32)
        pc = PrefixCacheConfig(
            enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
            suffix_buckets=(16,), hbm_budget_mb=64,
        )
        ec = EngineConfig(
            prompt_buckets=(64,), max_batch_size=2, speculative="off",
            max_seq_len=128, prefix_cache=pc,
        )
        oneshot = InferenceEngine(
            cfg0, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
            engine_config=ec, dtypes=FP32,
        )
        rng = np.random.default_rng(9)
        head = [cfg0.bos_token_id] + list(map(int, rng.integers(3, 120, 7)))
        chunk = list(map(int, rng.integers(3, 120, 11)))
        suffix = list(map(int, rng.integers(3, 120, 6)))
        plain = list(map(int, rng.integers(3, 120, 20)))
        segments = [("head:inter", head), ("chunk:inter", chunk)]

        def run(inter_on):
            eng_cfg = dataclasses.replace(
                ec, kv_paged=True, kv_block_size=16,
                interleave_prefill=inter_on, prefill_chunk_tokens=8,
            )
            cont = ContinuousEngine(
                cfg0, params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
                engine_config=eng_cfg, dtypes=FP32,
            )
            cp = oneshot.prefix_cache.prefix_for(segments)
            outs = {}
            _, fin = cont.admit_prefixed(1, suffix, cp, max_new=8)
            if fin is not None:
                outs[1] = fin
            # the plain admission chunks while the spliced row decodes
            for (rid, _), res in zip([(2, plain)],
                                     cont.admit_many([(2, plain, 8, None)])):
                _, f2 = res
                if f2 is not None:
                    outs[rid] = f2
            for _ in range(300):
                for r, toks in cont.step():
                    outs[r] = toks
                if not cont.has_active():
                    break
            # NOTE: no zero-block assertion — the prefix REGISTRATION
            # legitimately retains its blocks for future admissions
            return outs

        assert run(True) == run(False)

    def test_speculative_verify_composes_byte_identical(self, setup):
        """Mixed windows take routing priority while admissions queue;
        verify windows resume once it drains — both shapes are
        draw-invariant, so streams match plain PAGED and speculation is
        non-vacuous."""
        cfg, params = setup
        # repeat-heavy prompts so prompt-lookup drafting actually fires
        reqs = [
            (1, [3, 17, 42, 3, 17, 42, 3, 17] * 2, 10),
            (2, [11] * 20, 10),
        ]
        base = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32), reqs,
        )
        both = dataclasses.replace(
            INTER, spec_paged=True, spec_paged_tokens=4
        )
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=both, dtypes=FP32
        )
        got = drain(eng, reqs)
        assert got == base
        assert "mixed" in eng.ledger.state()["kinds"], "vacuous: no mixed"
        assert eng.stats.spec_verify_steps > 0, "vacuous: no verify step"


# ---------------------------------------------------------------------------
# goodput attribution of mixed windows
# ---------------------------------------------------------------------------


class TestGoodputMixed:
    def test_mixed_window_attribution_and_conservation(self, setup):
        """Chunked-prefill lanes land in `prefill_compute` (NOT the
        `padding_bubble` the phase-separated scheduler burned), decode
        lanes that kept their token in `decode_useful`, categories
        conserve against busy time within 5%, and the offline
        reconstruction counts the same useful decode tokens."""
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=INTER, dtypes=FP32
        )
        seq0 = flight.recorder().events_emitted
        drain(eng, [(i + 1, p, 10) for i, p in enumerate(PROMPTS)])
        st = eng.ledger.state()
        mixed = st["kinds"].get("mixed")
        assert mixed and mixed["busy_s"] > 0
        assert st["categories"]["prefill_compute"] > 0
        assert st["categories"]["decode_useful"] > 0
        busy = st["busy_s"]
        assert busy > 0
        assert abs(busy - sum(st["categories"].values())) / busy < 0.05
        events = [
            e for e in flight.recorder().snapshot(etype="goodput_window")
            if e["seq"] >= seq0
        ]
        assert any(e.get("kind") == "mixed" for e in events)
        for e in events:
            cats = sum(e.get(c, 0.0) for c in goodput.WINDOW_CATEGORIES)
            assert cats == pytest.approx(e["dur_ms"], abs=0.01)
        rebuilt = goodput.state_from_events(events)
        assert rebuilt["useful_decode_tokens"] == pytest.approx(
            st["useful_decode_tokens"]
        )


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------


class TestConfig:
    def test_construction_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="requires kv_paged"):
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(
                    INTER, kv_paged=False
                ),
                dtypes=FP32,
            )
        with pytest.raises(ValueError, match="prefill_chunk_tokens"):
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(
                    INTER, prefill_chunk_tokens=0
                ),
                dtypes=FP32,
            )
        with pytest.raises(ValueError, match="window_token_budget"):
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(
                    INTER, window_token_budget=2  # < max_batch_size + 1
                ),
                dtypes=FP32,
            )

    def test_env_round_trip(self, monkeypatch):
        for k, v in (
            ("TPU_RAG_KV_PAGED", "1"),
            ("TPU_RAG_INTERLEAVE_PREFILL", "1"),
            ("TPU_RAG_PREFILL_CHUNK_TOKENS", "48"),
            ("TPU_RAG_WINDOW_TOKEN_BUDGET", "96"),
        ):
            monkeypatch.setenv(k, v)
        cfg = AppConfig.from_env()
        assert cfg.engine.interleave_prefill is True
        assert cfg.engine.prefill_chunk_tokens == 48
        assert cfg.engine.window_token_budget == 96
        monkeypatch.setenv("TPU_RAG_INTERLEAVE_PREFILL", "2")
        with pytest.raises(ValueError, match="TPU_RAG_INTERLEAVE_PREFILL"):
            AppConfig.from_env()
        monkeypatch.setenv("TPU_RAG_INTERLEAVE_PREFILL", "1")
        monkeypatch.setenv("TPU_RAG_WINDOW_TOKEN_BUDGET", "-1")
        with pytest.raises(ValueError, match="WINDOW_TOKEN_BUDGET"):
            AppConfig.from_env()
        monkeypatch.setenv("TPU_RAG_WINDOW_TOKEN_BUDGET", "96")
        monkeypatch.setenv("TPU_RAG_PREFILL_CHUNK_TOKENS", "0")
        with pytest.raises(ValueError, match="PREFILL_CHUNK_TOKENS"):
            AppConfig.from_env()
        # cross-field: interleave without the paged arena is rejected
        monkeypatch.setenv("TPU_RAG_PREFILL_CHUNK_TOKENS", "48")
        monkeypatch.setenv("TPU_RAG_KV_PAGED", "0")
        with pytest.raises(ValueError, match="requires kv_paged"):
            AppConfig.from_env()


# ---------------------------------------------------------------------------
# tensor parallel
# ---------------------------------------------------------------------------


class TestChunkedPrefillTP:
    def test_tp2_byte_identity(self, setup):
        """Mixed windows over the HEAD-SHARDED arena: tp=2 interleaved
        streams match tp=1 interleaved and tp=2 phase-separated — the tp
        split must not change a single token of any stream."""
        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg, params = setup
        reqs = [(1, PROMPTS[2], 8), (2, PROMPTS[0], 8)]
        base_tp1 = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=INTER, dtypes=FP32), reqs,
        )
        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        sharded = shard_llama_params(params, ctx)
        base_tp2 = drain(
            ContinuousEngine(
                cfg, sharded, sampling=GREEDY, engine_config=PAGED,
                dtypes=FP32, mesh=ctx,
            ),
            reqs,
        )
        eng = ContinuousEngine(
            cfg, sharded, sampling=GREEDY, engine_config=INTER,
            dtypes=FP32, mesh=ctx,
        )
        inter_tp2 = drain(eng, reqs)
        assert inter_tp2 == base_tp2 == base_tp1
        assert "mixed" in eng.ledger.state()["kinds"], "vacuous tp=2 identity"
