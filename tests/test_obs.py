"""Observability subsystem tests (ISSUE 2): metrics registry primitives,
strict Prometheus exposition checking, span-tree tracing through a real
``/generate``, and JSON-snapshot ↔ exposition equivalence."""

import re
import time

import jax
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.obs import tracing
from rag_llm_k8s_tpu.server.app import RagService, create_app

FP32 = DTypePolicy.fp32()


class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode("utf-8", "replace")


# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------


class TestPrimitives:
    def test_counter_monotonic(self):
        reg = obs_metrics.MetricsRegistry()
        c = reg.counter("rag_test_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_callback_counter_rejects_inc(self):
        reg = obs_metrics.MetricsRegistry()
        c = reg.counter("rag_cb_total", fn=lambda: 7)
        assert c.value == 7.0
        with pytest.raises(RuntimeError):
            c.inc()

    def test_gauge_and_broken_probe(self):
        reg = obs_metrics.MetricsRegistry()
        g = reg.gauge("rag_level")
        g.set(4)
        g.dec()
        assert g.value == 3.0
        boom = reg.gauge("rag_boom", fn=lambda: 1 / 0)
        assert boom.value == 0.0  # a broken probe must not 500 /metrics

    def test_kind_conflict_rejected(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("rag_x_total")
        with pytest.raises(ValueError):
            reg.gauge("rag_x_total")

    def test_log_buckets_strictly_increasing(self):
        for b in (obs_metrics.LATENCY_BUCKETS, obs_metrics.REQUEST_BUCKETS,
                  obs_metrics.TOKEN_LATENCY_BUCKETS,
                  obs_metrics.log_buckets(0.001, 10, 1.07)):
            assert all(b2 > b1 for b1, b2 in zip(b, b[1:]))

    def test_histogram_buckets_and_quantile(self):
        reg = obs_metrics.MetricsRegistry()
        h = reg.histogram("rag_h_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0):
            h.observe(v)
        counts, hsum, count = h.snapshot()
        assert counts == (1, 2, 1, 0) and count == 4
        assert hsum == pytest.approx(6.05)
        # p50 lands in the (0.1, 1.0] bucket, p99 in (1.0, 10.0]
        assert 0.1 <= h.quantile(0.5) <= 1.0
        assert 1.0 <= h.quantile(0.99) <= 10.0
        assert reg.histogram("rag_empty_seconds").quantile(0.5) is None

    def test_histogram_snapshot_diff_quantile(self):
        """bench.py's per-pass windowing: quantile over a snapshot diff."""
        reg = obs_metrics.MetricsRegistry()
        h = reg.histogram("rag_win_seconds", buckets=(1.0, 2.0, 4.0))
        h.observe(0.5)
        before = h.snapshot()
        h.observe(3.0)
        h.observe(3.0)
        after = h.snapshot()
        diff = (
            tuple(a - b for a, b in zip(after[0], before[0])),
            after[1] - before[1],
            after[2] - before[2],
        )
        q = h.quantile(0.5, diff)
        assert 2.0 <= q <= 4.0  # the early 0.5 observation is excluded

    def test_labels_are_distinct_series(self):
        reg = obs_metrics.MetricsRegistry()
        fam = reg.labeled_histogram("rag_lab_seconds", buckets=(1.0,))
        fam.labels(stage="a").observe(0.5)
        fam.labels(stage="b").observe(0.5)
        fam.labels(stage="a").observe(0.5)
        assert fam.labels(stage="a").count == 2
        assert fam.labels(stage="b").count == 1

    def test_label_value_escaping_keeps_one_line(self):
        """Newline/quote/backslash in a label value must become two-char
        escapes — a raw newline would split the sample line and make a
        scraper reject the whole exposition."""
        reg = obs_metrics.MetricsRegistry()
        reg.labeled_counter("rag_esc_total").labels(k='a"b\\c\nd').inc()
        text = reg.render_prometheus()
        (line,) = [l for l in text.splitlines() if l.startswith("rag_esc_total{")]
        assert line == 'rag_esc_total{k="a\\"b\\\\c\\nd"} 1.0'


class TestTracingUnit:
    def test_span_nesting_and_finish(self):
        tr = tracing.start_trace("t1")
        with tracing.span("outer"):
            with tracing.span("inner"):
                time.sleep(0.002)
        buf = tracing.TraceBuffer(capacity=2)
        tree = tracing.finish_trace(tr, buf)
        assert tracing.current_trace() is None
        assert tree["trace_id"] == "t1"
        (outer,) = tree["spans"]
        assert outer["name"] == "outer"
        (inner,) = outer["spans"]
        assert inner["name"] == "inner"
        assert inner["duration_ms"] <= outer["duration_ms"]
        assert len(buf) == 1

    def test_ring_buffer_capacity(self):
        buf = tracing.TraceBuffer(capacity=3)
        for i in range(5):
            buf.add({"trace_id": str(i)})
        ids = [t["trace_id"] for t in buf.list()]
        assert ids == ["2", "3", "4"]
        assert [t["trace_id"] for t in buf.list(limit=1)] == ["4"]
        # non-positive limits mean "no trim", never "drop the oldest"
        assert len(buf.list(limit=0)) == 3
        assert len(buf.list(limit=-1)) == 3

    def test_span_without_trace_is_noop(self):
        with tracing.span("orphan") as sp:
            assert sp is None


# ---------------------------------------------------------------------------
# HTTP-level: exposition, traces, healthz (one tiny service for the module)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served():
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)
    engine = InferenceEngine(
        llama_cfg,
        init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
        sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
        engine_config=EngineConfig(prompt_buckets=(128, 512), max_batch_size=2,
                                   max_seq_len=640),
        dtypes=FP32,
    )
    encoder = EncoderRunner(
        enc_cfg,
        init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32,), max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size)
    svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
    svc.ready = True
    vec = encoder.encode([ByteTokenizer().encode("tiny doc text")])[0]
    store.add([vec], [{"filename": "f", "chunk_id": 0, "text": "kernels tile queries"}])
    client = create_app(svc).test_client()
    # one answered query so every request-path metric has data
    r = client.post("/query", json={"prompt": "what?"})
    assert r.status_code == 200, r.get_json()
    return svc, client


# strict exposition grammar (text format 0.0.4, the subset we emit)
_HELP_RE = re.compile(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* \S.*$")
_TYPE_RE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)$")
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*")*\})?'
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|NaN)$"
)


def _parse_samples(text):
    """{(name, labelstr): float} for every sample line, strict-checked."""
    samples = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP"):
            assert _HELP_RE.match(line), line
            continue
        if line.startswith("# TYPE"):
            assert _TYPE_RE.match(line), line
            continue
        assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
        head, val = line.rsplit(" ", 1)
        name, brace, labels = head.partition("{")
        samples[(name, brace + labels)] = (
            float(val) if val != "+Inf" else float("inf")
        )
    return samples


class TestExposition:
    def test_strict_line_format_and_required_families(self, served):
        _, client = served
        r = client.get("/metrics")
        assert r.status_code == 200
        assert r.content_type.startswith("text/plain")
        text = r.get_data(as_text=True)
        samples = _parse_samples(text)
        names = {n for n, _ in samples}
        # the acceptance-criteria families
        assert "rag_request_duration_seconds_bucket" in names
        assert "rag_request_duration_seconds_count" in names
        assert "rag_decode_inter_token_seconds_bucket" in names
        assert "rag_batch_occupancy" in names
        assert "rag_compile_seconds_total" in names
        # engine + legacy families still scrape from the SAME endpoint
        assert "tpu_rag_engine_generate_calls" in names
        assert "tpu_rag_index_vectors" in names
        assert "rag_coalesce_wait_seconds_bucket" in names
        assert "rag_time_to_first_token_seconds_count" in names
        assert "rag_stage_duration_seconds_bucket" in names
        # the query actually landed in the request histogram and compile
        # time was attributed
        assert samples[("rag_request_duration_seconds_count", "")] >= 1
        assert samples[("rag_compile_seconds_total", "")] > 0
        # every serving stage observed — including assemble/detokenize,
        # which have no timings key and observe at their span sites
        for stage in ("retrieve", "assemble", "generate", "detokenize"):
            key = ("rag_stage_duration_seconds_count", f'{{stage="{stage}"}}')
            assert samples[key] >= 1, stage
        # stage counts track request counts one-for-one (a fallback path
        # must never double-count a stage for one request)
        n_req = samples[("rag_request_duration_seconds_count", "")]
        for stage in ("assemble", "detokenize"):
            key = ("rag_stage_duration_seconds_count", f'{{stage="{stage}"}}')
            assert samples[key] == n_req, stage

    def test_histogram_bucket_monotonicity(self, served):
        _, client = served
        text = client.get("/metrics").get_data(as_text=True)
        samples = _parse_samples(text)
        # group bucket series by (family, non-le labels)
        series = {}
        for (name, labels), val in samples.items():
            if not name.endswith("_bucket"):
                continue
            base = name[: -len("_bucket")]
            inner = labels.strip("{}")
            parts = [p for p in inner.split(",") if p and not p.startswith("le=")]
            le = next(p for p in inner.split(",") if p.startswith("le="))
            le_val = le[4:-1]
            le_f = float("inf") if le_val == "+Inf" else float(le_val)
            series.setdefault((base, tuple(parts)), []).append((le_f, val))
        assert series, "no histogram series found"
        for (base, labels), pts in series.items():
            pts.sort()
            values = [v for _, v in pts]
            assert values == sorted(values), f"{base}{labels} not cumulative"
            assert pts[-1][0] == float("inf")
            # +Inf bucket equals the series count
            count_key = (f"{base}_count", "{" + ",".join(labels) + "}" if labels else "")
            assert pts[-1][1] == samples[count_key], base

    def test_json_snapshot_equivalent_to_exposition(self, served):
        svc, client = served
        body = client.get("/metrics", headers={"Accept": "application/json"}).get_json()
        text = client.get("/metrics").get_data(as_text=True)
        samples = _parse_samples(text)
        # every scalar in the JSON view equals the exposition's value for
        # the same (canonicalized) name, label children summed
        by_name = {}
        for (name, _), val in samples.items():
            if not name.endswith("_bucket"):
                by_name[name] = by_name.get(name, 0.0) + val
        skipped = 0
        for key, val in body.items():
            canon = key if key.startswith("rag_") else f"tpu_rag_{key}"
            if canon not in by_name:
                skipped += 1
                continue
            # callback metrics can tick between the two scrapes (uptime-ish
            # values); everything is monotonic or level, so equality holds
            # for all but actively-changing gauges — require near-equality
            assert by_name[canon] == pytest.approx(val, rel=1e-6, abs=1e-6), key
        assert skipped == 0, "JSON snapshot carries names the exposition lacks"
        # and the legacy JSON keys the seed's consumers read are intact
        assert body["index_vectors"] >= 1
        assert body["engine_generate_calls"] >= 1
        assert "query_seconds_sum" in body

    def test_legacy_prometheus_names_preserved(self, served):
        _, client = served
        text = client.get("/metrics").get_data(as_text=True)
        samples = _parse_samples(text)
        assert samples[("tpu_rag_index_vectors", "")] >= 1
        assert samples[("tpu_rag_engine_generate_calls", "")] >= 1


class TestTracedGenerate:
    def test_span_tree_matches_timings(self, served):
        _, client = served
        r = client.post("/generate", json={"prompt": "what do kernels do?",
                                           "trace": True})
        assert r.status_code == 200, r.get_json()
        body = r.get_json()
        # trace is additive: the timings contract is untouched (chip_ms /
        # goodput_frac are the goodput ledger's per-request attribution —
        # ISSUE 14; cost_usd joins them only when a chip-hour price is set)
        assert set(body["timings"]) == {
            "tokenize_ms", "embed_retrieve_ms", "generate_ms", "total_ms",
            "chip_ms", "goodput_frac",
        }
        tree = body["trace"]
        names = [s["name"] for s in tree["spans"]]
        assert names == ["retrieve", "assemble", "generate", "detokenize"]
        # ordering: spans start in pipeline order and do not regress
        starts = [s["start_ms"] for s in tree["spans"]]
        assert starts == sorted(starts)
        # nesting: the retrieve stage carries its synthesized interior
        retrieve = tree["spans"][0]
        inner = [s["name"] for s in retrieve.get("spans", [])]
        assert inner == ["tokenize", "embed_knn"]
        for child in retrieve["spans"]:
            assert child["start_ms"] >= retrieve["start_ms"] - 5.0
            assert (child["start_ms"] + child["duration_ms"]
                    <= retrieve["start_ms"] + retrieve["duration_ms"] + 5.0)
        # the acceptance contract: stage durations sum to ~total_ms
        stage_sum = sum(s["duration_ms"] for s in tree["spans"])
        assert stage_sum == pytest.approx(body["timings"]["total_ms"], rel=0.05)

    def test_untraced_response_has_no_trace_key(self, served):
        _, client = served
        body = client.post("/query", json={"prompt": "again"}).get_json()
        assert "trace" not in body

    def test_debug_traces_ring(self, served, monkeypatch):
        # /debug/traces follows the uniform 403-unless-armed contract
        # since the flight-recorder round (tests/test_flight.py pins the
        # contract across every /debug route; arming here exercises the
        # served payload)
        svc, client = served
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        assert client.get("/debug/traces").status_code == 403
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        n_before = len(svc.traces)
        client.post("/query", json={"prompt": "ring me"})
        r = client.get("/debug/traces")
        assert r.status_code == 200
        traces = r.get_json()["traces"]
        assert len(traces) == n_before + 1
        last = traces[-1]
        assert last["attrs"]["prompt"].startswith("ring me")
        assert {s["name"] for s in last["spans"]} >= {"retrieve", "generate"}
        limited = client.get("/debug/traces?limit=1").get_json()["traces"]
        assert len(limited) == 1


class TestHealthz:
    def test_fleet_segmentation_fields(self, served):
        _, client = served
        body = client.get("/healthz").get_json()
        assert body["status"] == "ok"
        assert body["uptime_s"] >= 0
        assert body["version"]
        assert body["engine_mode"] == "one-shot"
        assert body["device_platform"] == "cpu"
        assert body["device_count"] >= 1


class TestProfileRoute:
    def test_seconds_validation(self, served):
        _, client = served
        r = client.post("/profile", json={"seconds": -1})
        assert r.status_code == 400
        r = client.post("/profile", json={"seconds": 1e9})
        assert r.status_code == 400


class TestCoalesceWaitHistogram:
    def test_coalescer_observes_item_wait(self):
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        reg = obs_metrics.MetricsRegistry()
        hist = reg.histogram("rag_coalesce_wait_seconds")
        co = Coalescer(lambda xs: [x * 2 for x in xs], max_batch=4, max_wait_ms=1.0)
        co.wait_histogram = hist
        try:
            assert co.submit(21) == 42
            assert hist.count >= 1
            assert hist.sum >= 0.0
        finally:
            co.shutdown()


class TestOneShotEngineInstrumentation:
    def test_generate_feeds_histograms(self, served):
        svc, _ = served
        reg = svc.metrics
        gen = reg.histogram("rag_generate_duration_seconds")
        assert gen.count >= 1  # the fixture's query went through generate
        itl = reg.labeled_histogram("rag_decode_inter_token_seconds")
        assert itl.labels(mode="oneshot_est").count >= 1
        events = reg.counter("rag_compile_events_total")
        assert events.value >= 1
