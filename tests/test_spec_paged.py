"""Speculative decoding in the continuous paged engine (ISSUE 13).

The load-bearing contract is BYTE-IDENTICAL streams between the paged
engine with speculation ON and OFF — greedy AND seeded sampling — across
mixed-length admission groups, mid-flight admission, EOS mid-verify-
window, prefixed admissions, budget clamps, the slot ladder's top, pool
preemption and tp=2. Speculation may only change how many tokens a sync
window retires, never which tokens. Everything else here is the host
half's unit surface (prompt-lookup drafting, the adaptive-K controller,
the acceptance math) and bookkeeping (stats, zero leaked blocks).

``TestSmoke`` is the `make spec-smoke` lane; the chaos interactions
(decode fault mid-verify, preemption of a speculating row, both tp=1 and
tp=2) ride `make chaos` in tests/test_resilience.py::TestSpecChaos.
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    PrefixCacheConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.sampling import accept_drafts
from rag_llm_k8s_tpu.engine.speculative import (
    adaptive_draft_len,
    fold_acceptance,
    prompt_lookup_draft,
)
from rag_llm_k8s_tpu.models.llama import init_llama_params

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=10)
PAGED = EngineConfig(
    prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64,
    kv_paged=True, kv_block_size=16,
)
SPEC = dataclasses.replace(PAGED, spec_paged=True, spec_paged_tokens=4)
# repeat-heavy prompts so prompt-lookup actually fires (the RAG shape:
# answers quote their context), plus shapes that exercise mixed buckets
PROMPTS = [
    [3, 17, 42, 3, 17, 42, 3, 17],
    [5, 5, 8],
    [11] * 12,
    [2, 9, 2, 9, 2, 9, 2],
]


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params


def drain(eng, reqs, seeds=None):
    """admit_many + step-to-completion → {rid: tokens}; asserts zero
    leaked blocks on the way out."""
    results = {}
    outs = eng.admit_many([
        (rid, p, mn, None if seeds is None else seeds[i])
        for i, (rid, p, mn) in enumerate(reqs)
    ])
    for (rid, _, _), res in zip(reqs, outs):
        if isinstance(res, BaseException):
            raise res
        _, fin = res
        if fin is not None:
            results[rid] = fin
    for _ in range(300):
        for rid, toks in eng.step():
            results[rid] = toks
        if not eng.has_active():
            break
    assert eng.kv_pool.blocks_in_use() == 0
    return results


# ---------------------------------------------------------------------------
# host half: drafting + adaptive controller + acceptance math
# ---------------------------------------------------------------------------


class TestSpeculativeHelpers:
    def test_prompt_lookup_finds_last_occurrence(self):
        #          0  1  2  3  4  5  6  7
        h = [7, 8, 9, 1, 7, 8, 3, 7, 8]
        # trailing bigram (7, 8): the LAST earlier occurrence ends at
        # index 5, so the continuation is [3, 7] (k=2)
        assert prompt_lookup_draft(h, 2, 2) == [3, 7]
        assert prompt_lookup_draft(h, 2, 1) == [3]

    def test_prompt_lookup_truncates_at_frontier(self):
        h = [1, 2, 3, 1, 2]
        # gram (1, 2) recurs ending at index 1; only one token follows
        assert prompt_lookup_draft(h, 2, 4) == [3, 1, 2]
        # the frontier's own gram never matches itself (j < n-1)
        assert prompt_lookup_draft([4, 5, 6], 2, 4) == []

    def test_prompt_lookup_degenerate_inputs(self):
        assert prompt_lookup_draft([], 2, 4) == []
        assert prompt_lookup_draft([1, 2], 2, 4) == []
        assert prompt_lookup_draft([1, 2, 3], 2, 0) == []
        assert prompt_lookup_draft([1, 2, 3], 0, 4) == []

    def test_adaptive_draft_len(self):
        assert adaptive_draft_len(None, 8, 0.3) == 8  # optimistic start
        assert adaptive_draft_len(0.1, 8, 0.3) == 1  # degrades to K=1
        assert adaptive_draft_len(1.0, 8, 0.3) == 8
        assert adaptive_draft_len(0.5, 8, 0.3) == 4  # scales with EMA
        assert adaptive_draft_len(0.3, 8, 0.3) >= 1  # floor inclusive

    def test_fold_acceptance(self):
        assert fold_acceptance(None, 0, 0) is None  # no evidence
        assert fold_acceptance(None, 4, 2) == pytest.approx(0.5)
        folded = fold_acceptance(1.0, 4, 0)
        assert 0.0 < folded < 1.0  # decays toward the new observation
        assert fold_acceptance(0.5, 0, 0) == 0.5  # empty window = identity

    def test_accept_drafts_math(self):
        drafts = jnp.asarray([[7, 8, 9], [7, 8, 9], [7, 8, 9]], jnp.int32)
        targets = jnp.asarray(
            [[7, 8, 9, 4], [7, 5, 9, 4], [7, 8, 9, 4]], jnp.int32
        )
        nd = jnp.asarray([3, 3, 2], jnp.int32)
        m, emitted = accept_drafts(drafts, targets, nd)
        # row 0: all 3 accepted, bonus target 4 at plane 3
        # row 1: mismatch at plane 1 → m=1, correction 5 at plane 1
        # row 2: only 2 offered → m=2, correction target 9 at plane 2
        assert list(np.asarray(m)) == [3, 1, 2]
        e = np.asarray(emitted)
        assert list(e[0, :4]) == [7, 8, 9, 4]
        assert list(e[1, :2]) == [7, 5]
        assert list(e[2, :3]) == [7, 8, 9]


# ---------------------------------------------------------------------------
# byte identity (the correctness gate)
# ---------------------------------------------------------------------------


class TestSmoke:
    """`make spec-smoke`: paged greedy + seeded-sampled streams with
    speculation ON are byte-identical to speculation OFF on the tiny
    config — mixed-length admission groups and mid-flight admission —
    and verify steps actually fire (the identity must not be vacuous)."""

    def test_greedy_mixed_batch_byte_identity(self, setup):
        cfg, params = setup
        reqs = [(i + 1, p, 10) for i, p in enumerate(PROMPTS)]
        base = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32), reqs,
        )
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=SPEC, dtypes=FP32
        )
        spec = drain(eng, reqs)
        assert spec == base
        assert eng.stats.spec_verify_steps > 0, "no verify step ever ran"
        assert eng.stats.spec_drafted_tokens > 0
        assert eng.stats.spec_accepted_tokens > 0, (
            "nothing accepted — the identity above is vacuous"
        )

    @pytest.mark.parametrize("temp", [0.7, 0.01])
    def test_seeded_sampling_mid_flight_byte_identity(self, setup, temp):
        """Seeded sampling: the verify step's targets continue the exact
        (seed, position) key-fold sequence, so sampled streams match
        bit-for-bit. temp=0.7 is the realistic point (a random tiny
        model's sampled stream never repeats, so this pins the ZERO-draft
        / plain-window fallback under sampling); temp=0.01 concentrates
        the distribution until the stream cycles, pinning sampled
        drafting AND acceptance non-vacuously."""
        cfg, params = setup
        samp = SamplingConfig(
            do_sample=True, temperature=temp, top_p=0.9, max_new_tokens=10
        )

        def run(eng_cfg):
            eng = ContinuousEngine(
                cfg, params, sampling=samp, engine_config=eng_cfg,
                dtypes=FP32,
            )
            results = {}
            _, fin = eng.admit(1, PROMPTS[0], 10, seed=123)
            if fin is not None:
                results[1] = fin
            eng.step()
            _, fin = eng.admit(2, PROMPTS[2], 10, seed=7)  # joins mid-flight
            if fin is not None:
                results[2] = fin
            for _ in range(300):
                for rid, toks in eng.step():
                    results[rid] = toks
                if not eng.has_active():
                    break
            assert eng.kv_pool.blocks_in_use() == 0
            return results, eng.stats

        base, _ = run(PAGED)
        spec, stats = run(SPEC)
        assert spec == base
        if temp == 0.01:
            assert stats.spec_drafted_tokens > 0, "vacuous: nothing drafted"
            assert stats.spec_accepted_tokens > 0, "vacuous: nothing accepted"


class TestSpecPaged:
    def test_eos_mid_verify_window_byte_identity(self, setup):
        """An EOS the model emits mid-window must end the stream at the
        same token with speculation on — including when the EOS token is
        itself an ACCEPTED draft."""
        cfg, params = setup
        base_eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED, dtypes=FP32
        )
        ref = drain(base_eng, [(1, PROMPTS[2], 10)])[1]
        # an EOS that fires mid-stream, not at token 0
        idx = next(
            (i for i in range(1, len(ref)) if ref[i] not in ref[:i]),
            len(ref) - 1,
        )
        cfg_eos = dataclasses.replace(cfg, eos_token_ids=(ref[idx],))
        reqs = [(1, PROMPTS[2], 10), (2, PROMPTS[0], 10)]
        base = drain(
            ContinuousEngine(cfg_eos, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32), reqs,
        )
        assert 0 < len(base[1]) < 10, "EOS never fired mid-stream — vacuous"
        spec = drain(
            ContinuousEngine(cfg_eos, params, sampling=GREEDY,
                             engine_config=SPEC, dtypes=FP32), reqs,
        )
        assert spec == base

    def test_budget_clamp_byte_identity(self, setup):
        """max_new smaller than the draft width: the drafter clamps to the
        remaining budget and the stream still cuts at exactly max_new."""
        cfg, params = setup
        reqs = [(1, PROMPTS[2], 3), (2, PROMPTS[0], 2)]
        base = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32), reqs,
        )
        spec = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=SPEC, dtypes=FP32), reqs,
        )
        assert spec == base
        assert all(len(t) <= 3 for t in spec.values())

    def test_slot_ladder_top_byte_identity(self, setup):
        """Rows decoding to the very top of the slot ladder: the drafter
        clamps so the accepted frontier can't overrun Tmax, and junk
        verify lanes past the table park in the NULL block instead of
        clipping into the last logical block."""
        cfg, params = setup
        tight = dataclasses.replace(
            PAGED, prompt_buckets=(16,), max_seq_len=32, max_batch_size=2
        )
        tight_spec = dataclasses.replace(
            tight, spec_paged=True, spec_paged_tokens=4
        )
        reqs = [(1, [11] * 12, 40), (2, [2, 9, 2, 9, 2, 9, 2], 40)]
        base = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=tight, dtypes=FP32), reqs,
        )
        spec = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=tight_spec, dtypes=FP32), reqs,
        )
        assert spec == base

    def test_prefixed_admission_byte_identity(self, setup):
        """Prefix-cache admissions speculate too: the draft corpus starts
        at the suffix and grows with the emitted stream; streams stay
        byte-identical to spec-off prefixed admissions."""
        cfg0 = LlamaConfig.tiny(vocab_size=128)
        params = init_llama_params(jax.random.PRNGKey(0), cfg0, FP32)
        pc = PrefixCacheConfig(
            enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
            suffix_buckets=(16,), hbm_budget_mb=64,
        )
        ec = EngineConfig(
            prompt_buckets=(64,), max_batch_size=2, speculative="off",
            max_seq_len=128, prefix_cache=pc,
        )
        oneshot = InferenceEngine(
            cfg0, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
            engine_config=ec, dtypes=FP32,
        )
        rng = np.random.default_rng(9)
        head = [cfg0.bos_token_id] + list(map(int, rng.integers(3, 120, 7)))
        chunk = list(map(int, rng.integers(3, 120, 11)))
        suffix = list(map(int, rng.integers(3, 120, 6)))
        segments = [("head:spec", head), ("chunk:spec", chunk)]

        def run(spec_on):
            eng_cfg = dataclasses.replace(
                ec, kv_paged=True, kv_block_size=16, spec_paged=spec_on,
                spec_paged_tokens=4,
            )
            cont = ContinuousEngine(
                cfg0, params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
                engine_config=eng_cfg, dtypes=FP32,
            )
            cp = oneshot.prefix_cache.prefix_for(segments)
            _, fin = cont.admit_prefixed(1, suffix, cp, max_new=8)
            outs = {}
            while cont.has_active():
                for r, toks in cont.step():
                    outs[r] = toks
            return fin if fin is not None else outs[1]

        assert run(True) == run(False)

    def test_preemption_of_speculating_rows_byte_identity(self, setup):
        """A pool sized for half the batch's growth forces mid-decode
        preemption WHILE rows speculate: resubmission (prompt + emitted)
        still reproduces the spec-off streams, zero leaked blocks."""
        cfg, params = setup
        want = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED, dtypes=FP32),
            [(i + 1, p, 40) for i, p in enumerate(PROMPTS)],
        )
        tight = dataclasses.replace(SPEC, kv_pool_blocks=8)
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=tight, dtypes=FP32
        )
        sched = ContinuousScheduler(eng)
        try:
            outs = [None] * len(PROMPTS)
            errs = [None] * len(PROMPTS)

            def run(i):
                try:
                    outs[i] = sched.submit(
                        PROMPTS[i], max_new_tokens=40, timeout=300
                    )
                except BaseException as e:  # noqa: BLE001
                    errs[i] = e

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(PROMPTS))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert errs == [None] * len(PROMPTS), errs
            assert outs == [want[i + 1] for i in range(len(PROMPTS))]
            assert eng.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()

    def test_verify_routing_is_throughput_gated(self, setup):
        """decode_sync_steps > 1: a verify window is ONE device call, so
        a lone quoting row must not collapse the k-step amortization for
        non-drafting batchmates — the router compares the EMA-expected
        verify yield against the plain window's k × rows (and any draft
        wins at k == 1, where the plain call retires 1/row anyway)."""
        cfg, params = setup
        sync4 = dataclasses.replace(SPEC, decode_sync_steps=4)
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=sync4, dtypes=FP32
        )
        eng.admit_many([(1, PROMPTS[0], 10, None), (2, PROMPTS[1], 10, None)])
        # one fresh (optimistic) row drafting 4 of 2 active: 1+4+1 = 6
        # expected < 2 rows × 4 steps = 8 → plain window wins
        assert eng._verify_worthwhile({0: [1, 2, 3, 4], 1: []}) is False
        # both rows drafting clears the bar: (1+4) × 2 = 10 >= 8
        assert eng._verify_worthwhile({0: [1, 2, 3, 4], 1: [5, 6, 7, 8]})
        # a low-EMA row's drafts are discounted by their measured odds
        eng.slots[0].spec_ema = 0.1
        eng.slots[1].spec_ema = 0.1
        assert eng._verify_worthwhile(
            {0: [1, 2, 3, 4], 1: [5, 6, 7, 8]}
        ) is False
        while eng.has_active():
            eng.step()
        assert eng.kv_pool.blocks_in_use() == 0
        # k == 1: any draft routes to verify
        eng1 = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=SPEC, dtypes=FP32
        )
        eng1.admit_many([(1, PROMPTS[0], 10, None)])
        assert eng1._verify_worthwhile({0: [1]}) is True
        while eng1.has_active():
            eng1.step()

    def test_sync_steps_gt1_byte_identity(self, setup):
        """Speculation composes with multi-step sync windows: whichever
        way each window routes, streams match spec-off at the same
        decode_sync_steps."""
        cfg, params = setup
        reqs = [(i + 1, p, 12) for i, p in enumerate(PROMPTS)]
        base = drain(
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(PAGED, decode_sync_steps=3),
                dtypes=FP32,
            ),
            reqs,
        )
        spec = drain(
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(SPEC, decode_sync_steps=3),
                dtypes=FP32,
            ),
            reqs,
        )
        assert spec == base

    def test_adaptive_controller_wired_to_slots(self, setup):
        """Verify windows fold measured acceptance into the slot EMA and
        the next window's draft length reads it (integration of the unit
        surface above with the live engine)."""
        cfg, params = setup
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=SPEC, dtypes=FP32
        )
        row, fin = eng.admit(1, [11] * 12, 10)
        assert fin is None
        for _ in range(4):
            if not eng.has_active():
                break
            eng.step()
        if eng.has_active():
            slot = eng.slots[row]
            if eng.stats.spec_verify_steps:
                assert slot.spec_ema is not None
                k = adaptive_draft_len(
                    slot.spec_ema, eng.spec_K, eng.spec_min_accept
                )
                assert 1 <= k <= eng.spec_K
        while eng.has_active():
            eng.step()
        assert eng.kv_pool.blocks_in_use() == 0

    def test_construction_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="requires kv_paged"):
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(
                    PAGED, kv_paged=False, spec_paged=True
                ),
                dtypes=FP32,
            )
        with pytest.raises(ValueError, match="spec_paged_tokens"):
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(SPEC, spec_paged_tokens=0),
                dtypes=FP32,
            )
        with pytest.raises(ValueError, match="spec_paged_min_accept"):
            ContinuousEngine(
                cfg, params, sampling=GREEDY,
                engine_config=dataclasses.replace(
                    SPEC, spec_paged_min_accept=1.5
                ),
                dtypes=FP32,
            )

    def test_env_round_trip(self, monkeypatch):
        for k, v in (
            ("TPU_RAG_SPEC_PAGED", "1"),
            ("TPU_RAG_SPEC_PAGED_TOKENS", "5"),
            ("TPU_RAG_SPEC_PAGED_MIN_ACCEPT", "0.4"),
        ):
            monkeypatch.setenv(k, v)
        cfg = AppConfig.from_env()
        assert cfg.engine.spec_paged is True
        assert cfg.engine.spec_paged_tokens == 5
        assert cfg.engine.spec_paged_min_accept == pytest.approx(0.4)
        monkeypatch.setenv("TPU_RAG_SPEC_PAGED", "2")
        with pytest.raises(ValueError, match="TPU_RAG_SPEC_PAGED"):
            AppConfig.from_env()
        monkeypatch.setenv("TPU_RAG_SPEC_PAGED", "1")
        monkeypatch.setenv("TPU_RAG_SPEC_PAGED_MIN_ACCEPT", "1.5")
        with pytest.raises(ValueError, match="MIN_ACCEPT"):
            AppConfig.from_env()


# ---------------------------------------------------------------------------
# tensor parallel
# ---------------------------------------------------------------------------


class TestSpecPagedTP:
    def test_tp2_byte_identity(self, setup):
        """Speculation over the HEAD-SHARDED arena: tp=2 verify steps
        (the chunked paged kernels under the serving partition specs)
        stream byte-identically to tp=1 spec-on and to tp=2 spec-off,
        with zero leaked blocks — the tp split must not change a single
        accepted token."""
        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg, params = setup
        reqs = [(1, PROMPTS[0], 8), (2, PROMPTS[2], 8)]
        base_tp1 = drain(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=SPEC, dtypes=FP32), reqs,
        )
        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        sharded = shard_llama_params(params, ctx)
        eng_off = ContinuousEngine(
            cfg, sharded, sampling=GREEDY, engine_config=PAGED,
            dtypes=FP32, mesh=ctx,
        )
        base_tp2 = drain(eng_off, reqs)
        eng = ContinuousEngine(
            cfg, sharded, sampling=GREEDY, engine_config=SPEC,
            dtypes=FP32, mesh=ctx,
        )
        spec_tp2 = drain(eng, reqs)
        assert spec_tp2 == base_tp2 == base_tp1
        assert eng.stats.spec_accepted_tokens > 0, "vacuous tp=2 identity"
