"""Flash-attention kernel numerics (interpret mode on CPU) vs dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.ops.attention import attention_xla, flash_attention


def _problem(seed, B=2, S=256, H=4, K=2, hd=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd), dtype)
    k = jax.random.normal(ks[1], (B, S, K, hd), dtype)
    v = jax.random.normal(ks[2], (B, S, K, hd), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _problem(0)
        got = flash_attention(q, k, v, causal=causal, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_left_pad_window(self):
        """kv_start models the engine's left-padded rows; valid rows match."""
        q, k, v = _problem(1)
        B, S = q.shape[:2]
        kv_start = jnp.array([0, 37], jnp.int32)
        got = flash_attention(q, k, v, kv_start=kv_start, causal=True, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, kv_start=kv_start, causal=True)
        valid = (jnp.arange(S)[None, :] >= kv_start[:, None])[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, got, 0)),
            np.asarray(jnp.where(valid, want, 0)),
            rtol=2e-4,
            atol=2e-5,
        )

    def test_kv_len_frontier(self):
        q, k, v = _problem(2)
        kv_len = jnp.array([256, 150], jnp.int32)
        got = flash_attention(q, k, v, kv_len=kv_len, causal=False, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, kv_len=kv_len, causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_gqa_head_mapping(self):
        q, k, v = _problem(3, H=8, K=2)
        got = flash_attention(q, k, v, causal=True, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_rectangular_blocks(self):
        q, k, v = _problem(4, S=128)
        got = flash_attention(q, k, v, causal=True, bq=32, bk=128, interpret=True)
        want = attention_xla(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)
