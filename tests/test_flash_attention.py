"""Flash-attention kernel numerics (interpret mode on CPU) vs dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import set_mesh

from rag_llm_k8s_tpu.ops.attention import attention_xla, flash_attention


def _problem(seed, B=2, S=256, H=4, K=2, hd=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd), dtype)
    k = jax.random.normal(ks[1], (B, S, K, hd), dtype)
    v = jax.random.normal(ks[2], (B, S, K, hd), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _problem(0)
        got = flash_attention(q, k, v, causal=causal, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_left_pad_window(self):
        """kv_start models the engine's left-padded rows; valid rows match."""
        q, k, v = _problem(1)
        B, S = q.shape[:2]
        kv_start = jnp.array([0, 37], jnp.int32)
        got = flash_attention(q, k, v, kv_start=kv_start, causal=True, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, kv_start=kv_start, causal=True)
        valid = (jnp.arange(S)[None, :] >= kv_start[:, None])[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, got, 0)),
            np.asarray(jnp.where(valid, want, 0)),
            rtol=2e-4,
            atol=2e-5,
        )

    def test_kv_len_frontier(self):
        q, k, v = _problem(2)
        kv_len = jnp.array([256, 150], jnp.int32)
        got = flash_attention(q, k, v, kv_len=kv_len, causal=False, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, kv_len=kv_len, causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_gqa_head_mapping(self):
        q, k, v = _problem(3, H=8, K=2)
        got = flash_attention(q, k, v, causal=True, bq=64, bk=64, interpret=True)
        want = attention_xla(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_rectangular_blocks(self):
        q, k, v = _problem(4, S=128)
        got = flash_attention(q, k, v, causal=True, bq=32, bk=128, interpret=True)
        want = attention_xla(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


class TestDecodeAttention:
    """Fused decode kernel (interpret mode) vs dense oracle."""

    def _problem(self, seed, B=2, H=8, K=2, T=256, hd=64, L=3, dtype=jnp.float32):
        from rag_llm_k8s_tpu.ops.attention import decode_attention, decode_attention_xla

        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (B, 1, H, hd), dtype)
        k_cache = jax.random.normal(ks[1], (L, B, K, T, hd), dtype)
        v_cache = jax.random.normal(ks[2], (L, B, K, T, hd), dtype)
        return q, k_cache, v_cache, decode_attention, decode_attention_xla

    def test_matches_oracle_per_layer(self):
        """Layer indirection: the kernel must read exactly layer ``lay``'s
        slice of the stacked cache (scalar-prefetched block indexing)."""
        q, kc, vc, kernel, oracle = self._problem(0)
        T = kc.shape[3]
        kv_start = jnp.array([0, 37], jnp.int32)
        kv_len = jnp.array([T, 150], jnp.int32)
        for lay in range(kc.shape[0]):
            got = kernel(q, kc, vc, kv_start, kv_len, jnp.int32(lay), bk=64, interpret=True)
            want = oracle(q, kc, vc, kv_start, kv_len, jnp.int32(lay))
            np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_single_valid_slot(self):
        """Window of width 1 (first decode after a 1-token prompt)."""
        q, kc, vc, kernel, oracle = self._problem(1)
        kv_start = jnp.array([5, 200], jnp.int32)
        kv_len = kv_start + 1
        lay = jnp.int32(1)
        got = kernel(q, kc, vc, kv_start, kv_len, lay, bk=64, interpret=True)
        want = oracle(q, kc, vc, kv_start, kv_len, lay)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_mha_no_grouping(self):
        q, kc, vc, kernel, oracle = self._problem(2, H=4, K=4)
        T = kc.shape[3]
        kv_start = jnp.array([0, 0], jnp.int32)
        kv_len = jnp.array([T, T // 2], jnp.int32)
        lay = jnp.int32(2)
        got = kernel(q, kc, vc, kv_start, kv_len, lay, bk=128, interpret=True)
        want = oracle(q, kc, vc, kv_start, kv_len, lay)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


class TestDecodeAttentionQ8:
    """int8-KV decode kernel (interpret mode) vs its oracle and vs bf16."""

    def _problem(self, seed, B=2, H=8, K=2, T=256, hd=64, L=3):
        from rag_llm_k8s_tpu.ops.attention import quantize_kv

        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (B, 1, H, hd), jnp.float32)
        k_cache = jax.random.normal(ks[1], (L, B, K, T, hd), jnp.float32)
        v_cache = jax.random.normal(ks[2], (L, B, K, T, hd), jnp.float32)
        kq, kscale = quantize_kv(k_cache)
        vq, vscale = quantize_kv(v_cache)
        return q, k_cache, v_cache, kq, kscale, vq, vscale

    def test_quantize_kv_roundtrip(self):
        from rag_llm_k8s_tpu.ops.attention import quantize_kv

        x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 64), jnp.float32)
        q, s = quantize_kv(x)
        assert q.dtype == jnp.int8 and s.shape == (4, 8)
        deq = q.astype(jnp.float32) * s[..., None]
        # per-element error bounded by half a quantization step
        assert float(jnp.max(jnp.abs(deq - x) - s[..., None] / 2)) <= 1e-6

    def test_kernel_matches_q8_oracle_per_layer(self):
        """The int8 kernel and the int8 XLA oracle see the SAME quantized
        payload, so they must agree to kernel-numerics tolerance."""
        from rag_llm_k8s_tpu.ops.attention import (
            decode_attention_q8,
            decode_attention_xla_q8,
        )

        q, _, _, kq, kscale, vq, vscale = self._problem(0)
        T = kq.shape[3]
        kv_start = jnp.array([0, 37], jnp.int32)
        kv_len = jnp.array([T, 150], jnp.int32)
        for lay in range(kq.shape[0]):
            got = decode_attention_q8(
                q, kq, vq, kscale, vscale, kv_start, kv_len, jnp.int32(lay),
                bk=64, interpret=True,
            )
            want = decode_attention_xla_q8(
                q, kq, vq, kscale, vscale, kv_start, kv_len, jnp.int32(lay)
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
            )

    def test_q8_close_to_bf16_attention(self):
        """End result stays close to the unquantized cache path: int8 KV is
        a ~0.4%-per-element perturbation, and softmax-weighted averaging
        keeps the output error at the same order."""
        from rag_llm_k8s_tpu.ops.attention import (
            decode_attention_q8,
            decode_attention_xla,
        )

        q, kc, vc, kq, kscale, vq, vscale = self._problem(1)
        T = kc.shape[3]
        kv_start = jnp.array([3, 0], jnp.int32)
        kv_len = jnp.array([T - 5, T], jnp.int32)
        lay = jnp.int32(1)
        got = decode_attention_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, lay, bk=64, interpret=True
        )
        want = decode_attention_xla(q, kc, vc, kv_start, kv_len, lay)
        err = float(
            jnp.linalg.norm(got - want) / (jnp.linalg.norm(want) + 1e-9)
        )
        assert err < 0.02, f"relative error vs bf16 cache: {err}"

    def test_single_valid_slot(self):
        from rag_llm_k8s_tpu.ops.attention import (
            decode_attention_q8,
            decode_attention_xla_q8,
        )

        q, _, _, kq, kscale, vq, vscale = self._problem(2)
        kv_start = jnp.array([5, 200], jnp.int32)
        kv_len = kv_start + 1
        lay = jnp.int32(2)
        got = decode_attention_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, lay, bk=64, interpret=True
        )
        want = decode_attention_xla_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, lay
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_uninitialized_scale_slots_do_not_poison(self):
        """Slots past the frontier carry NaN scales (as donated device
        memory can); the masked dequant must still produce finite output."""
        from rag_llm_k8s_tpu.ops.attention import (
            decode_attention_q8,
            decode_attention_xla_q8,
        )

        q, _, _, kq, kscale, vq, vscale = self._problem(3)
        T = kq.shape[3]
        valid = jnp.arange(T)[None, None, None, :] < 100
        kscale = jnp.where(valid, kscale, jnp.nan)
        vscale = jnp.where(valid, vscale, jnp.nan)
        kv_start = jnp.array([0, 10], jnp.int32)
        kv_len = jnp.array([100, 100], jnp.int32)
        lay = jnp.int32(0)
        got = decode_attention_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, lay, bk=64, interpret=True
        )
        assert bool(jnp.all(jnp.isfinite(got)))
        want = decode_attention_xla_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, lay
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )


class TestModelPallasPath:
    """Full LlamaModel with Pallas attention (interpret) vs the XLA oracle
    model — proves the kernels are THE serving path, not an island."""

    def _models_and_inputs(self, mesh=None):
        from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
        from rag_llm_k8s_tpu.models.llama import (
            LlamaModel,
            init_llama_params,
            make_kv_cache,
            mask_window,
        )

        fp32 = DTypePolicy.fp32()
        # head counts divisible by tp=4 so the shard_map path engages on mesh8
        cfg = LlamaConfig.tiny()
        cfg = type(cfg)(**{**cfg.__dict__, "num_heads": 8, "num_kv_heads": 8})
        params = init_llama_params(jax.random.PRNGKey(0), cfg, fp32)
        oracle = LlamaModel(cfg, fp32, attn_impl="xla")
        pallas = LlamaModel(cfg, fp32, attn_impl="pallas_interpret", mesh=mesh)
        return cfg, params, oracle, pallas, fp32, make_kv_cache, mask_window

    def _run_prefill_decode(self, model, cfg, params, make_kv_cache, tokens, pad_mask, T):
        from rag_llm_k8s_tpu.models.llama import mask_window

        B, S = tokens.shape
        cache = make_kv_cache(cfg, B, T, jnp.float32)
        kv_start, _ = mask_window(pad_mask)
        pos = jnp.clip(jnp.cumsum(pad_mask, axis=-1) - 1, 0)
        real_len = jnp.sum(pad_mask, axis=-1)
        plog, cache = model.apply(
            {"params": params}, tokens, pos, cache,
            kv_start, jnp.full((B,), S, jnp.int32), jnp.int32(0),
        )
        # one decode step: feed the last real token again at slot S
        dlog, _ = model.apply(
            {"params": params}, tokens[:, -1:], real_len[:, None].astype(jnp.int32),
            cache, kv_start, jnp.full((B,), S + 1, jnp.int32), jnp.int32(S),
        )
        return plog, dlog

    def test_prefill_and_decode_parity(self):
        cfg, params, oracle, pallas, fp32, mkc, mw = self._models_and_inputs()
        B, S, T = 2, 64, 128
        tokens = jax.random.randint(jax.random.PRNGKey(3), (B, S), 3, cfg.vocab_size)
        pad_mask = jnp.ones((B, S), jnp.int32).at[1, :17].set(0)  # row 1 left-padded
        p_ref, d_ref = self._run_prefill_decode(oracle, cfg, params, mkc, tokens, pad_mask, T)
        p_got, d_got = self._run_prefill_decode(pallas, cfg, params, mkc, tokens, pad_mask, T)
        valid = pad_mask.astype(bool)[:, :, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, p_got, 0)),
            np.asarray(jnp.where(valid, p_ref, 0)),
            rtol=5e-4, atol=5e-4,
        )
        np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref), rtol=5e-4, atol=5e-4)

    def test_shard_map_tp_parity(self, mesh8):
        """Pallas kernels under shard_map over the tp axis of an 8-virtual-device
        mesh match the unsharded oracle — the multi-chip serving attention."""
        cfg, params, oracle, pallas, fp32, mkc, mw = self._models_and_inputs(mesh=mesh8.mesh)
        B, S, T = 2, 64, 128
        tokens = jax.random.randint(jax.random.PRNGKey(4), (B, S), 3, cfg.vocab_size)
        pad_mask = jnp.ones((B, S), jnp.int32).at[0, :9].set(0)
        p_ref, d_ref = self._run_prefill_decode(oracle, cfg, params, mkc, tokens, pad_mask, T)
        with set_mesh(mesh8.mesh):
            p_got, d_got = self._run_prefill_decode(pallas, cfg, params, mkc, tokens, pad_mask, T)
        valid = pad_mask.astype(bool)[:, :, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, p_got, 0)),
            np.asarray(jnp.where(valid, p_ref, 0)),
            rtol=5e-4, atol=5e-4,
        )
        np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref), rtol=5e-4, atol=5e-4)


class TestChunkPrefillAttention:
    """Cache-wide chunked-prefill kernel (interpret mode) vs dense oracle,
    and the chunked path's equivalence to single-shot prefill."""

    def _problem(self, seed, B=2, S=64, H=8, K=2, T=256, hd=64, L=3, dtype=jnp.float32):
        from rag_llm_k8s_tpu.ops.attention import (
            chunk_attention_xla,
            chunk_prefill_attention,
        )

        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (B, S, H, hd), dtype)
        k_cache = jax.random.normal(ks[1], (L, B, K, T, hd), dtype)
        v_cache = jax.random.normal(ks[2], (L, B, K, T, hd), dtype)
        return q, k_cache, v_cache, chunk_prefill_attention, chunk_attention_xla

    def test_matches_oracle_per_layer_and_offset(self):
        q, kc, vc, kernel, oracle = self._problem(0)
        S, T = q.shape[1], kc.shape[3]
        kv_start = jnp.array([0, 23], jnp.int32)
        for wi in (0, 64, T - S):  # first chunk, interior chunk, last chunk
            kv_len = jnp.full((2,), wi + S, jnp.int32)
            for lay in range(kc.shape[0]):
                got = kernel(q, kc, vc, kv_start, kv_len, jnp.int32(lay),
                             jnp.int32(wi), bq=32, bk=64, interpret=True)
                want = oracle(q, kc, vc, kv_start, kv_len, jnp.int32(lay), jnp.int32(wi))
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
                )

    def test_first_chunk_equals_flash_prefill(self):
        """At write_index=0 with kv_len=S the chunked kernel must reproduce
        plain causal prefill over the fresh K/V (written into the cache)."""
        q, kc, vc, kernel, _ = self._problem(1, S=128)
        B, S, H, hd = q.shape
        K = kc.shape[2]
        lay = 1
        fresh_k = jax.random.normal(jax.random.PRNGKey(7), (B, S, K, hd))
        fresh_v = jax.random.normal(jax.random.PRNGKey(8), (B, S, K, hd))
        kc = kc.at[lay, :, :, :S].set(fresh_k.transpose(0, 2, 1, 3))
        vc = vc.at[lay, :, :, :S].set(fresh_v.transpose(0, 2, 1, 3))
        kv_start = jnp.array([0, 5], jnp.int32)
        kv_len = jnp.full((B,), S, jnp.int32)
        got = kernel(q, kc, vc, kv_start, kv_len, jnp.int32(lay), jnp.int32(0),
                     bq=64, bk=64, interpret=True)
        want = flash_attention(q, fresh_k, fresh_v, kv_start, kv_len,
                               causal=True, bq=64, bk=64, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


class TestChunkPrefillAttentionQ8:
    """int8-KV chunked-prefill kernel (interpret mode) vs its q8 oracle and
    vs the bf16 cache path — the long-prompt int8 serving path must never
    materialize a bf16 layer slice, so the kernel dequantizes in epilogues."""

    def _problem(self, seed, B=2, S=64, H=8, K=2, T=256, hd=64, L=3):
        from rag_llm_k8s_tpu.ops.attention import quantize_kv

        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
        k_cache = jax.random.normal(ks[1], (L, B, K, T, hd), jnp.float32)
        v_cache = jax.random.normal(ks[2], (L, B, K, T, hd), jnp.float32)
        kq, kscale = quantize_kv(k_cache)
        vq, vscale = quantize_kv(v_cache)
        return q, k_cache, v_cache, kq, kscale, vq, vscale

    def test_matches_q8_oracle_per_layer_and_offset(self):
        from rag_llm_k8s_tpu.ops.attention import (
            chunk_attention_xla_q8,
            chunk_prefill_attention_q8,
        )

        q, _, _, kq, kscale, vq, vscale = self._problem(0)
        S, T = q.shape[1], kq.shape[3]
        kv_start = jnp.array([0, 23], jnp.int32)
        for wi in (0, 64, T - S):  # first chunk, interior chunk, last chunk
            kv_len = jnp.full((2,), wi + S, jnp.int32)
            for lay in range(kq.shape[0]):
                got = chunk_prefill_attention_q8(
                    q, kq, vq, kscale, vscale, kv_start, kv_len,
                    jnp.int32(lay), jnp.int32(wi), bq=32, bk=64, interpret=True,
                )
                want = chunk_attention_xla_q8(
                    q, kq, vq, kscale, vscale, kv_start, kv_len,
                    jnp.int32(lay), jnp.int32(wi),
                )
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
                )

    def test_q8_close_to_bf16_chunk_path(self):
        from rag_llm_k8s_tpu.ops.attention import (
            chunk_attention_xla,
            chunk_prefill_attention_q8,
        )

        q, kc, vc, kq, kscale, vq, vscale = self._problem(1)
        S, T = q.shape[1], kc.shape[3]
        wi, lay = 64, jnp.int32(1)
        kv_start = jnp.array([3, 0], jnp.int32)
        kv_len = jnp.full((2,), wi + S, jnp.int32)
        got = chunk_prefill_attention_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, lay, jnp.int32(wi),
            bq=32, bk=64, interpret=True,
        )
        want = chunk_attention_xla(q, kc, vc, kv_start, kv_len, lay, jnp.int32(wi))
        err = float(jnp.linalg.norm(got - want) / (jnp.linalg.norm(want) + 1e-9))
        assert err < 0.02, f"relative error vs bf16 cache: {err}"

    def test_uninitialized_scale_slots_do_not_poison(self):
        """Slots past the frontier can hold NaN scales (donated device
        memory): the window mask must zero them before they touch the
        accumulator."""
        from rag_llm_k8s_tpu.ops.attention import (
            chunk_attention_xla_q8,
            chunk_prefill_attention_q8,
        )

        q, _, _, kq, kscale, vq, vscale = self._problem(2)
        S, T = q.shape[1], kq.shape[3]
        wi = 64
        kv_len = jnp.full((2,), wi + S, jnp.int32)
        kv_start = jnp.zeros((2,), jnp.int32)
        nan_tail = jnp.where(jnp.arange(T)[None, None, None, :] >= wi + S,
                             jnp.nan, 1.0)
        kscale = kscale * nan_tail
        vscale = vscale * nan_tail
        got = chunk_prefill_attention_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, jnp.int32(0),
            jnp.int32(wi), bq=32, bk=64, interpret=True,
        )
        assert not bool(jnp.any(jnp.isnan(got))), "NaN scales leaked"
        want = chunk_attention_xla_q8(
            q, kq, vq, kscale, vscale, kv_start, kv_len, jnp.int32(0), jnp.int32(wi)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )
