"""Weight-only int8 quantization (serving path).

The reference serves fp32 on CPU (/root/reference/llm/rag.py:24,172); this
framework's serving default is bf16, with an optional weight-only int8 mode
(``EngineConfig.weight_quant="int8"``) that halves the HBM bytes every
decode step streams — measured +18-35% decode throughput on v5e — and fits
the reference's actual 8B model (download_model.py:5) on ONE 16 GB chip.

Covered here: quantization math, logits parity vs bf16, both engine paths,
tied + untied heads, composition with projection fusion, the streaming int8
loader, and TP sharding of the quantized tree on the 8-virtual-device mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import traverse_util

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    MeshConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.core.mesh import make_mesh
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine
from rag_llm_k8s_tpu.engine.engine import InferenceEngine, maybe_quantize_params
from rag_llm_k8s_tpu.models.llama import (
    LlamaModel,
    fuse_llama_params,
    init_llama_params,
    make_kv_cache,
    quantize_llama_params,
)
from rag_llm_k8s_tpu.models.loader import convert_hf_state_dict
from rag_llm_k8s_tpu.parallel.sharding import (
    is_quant_leaf,
    llama_param_specs,
    make_streaming_put,
    shard_llama_params,
)

DT = DTypePolicy()


def tiny(tied: bool) -> LlamaConfig:
    cfg = LlamaConfig.tiny()
    if cfg.tie_word_embeddings != tied:
        cfg = dataclasses.replace(cfg, tie_word_embeddings=tied)
    return cfg


def hf_state_dict(cfg: LlamaConfig, seed: int = 0) -> dict:
    """Random numpy state dict at the HF [out, in] layout."""
    r = np.random.default_rng(seed)
    D, H, K, hd, F, V = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
        cfg.intermediate_size, cfg.vocab_size,
    )
    n = lambda *s: (r.standard_normal(s) * 0.02).astype(np.float32)  # noqa: E731
    sd = {"model.embed_tokens.weight": n(V, D), "model.norm.weight": np.ones(D, np.float32)}
    if not cfg.tie_word_embeddings:
        sd["lm_head.weight"] = n(V, D)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        sd[p + "self_attn.q_proj.weight"] = n(H * hd, D)
        sd[p + "self_attn.k_proj.weight"] = n(K * hd, D)
        sd[p + "self_attn.v_proj.weight"] = n(K * hd, D)
        sd[p + "self_attn.o_proj.weight"] = n(D, H * hd)
        sd[p + "mlp.gate_proj.weight"] = n(F, D)
        sd[p + "mlp.up_proj.weight"] = n(F, D)
        sd[p + "mlp.down_proj.weight"] = n(D, F)
        sd[p + "input_layernorm.weight"] = np.ones(D, np.float32)
        sd[p + "post_attention_layernorm.weight"] = np.ones(D, np.float32)
    return sd


class TestQuantizeMath:
    def test_roundtrip_error_bounded(self):
        """Per-channel symmetric int8: dequantized error <= scale/2 per
        element, i.e. <= max|w_channel|/254."""
        r = np.random.default_rng(3)
        w = jnp.asarray(r.standard_normal((8, 16, 32)) * 0.1, jnp.float32)
        tree = {"layers": {"attn": {"wq": {"kernel": w}}, "mlp": {}}}
        q = quantize_llama_params({**tree, "lm_head": jnp.zeros((4, 8))})
        kq = q["layers"]["attn"]["wq"]["kernel_q"]
        scale = q["layers"]["attn"]["wq"]["qscale"]
        assert kq.dtype == jnp.int8 and scale.dtype == jnp.float32
        assert scale.shape == (8, 32)
        deq = kq.astype(jnp.float32) * scale[:, None, :]
        err = jnp.abs(deq - w)
        assert float(jnp.max(err - scale[:, None, :] / 2)) <= 1e-6

    def test_scales_match_channel_maxima(self):
        w = jnp.asarray([[1.0, -0.5], [-2.0, 0.25]], jnp.float32)  # [in=2, out=2]
        q = quantize_llama_params(
            {"layers": {"attn": {}, "mlp": {}}, "lm_head": w}
        )
        # lm_head [D, V] quantizes over axis 0 -> per-vocab-column scales
        np.testing.assert_allclose(
            np.asarray(q["lm_head_scale"]), [2.0 / 127, 0.5 / 127], rtol=1e-6
        )

    def test_zero_weights_do_not_divide_by_zero(self):
        q = quantize_llama_params(
            {"layers": {"attn": {}, "mlp": {}}, "lm_head": jnp.zeros((4, 8))}
        )
        assert int(jnp.max(jnp.abs(q["lm_head_q"]))) == 0
        assert np.all(np.isfinite(np.asarray(q["lm_head_scale"])))


@pytest.mark.parametrize("tied", [False, True])
class TestLogitsParity:
    def test_quantized_logits_close(self, tied):
        cfg = tiny(tied)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        qparams = quantize_llama_params(params)
        B, S = 2, 16
        cache = make_kv_cache(cfg, B, S, DT.compute_dtype)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        win = jnp.zeros((B,), jnp.int32), jnp.full((B,), S, jnp.int32)
        ref, _ = LlamaModel(cfg, DT, attn_impl="xla").apply(
            {"params": params}, tokens, pos, cache, *win, jnp.int32(0)
        )
        got, _ = LlamaModel(cfg, DT, attn_impl="xla", quantized=True).apply(
            {"params": qparams}, tokens, pos, cache, *win, jnp.int32(0)
        )
        rel = float(jnp.linalg.norm(ref - got) / (jnp.linalg.norm(ref) + 1e-9))
        cos = float(
            jnp.sum(ref * got) / (jnp.linalg.norm(ref) * jnp.linalg.norm(got) + 1e-9)
        )
        assert rel < 0.08, f"relative logit error {rel}"
        assert cos > 0.995, f"logit cosine {cos}"

    def test_greedy_tokens_match_bf16(self, tied):
        """On the tiny model, 3.5-bit-equivalent noise does not flip greedy
        argmaxes — generated ids are identical to the bf16 engine's."""
        cfg = tiny(tied)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        prompts = [[cfg.bos_token_id, 5, 7, 9]] * 2
        outs = {}
        for wq in ("bf16", "int8"):
            eng = InferenceEngine(
                cfg, params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
                engine_config=EngineConfig(
                    prompt_buckets=(16,), max_batch_size=2, weight_quant=wq
                ),
                dtypes=DT,
            )
            outs[wq] = eng.generate(prompts)
        assert outs["bf16"] == outs["int8"]


class TestEnginePlumbing:
    def test_maybe_quantize_validates_mode(self):
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        with pytest.raises(ValueError, match="weight_quant"):
            maybe_quantize_params(params, EngineConfig(weight_quant="fp8"))

    def test_already_quantized_tree_passes_through(self):
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        q = quantize_llama_params(params)
        out, quantized = maybe_quantize_params(q, EngineConfig(weight_quant="bf16"))
        assert quantized and out is q

    def test_fusion_composes_with_quantization(self):
        """fuse -> quantize keeps per-channel scales across the concat: the
        fused+quantized engine generates the same greedy ids as unfused."""
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        prompts = [[cfg.bos_token_id, 11, 3]]
        ids = {}
        for fuse in (False, True):
            eng = InferenceEngine(
                cfg, params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
                engine_config=EngineConfig(
                    prompt_buckets=(16,), max_batch_size=1,
                    weight_quant="int8", fuse_matmuls=fuse,
                ),
                dtypes=DT,
            )
            assert eng.model.quantized
            assert eng.model.fused_qkv == fuse
            ids[fuse] = eng.generate(prompts)
        assert ids[False] == ids[True]

    def test_continuous_engine_serves_quantized(self):
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        eng = ContinuousEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=2, max_seq_len=64,
                weight_quant="int8",
            ),
            dtypes=DT,
        )
        assert eng.model.quantized
        _, finished = eng.admit(0, [cfg.bos_token_id, 4, 2], 6)
        assert finished is None
        results = {}
        for _ in range(8):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert len(results[0]) == 6


class TestEnvWiring:
    def test_weight_quant_env_override(self):
        from rag_llm_k8s_tpu.core.config import AppConfig

        cfg = AppConfig.from_env({"TPU_RAG_WEIGHT_QUANT": "int8"})
        assert cfg.engine.weight_quant == "int8"
        assert AppConfig.from_env({}).engine.weight_quant == "bf16"
        with pytest.raises(ValueError, match="TPU_RAG_WEIGHT_QUANT"):
            AppConfig.from_env({"TPU_RAG_WEIGHT_QUANT": "fp8"})

    def test_kv_quant_env_override(self):
        from rag_llm_k8s_tpu.core.config import AppConfig

        cfg = AppConfig.from_env({"TPU_RAG_KV_QUANT": "int8"})
        assert cfg.engine.kv_quant == "int8"
        assert AppConfig.from_env({}).engine.kv_quant == "bf16"
        with pytest.raises(ValueError, match="TPU_RAG_KV_QUANT"):
            AppConfig.from_env({"TPU_RAG_KV_QUANT": "fp4"})


class TestLoaderInt8:
    def test_streaming_layout_and_dtypes(self):
        cfg = tiny(False)
        tree = convert_hf_state_dict(hf_state_dict(cfg), cfg, DT, quant="int8")
        flat = traverse_util.flatten_dict(tree)
        assert tree["layers"]["attn"]["wq"]["kernel_q"].dtype == jnp.int8
        assert tree["layers"]["attn"]["wq"]["qscale"].dtype == jnp.float32
        assert tree["lm_head_q"].dtype == jnp.int8
        assert tree["embedding"].dtype == DT.param_dtype  # untied: gather-only
        assert tree["final_norm"]["scale"].dtype == DT.param_dtype
        for path in flat:
            if is_quant_leaf(path):
                assert flat[path].dtype in (jnp.int8, jnp.float32)

    def test_tied_embedding_quantizes(self):
        cfg = tiny(True)
        tree = convert_hf_state_dict(hf_state_dict(cfg), cfg, DT, quant="int8")
        assert tree["embedding_q"].dtype == jnp.int8
        assert tree["embedding_scale"].shape == (cfg.vocab_size,)
        assert "embedding" not in tree and "lm_head" not in tree

    def test_loader_tree_matches_model_structure(self):
        """The streamed int8 tree applies cleanly to LlamaModel(quantized)."""
        cfg = tiny(False)
        tree = convert_hf_state_dict(hf_state_dict(cfg), cfg, DT, quant="int8")
        model = LlamaModel(cfg, DT, attn_impl="xla", quantized=True)
        B, S = 1, 8
        cache = make_kv_cache(cfg, B, S, DT.compute_dtype)
        logits, _ = model.apply(
            {"params": tree},
            jnp.zeros((B, S), jnp.int32),
            jnp.broadcast_to(jnp.arange(S), (B, S)),
            cache,
            jnp.zeros((B,), jnp.int32),
            jnp.full((B,), S, jnp.int32),
            jnp.int32(0),
        )
        assert logits.shape == (B, S, cfg.vocab_size)
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_loader_int8_matches_post_hoc_quantization(self):
        """Host-side numpy quantization == on-device jnp quantization."""
        cfg = tiny(False)
        sd = hf_state_dict(cfg)
        streamed = convert_hf_state_dict(sd, cfg, DT, quant="int8")
        bf16 = convert_hf_state_dict(sd, cfg, DT)
        posthoc = quantize_llama_params(bf16)
        a = traverse_util.flatten_dict(streamed)
        b = traverse_util.flatten_dict(posthoc)
        assert a.keys() == b.keys()
        for path in a:
            if path[-1] in ("kernel_q", "lm_head_q"):
                # bf16 path quantizes from bf16-rounded weights; allow ±1 step
                diff = np.abs(
                    np.asarray(a[path], np.int32) - np.asarray(b[path], np.int32)
                )
                assert diff.max() <= 1, path


class TestKVQuant:
    """int8 KV cache (EngineConfig.kv_quant) through the one-shot engine."""

    def test_greedy_matches_bf16_cache(self):
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        prompts = [[cfg.bos_token_id, 5, 7, 9], [cfg.bos_token_id, 3]]
        outs = {}
        for kvq in ("bf16", "int8"):
            eng = InferenceEngine(
                cfg, params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
                engine_config=EngineConfig(
                    prompt_buckets=(16,), max_batch_size=2, kv_quant=kvq
                ),
                dtypes=DT,
            )
            outs[kvq] = eng.generate(prompts)
        assert outs["bf16"] == outs["int8"]

    def test_composes_with_weight_quant(self):
        """Both quantizations together — the full int8 serving mode."""
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        eng = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=1,
                weight_quant="int8", kv_quant="int8",
            ),
            dtypes=DT,
        )
        out = eng.generate([[cfg.bos_token_id, 11, 3]])
        assert len(out[0]) == 8

    def test_chunked_prefill_with_int8_cache(self):
        """Long prompts prefill through the quantized cache chunk by chunk
        (layer-slice dequant + bf16 chunk kernel) and keep decoding."""
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)

        def build(kvq):
            return InferenceEngine(
                cfg, params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=4),
                engine_config=EngineConfig(
                    prompt_buckets=(16,), max_batch_size=1, max_seq_len=64,
                    max_chunked_prompt=64, kv_quant=kvq,
                ),
                dtypes=DT,
            )

        long_prompt = [cfg.bos_token_id] + list(range(3, 40))
        want = build("bf16").generate([long_prompt])
        got = build("int8").generate([long_prompt])
        assert want == got

    def test_cache_arrays_are_int8(self):
        from rag_llm_k8s_tpu.models.llama import make_kv_cache

        cache = make_kv_cache(LlamaConfig.tiny(), 2, 32, quant="int8")
        assert cache.k.dtype == jnp.int8 and cache.v.dtype == jnp.int8
        assert cache.k_scale.dtype == jnp.float32
        assert cache.k_scale.shape == cache.k.shape[:-1]
        bf16 = make_kv_cache(LlamaConfig.tiny(), 2, 32)
        assert bf16.k_scale is None

    def test_row_frontier_int8_write_matches_bf16(self):
        """The per-row scatter write path (continuous batching's layout)
        quantizes correctly: prefill then one row-frontier decode step at
        DIFFERENT per-row frontiers matches the bf16-cache model closely,
        and the scale planes carry the written slots. (The continuous
        engine itself still rejects int8 KV; this pins the model-level
        support it will adopt.)"""
        from rag_llm_k8s_tpu.models.llama import LlamaModel, make_kv_cache

        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        B, S, T = 2, 4, 32
        tokens = jnp.array([[7, 5, 3, 2], [9, 4, 6, 8]], jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        logits = {}
        for kvq in ("bf16", "int8"):
            model = LlamaModel(cfg, DT, attn_impl="xla", kv_quant=kvq)
            step = LlamaModel(
                cfg, DT, attn_impl="xla", kv_quant=kvq, row_frontier=True
            )
            cache = make_kv_cache(cfg, B, T, DT.compute_dtype, quant=kvq)
            _, cache = model.apply(
                {"params": params}, tokens, pos, cache,
                jnp.zeros((B,), jnp.int32), jnp.full((B,), S, jnp.int32),
                jnp.int32(0),
            )
            wi = jnp.array([4, 2], jnp.int32)  # per-row frontiers differ
            lg, cache = step.apply(
                {"params": params},
                jnp.array([[11], [13]], jnp.int32),
                wi[:, None],
                cache,
                jnp.zeros((B,), jnp.int32),
                wi + 1,
                wi,
            )
            logits[kvq] = lg
            if kvq == "int8":
                assert cache.k.dtype == jnp.int8
                # each row's scale slot at ITS OWN frontier was written
                assert float(cache.k_scale[0, 0, 0, 4]) > 0
                assert float(cache.k_scale[0, 1, 0, 2]) > 0
        rel = float(
            jnp.linalg.norm(logits["int8"] - logits["bf16"])
            / (jnp.linalg.norm(logits["bf16"]) + 1e-9)
        )
        assert rel < 0.05, rel

    def test_continuous_engine_int8_kv_greedy_parity(self):
        """Continuous batching over an int8 cache: slot-based decode with
        per-row frontiers must produce the same greedy ids as the one-shot
        int8-KV engine."""
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        sampling = SamplingConfig(do_sample=False, max_new_tokens=6)
        ec = EngineConfig(
            prompt_buckets=(16,), max_batch_size=2, max_seq_len=64,
            kv_quant="int8",
        )
        oracle = InferenceEngine(cfg, params, sampling=sampling, engine_config=ec, dtypes=DT)
        prompts = [[cfg.bos_token_id, 5, 7, 9], [cfg.bos_token_id, 3]]
        want = [oracle.generate([p])[0] for p in prompts]
        eng = ContinuousEngine(cfg, params, sampling=sampling, engine_config=ec, dtypes=DT)
        assert eng._cache[0].dtype == jnp.int8 and len(eng._cache) == 4
        for rid, p in enumerate(prompts):
            _, fin = eng.admit(rid, p, sampling.max_new_tokens)
            assert fin is None
        results = {}
        for _ in range(sampling.max_new_tokens + 1):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert [results[i] for i in range(len(prompts))] == want

    def test_continuous_int8_kv_mid_flight_admission(self):
        """A request joining mid-generation writes its int8 prompt KV into a
        free slot and completes with the same ids it gets solo."""
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        sampling = SamplingConfig(do_sample=False, max_new_tokens=6)
        ec = EngineConfig(
            prompt_buckets=(16,), max_batch_size=2, max_seq_len=64,
            kv_quant="int8",
        )
        solo = InferenceEngine(
            cfg, params, sampling=sampling, engine_config=ec, dtypes=DT
        ).generate([[cfg.bos_token_id, 8, 6]])[0]
        eng = ContinuousEngine(cfg, params, sampling=sampling, engine_config=ec, dtypes=DT)
        eng.admit(1, [cfg.bos_token_id, 5, 7, 9], sampling.max_new_tokens)
        eng.step()
        eng.step()  # request 1 is two tokens in...
        eng.admit(2, [cfg.bos_token_id, 8, 6], sampling.max_new_tokens)  # ...2 joins
        results = {}
        for _ in range(2 * sampling.max_new_tokens):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert results[2] == solo

    def test_tp_generate_matches_single_device_int8_kv(self):
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        prompts = [[cfg.bos_token_id, 5, 7]] * 2
        mk = lambda mesh_ctx, p: InferenceEngine(  # noqa: E731
            cfg, p,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=2, kv_quant="int8"
            ),
            dtypes=DT,
            mesh=mesh_ctx,
        )
        ref = mk(None, params).generate(prompts)
        ctx = make_mesh(MeshConfig(dp=2, sp=1, tp=4))
        got = mk(ctx, shard_llama_params(params, ctx)).generate(prompts)
        assert ref == got


class TestQuantTP:
    """Quantized tree over the 8-virtual-device mesh (dp2 x tp4)."""

    def test_specs_shard_kernels_and_column_scales(self):
        cfg = tiny(False)
        ctx = make_mesh(MeshConfig(dp=2, sp=1, tp=4))
        q = quantize_llama_params(init_llama_params(jax.random.PRNGKey(0), cfg, DT))
        flat = traverse_util.flatten_dict(llama_param_specs(q, ctx))
        assert flat[("layers", "attn", "wq", "kernel_q")][-1] == "tp"
        assert flat[("layers", "attn", "wq", "qscale")][-1] == "tp"
        assert flat[("layers", "attn", "wo", "kernel_q")][1] == "tp"
        # row-parallel scale is per-OUTPUT-channel -> replicated
        assert all(ax is None for ax in flat[("layers", "attn", "wo", "qscale")])

    def test_tp_generate_matches_single_device(self):
        cfg = tiny(False)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        prompts = [[cfg.bos_token_id, 5, 7]] * 2
        ref = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=2, weight_quant="int8"
            ),
            dtypes=DT,
        ).generate(prompts)
        ctx = make_mesh(MeshConfig(dp=2, sp=1, tp=4))
        placed = shard_llama_params(quantize_llama_params(params), ctx)
        got = InferenceEngine(
            cfg, placed,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=2, weight_quant="int8"
            ),
            dtypes=DT,
            mesh=ctx,
        ).generate(prompts)
        assert ref == got

    def test_rope_headcut_sharding_is_exact(self):
        """Root-cause pin for the two tp parity failures above (they predate
        PR 6): tiny()'s K=2 kv heads do not tile tp=4, so the flat k/v
        projection output — column-sharded over tp by the param specs —
        reshapes to a SUB-head-sharded ``[B, S, K, hd]`` layout, and with
        ``dp`` also populated this container's jax 0.4.x GSPMD miscompiles
        the slice+concat rotate-by-halves RoPE over it: the jitted forward
        returns wrong VALUES (~0.3 absolute on these logits) while eager is
        exact. ``replicate_undividable_heads`` (models/llama.py) degrades
        off-tile head projections to replicated before RoPE; this asserts
        the jit-under-mesh logits match the single-device forward within
        sharded-accumulation noise (measured ≤ 6e-3 at the default bf16
        policy; the miscompile is ~50x that), so removing the guard fails
        here on values — not just on downstream greedy tokens."""
        cfg = tiny(False)
        ctx = make_mesh(MeshConfig(dp=2, sp=1, tp=4))
        assert cfg.num_kv_heads % ctx.tp != 0  # the miscompile's precondition
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        B, S = 2, 8
        tokens = jnp.asarray(
            np.random.default_rng(7).integers(3, cfg.vocab_size, (B, S)),
            jnp.int32,
        )
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        kv0 = jnp.zeros((B,), jnp.int32)
        kvl = jnp.full((B,), S, jnp.int32)
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        ref, _ = jax.jit(LlamaModel(cfg, DT).apply)(
            {"params": params}, tokens, pos, cache, kv0, kvl, jnp.int32(0)
        )
        placed = shard_llama_params(params, ctx)
        rep = ctx.replicated
        model_tp = LlamaModel(cfg, DT, mesh=ctx.mesh)
        got, _ = jax.jit(model_tp.apply)(
            {"params": placed},
            *(jax.device_put(a, rep) for a in (tokens, pos)),
            jax.device_put(cache, rep), *(
                jax.device_put(a, rep) for a in (kv0, kvl)
            ), jnp.int32(0),
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.03)

    def test_streaming_put_preserves_quant_dtypes(self):
        cfg = tiny(True)
        ctx = make_mesh(MeshConfig(dp=2, sp=1, tp=4))
        put = make_streaming_put(ctx, dtype=jnp.bfloat16)
        tree = convert_hf_state_dict(hf_state_dict(cfg), cfg, DT, put=put, quant="int8")
        assert tree["embedding_q"].dtype == jnp.int8
        assert tree["embedding_scale"].dtype == jnp.float32
        assert tree["layers"]["mlp"]["w_down"]["kernel_q"].dtype == jnp.int8
        assert tree["final_norm"]["scale"].dtype == jnp.bfloat16
