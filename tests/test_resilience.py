"""Resilience layer (ISSUE 4): admission control + load shedding, end-to-end
deadlines with mid-decode slot eviction, EngineStateLost recovery behind a
circuit breaker, and the fault-injection harness that makes all of it
provable on CPU. ``make chaos`` runs this file with ``TPU_RAG_FAULTS``
armed; it also runs inside the ordinary tier-1 gate (arming there is
programmatic, so no env is needed)."""

import threading
import time

import jax
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    KVTieringConfig,
    LlamaConfig,
    LookaheadConfig,
    PrefixCacheConfig,
    ResilienceConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.resilience.admission import AdmissionController, AdmissionRejected
from rag_llm_k8s_tpu.resilience.breaker import CircuitBreaker
from rag_llm_k8s_tpu.resilience.deadline import Deadline, DeadlineExceeded
from rag_llm_k8s_tpu.server.app import RagService, create_app

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
ENG_CFG = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    oracle = InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
    )
    return cfg, params, oracle


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# fault harness
# ---------------------------------------------------------------------------
class TestFaults:
    def test_count_based_arming_fires_exactly_n_times(self):
        faults.arm("embed", times=2)
        for _ in range(2):
            with pytest.raises(faults.InjectedFault) as ei:
                faults.maybe_fail("embed")
            assert ei.value.site == "embed"
        faults.maybe_fail("embed")  # disarmed: no-op
        assert faults.armed() == {}

    def test_unknown_site_is_loud(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            # the bad name is the point here  # ragcheck: disable=FAULT-SITE-REGISTRY
            faults.arm("definitely_not_a_site")
        with pytest.raises(ValueError, match="expected >= 1"):
            faults.arm("embed", times=0)

    def test_arm_from_env(self):
        armed = faults.arm_from_env({"TPU_RAG_FAULTS": "decode_step:2, embed"})
        assert armed == {"decode_step": 2, "embed": 1}
        faults.clear()
        # enable-only forms arm nothing
        assert faults.arm_from_env({"TPU_RAG_FAULTS": "1"}) == {}
        assert faults.arm_from_env({}) == {}
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.arm_from_env({"TPU_RAG_FAULTS": "tpyo:1"})

    def test_endpoint_enabled_tracks_env_presence(self):
        assert faults.endpoint_enabled({"TPU_RAG_FAULTS": ""})
        assert not faults.endpoint_enabled({})


# ---------------------------------------------------------------------------
# deadline
# ---------------------------------------------------------------------------
class TestDeadline:
    def test_expiry_and_check(self):
        clk = FakeClock()
        dl = Deadline(100.0, clock=clk)
        assert not dl.expired()
        assert dl.remaining() == pytest.approx(0.1)
        dl.check("retrieve")  # fine
        clk.advance(0.2)
        assert dl.expired()
        with pytest.raises(DeadlineExceeded) as ei:
            dl.check("assemble")
        assert ei.value.stage == "assemble"
        assert dl.wait_timeout() > 0  # floored, never a negative wait

    def test_invalid_budget(self):
        with pytest.raises(ValueError):
            Deadline(0)


# ---------------------------------------------------------------------------
# breaker
# ---------------------------------------------------------------------------
class TestBreaker:
    def test_opens_at_threshold_and_self_heals(self):
        clk = FakeClock()
        b = CircuitBreaker(threshold=3, window_s=100.0, clock=clk)
        b.record_reset()  # t=0
        clk.advance(10.0)
        b.record_reset()  # t=10
        assert not b.open
        assert b.retry_after_s() == 0.0
        clk.advance(10.0)
        b.record_reset()  # t=20: third inside the window -> open
        assert b.open
        assert b.recent_resets() == 3
        # Retry-After counts down to the FIRST reset aging out (t=100)
        assert b.retry_after_s() == pytest.approx(80.0)
        clk.advance(60.0)
        assert b.retry_after_s() == pytest.approx(20.0)
        clk.advance(21.0)  # t=101: the t=0 reset left the window
        assert not b.open
        assert b.recent_resets() == 2

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(threshold=0)
        with pytest.raises(ValueError):
            CircuitBreaker(window_s=0)


# ---------------------------------------------------------------------------
# admission gate
# ---------------------------------------------------------------------------
class TestAdmission:
    def test_queue_cap_rejection_under_concurrent_submits(self):
        gate = AdmissionController(max_concurrency=2, max_queue=3)
        reg = obs_metrics.MetricsRegistry()
        gate.reject_counter = reg.labeled_counter("rag_admission_rejected_total")
        hold = threading.Event()
        outcomes = []
        lock = threading.Lock()

        def run():
            try:
                with gate.admit():
                    hold.wait(timeout=30)
                with lock:
                    outcomes.append("served")
            except AdmissionRejected as e:
                with lock:
                    outcomes.append(e.reason)

        threads = [threading.Thread(target=run) for _ in range(10)]
        for t in threads:
            t.start()
        # settle: 2 active + 3 waiting; the other 5 shed immediately
        for _ in range(200):
            with lock:
                shed = len([o for o in outcomes if o == "queue_full"])
            if gate.active == 2 and gate.waiting == 3 and shed == 5:
                break
            time.sleep(0.01)
        assert gate.active == 2 and gate.queue_depth() == 3
        hold.set()
        for t in threads:
            t.join(timeout=30)
        with lock:
            assert sorted(outcomes) == ["queue_full"] * 5 + ["served"] * 5
        child = gate.reject_counter.labels(reason="queue_full",
                                           tenant="__other__")
        assert child.value == 5
        assert gate.active == 0 and gate.waiting == 0

    def test_fair_share_displaces_the_hog_tenants_newest_waiter(self):
        """One tenant holding every slot AND every queue position cannot
        lock a second tenant out: the under-share arrival displaces the
        hog's newest waiter (shed reason="fair_share"), keeping shed
        attribution on the tenant that caused the pressure."""
        gate = AdmissionController(max_concurrency=2, max_queue=2)
        reg = obs_metrics.MetricsRegistry()
        gate.reject_counter = reg.labeled_counter(
            "rag_admission_rejected_total"
        )
        hold = threading.Event()
        outcomes = []
        lock = threading.Lock()

        def run(tenant):
            try:
                with gate.admit(tenant=tenant):
                    hold.wait(timeout=30)
                with lock:
                    outcomes.append((tenant, "served"))
            except AdmissionRejected as e:
                with lock:
                    outcomes.append((tenant, e.reason))

        hogs = [threading.Thread(target=run, args=("hog",)) for _ in range(4)]
        for t in hogs:
            t.start()
        for _ in range(300):  # settle: 2 hog active + 2 hog queued
            if gate.active == 2 and gate.waiting == 2:
                break
            time.sleep(0.01)
        assert gate.active == 2 and gate.waiting == 2
        small = threading.Thread(target=run, args=("small",))
        small.start()
        for _ in range(300):  # the displaced hog waiter sheds
            with lock:
                shed = [o for o in outcomes if o == ("hog", "fair_share")]
            if shed:
                break
            time.sleep(0.01)
        with lock:
            assert ("hog", "fair_share") in outcomes
        hold.set()
        for t in hogs + [small]:
            t.join(timeout=30)
        with lock:
            assert ("small", "served") in outcomes
            assert outcomes.count(("hog", "fair_share")) == 1
            assert outcomes.count(("hog", "served")) == 3
        child = gate.reject_counter.labels(reason="fair_share", tenant="hog")
        assert child.value == 1
        assert gate.active == 0 and gate.waiting == 0

    def test_over_share_arrival_cannot_displace(self):
        """The displacing tenant must itself be within fair share: a
        FIFTH request from the hog (share = 4/1 = 4, its own count 5)
        sheds plain queue_full — fair-share never helps a hog cut its
        own line."""
        gate = AdmissionController(max_concurrency=2, max_queue=2)
        hold = threading.Event()
        errs = []
        lock = threading.Lock()

        def run():
            try:
                with gate.admit(tenant="hog"):
                    hold.wait(timeout=30)
            except AdmissionRejected as e:
                with lock:
                    errs.append(e.reason)

        hogs = [threading.Thread(target=run) for _ in range(4)]
        for t in hogs:
            t.start()
        for _ in range(300):
            if gate.active == 2 and gate.waiting == 2:
                break
            time.sleep(0.01)
        with pytest.raises(AdmissionRejected) as ei:
            with gate.admit(tenant="hog"):
                pass
        assert ei.value.reason == "queue_full"
        hold.set()
        for t in hogs:
            t.join(timeout=30)
        assert errs == []

    def test_rejection_contract(self):
        gate = AdmissionController(max_concurrency=1, max_queue=0,
                                   retry_after_s=2.5)
        with gate.admit():
            with pytest.raises(AdmissionRejected) as ei:
                with gate.admit():
                    pass
        assert ei.value.status == 429
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after_s == 2.5
        # slot released: admissible again
        with gate.admit():
            pass

    def test_breaker_open_sheds_everything_with_503(self):
        clk = FakeClock()
        b = CircuitBreaker(threshold=1, window_s=50.0, clock=clk)
        gate = AdmissionController(max_concurrency=8, max_queue=8, breaker=b)
        b.record_reset()
        with pytest.raises(AdmissionRejected) as ei:
            with gate.admit():
                pass
        assert ei.value.status == 503
        assert ei.value.reason == "breaker_open"
        assert ei.value.retry_after_s >= 1.0
        clk.advance(51.0)  # breaker heals -> gate admits again
        with gate.admit():
            pass

    def test_deadline_expiry_while_queued(self):
        gate = AdmissionController(max_concurrency=1, max_queue=4)
        clk = FakeClock()
        dl = Deadline(50.0, clock=clk)
        clk.advance(1.0)  # expired before it ever waits
        with gate.admit():
            with pytest.raises(DeadlineExceeded) as ei:
                with gate.admit(deadline=dl):
                    pass
        assert ei.value.stage == "queue"


# ---------------------------------------------------------------------------
# continuous engine: deadline eviction + reset recovery via fault injection
# ---------------------------------------------------------------------------
class TestDeadlineEviction:
    def test_expired_mid_decode_frees_slot_within_a_step(self, tiny):
        cfg, params, _ = tiny
        eng = ContinuousEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=2000),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=4, max_seq_len=2048
            ),
            dtypes=FP32,
        )
        sched = ContinuousScheduler(eng)
        try:
            with pytest.raises(DeadlineExceeded) as ei:
                sched.submit([3, 17, 42], deadline=Deadline(300.0))
            assert ei.value.stage in ("decode", "generate")
            # the zombie's slot must free within one scheduler iteration —
            # poll briefly to absorb the step in flight
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if len(eng.free_slots()) == eng.B:
                    break
                time.sleep(0.02)
            assert len(eng.free_slots()) == eng.B, "evicted row still active"
            # and the scheduler still serves
            out = sched.submit([5, 5, 8], max_new_tokens=4, timeout=120)
            assert isinstance(out, list) and out
        finally:
            sched.shutdown()

    def test_expired_in_queue_is_never_admitted(self, tiny):
        cfg, params, _ = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng)
        try:
            clk = FakeClock()
            dl = Deadline(10.0, clock=clk)
            clk.advance(1.0)  # already expired on arrival
            before = eng.stats.generate_calls
            with pytest.raises(DeadlineExceeded) as ei:
                sched.submit([3, 17, 42], deadline=dl, timeout=30)
            assert ei.value.stage == "queue"
            assert eng.stats.generate_calls == before  # no prefill happened
        finally:
            sched.shutdown()


class TestResetRecovery:
    def test_insert_fault_recovers_via_resubmit(self, tiny):
        """An injected EngineStateLost at admission completes the request
        via resubmission — the caller never sees the fault."""
        cfg, params, oracle = tiny
        want = oracle.generate([[3, 17, 42, 7, 99]])[0]
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        reg = obs_metrics.MetricsRegistry()
        sched.bind_metrics(reg)
        try:
            faults.arm("insert", times=1)
            out = sched.submit([3, 17, 42, 7, 99], timeout=120)
            assert out == want
            assert faults.armed() == {}, "the fault never fired"
            assert reg.counter("rag_engine_resets_total").value == 1
            fam = reg.labeled_counter("rag_inflight_retries_total")
            assert fam.labels(outcome="resubmitted").value == 1
            assert fam.labels(outcome="succeeded").value == 1
            assert fam.labels(outcome="gave_up").value == 0
        finally:
            sched.shutdown()

    def test_decode_fault_recovers_and_preserves_greedy_stream(self, tiny):
        cfg, params, oracle = tiny
        want = oracle.generate([[3, 17, 42, 7, 99]])[0]
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            faults.arm("decode_step", times=1)
            out = sched.submit([3, 17, 42, 7, 99], timeout=120)
            assert out == want
        finally:
            sched.shutdown()

    def test_recovery_with_prompt_at_largest_bucket_stays_exact(self, tiny):
        """A prompt already filling the largest bucket cannot resume as
        prompt+emitted (admit_many would left-truncate the context) — the
        recovery restarts from scratch instead, which is still exact."""
        cfg, params, oracle = tiny
        prompt = [5] * 32  # fills the largest bucket: no room for emitted tokens
        want = oracle.generate([prompt])[0]
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            faults.arm("decode_step", times=1)
            out = sched.submit(prompt, timeout=120)
            assert out == want
        finally:
            sched.shutdown()

    def test_paged_reset_returns_all_blocks_to_the_pool(self, tiny):
        """ISSUE 5 chaos contract: an injected EngineStateLost on the PAGED
        engine recovers via resubmit (greedy stream intact) and hands every
        pool block back — a leak here compounds a reset at a time into
        permanent pool backpressure while /healthz stays green."""
        import dataclasses

        cfg, params, oracle = tiny
        want = oracle.generate([[3, 17, 42, 7, 99]])[0]
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(
                ENG_CFG, kv_paged=True, kv_block_size=16
            ),
            dtypes=FP32,
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            for site in ("insert", "decode_step"):
                faults.arm(site, times=1)
                out = sched.submit([3, 17, 42, 7, 99], timeout=120)
                assert out == want, site
                assert faults.armed() == {}, f"{site} fault never fired"
                assert eng.kv_pool.blocks_in_use() == 0, (
                    site, eng.kv_pool.stats(),
                )
        finally:
            sched.shutdown()

    def test_paged_tp2_reset_returns_all_blocks_to_the_pool(self, tiny):
        """ISSUE 6 chaos contract: the same zero-leak guarantee on the
        HEAD-SHARDED arena — an injected EngineStateLost at tp=2 recovers
        via resubmit with the greedy stream intact, and the (replicated,
        host-side) allocator hands every block back. The tp split must not
        open a leak path reset recovery misses."""
        import dataclasses

        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg, params, oracle = tiny
        want = oracle.generate([[3, 17, 42, 7, 99]])[0]
        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        eng = ContinuousEngine(
            cfg, shard_llama_params(params, ctx), sampling=GREEDY,
            engine_config=dataclasses.replace(
                ENG_CFG, kv_paged=True, kv_block_size=16
            ),
            dtypes=FP32, mesh=ctx,
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            for site in ("insert", "decode_step"):
                faults.arm(site, times=1)
                out = sched.submit([3, 17, 42, 7, 99], timeout=120)
                assert out == want, site
                assert faults.armed() == {}, f"{site} fault never fired"
                assert eng.kv_pool.blocks_in_use() == 0, (
                    site, eng.kv_pool.stats(),
                )
        finally:
            sched.shutdown()

    def test_second_fault_gives_up_with_the_error(self, tiny):
        """retries=1 means exactly one recovery: a device that faults on
        the retry too fails the request (no infinite resubmit loop)."""
        cfg, params, _ = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        reg = obs_metrics.MetricsRegistry()
        sched.bind_metrics(reg)
        try:
            faults.arm("insert", times=2)
            with pytest.raises(Exception) as ei:
                sched.submit([3, 17, 42], timeout=120)
            assert "insert failed" in str(ei.value)
            fam = reg.labeled_counter("rag_inflight_retries_total")
            assert fam.labels(outcome="gave_up").value == 1
            # and the engine still serves afterwards
            out = sched.submit([5, 5, 8], timeout=120)
            assert isinstance(out, list) and out
        finally:
            sched.shutdown()

    def test_reset_storm_opens_breaker(self, tiny):
        cfg, params, _ = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        breaker = CircuitBreaker(threshold=2, window_s=600.0)
        sched.breaker = breaker
        try:
            for _ in range(2):
                faults.arm("decode_step", times=1)
                sched.submit([3, 17, 42], timeout=120)  # recovered each time
            assert breaker.open
        finally:
            sched.shutdown()


class TestMigrationChaos:
    """ISSUE 20 chaos contract: a fault INSIDE the migration import's
    donated region resets the decode-role engine (EngineStateLost); the
    scheduler re-prefills the packet's prompt + already-emitted tokens
    there, so the client stream stays byte-identical to a unified run —
    seeded, not just greedy, because every draw is (seed, position)
    keyed — and NEITHER engine leaks a block (the prefill engine already
    released the row at export; the decode engine's reset returns the
    partially-donated blocks)."""

    PAGED = EngineConfig(
        prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64,
        kv_paged=True, kv_block_size=16,
    )
    PROMPTS = [[5, 6, 7, 8, 9, 10, 11], [12, 13, 14], [3] * 20]

    def test_mid_migration_reset_recovers_byte_identical(self, tiny):
        import dataclasses

        cfg, params, _ = tiny
        seeded = SamplingConfig(do_sample=True, temperature=0.8,
                                max_new_tokens=8)
        uni = ContinuousScheduler(
            ContinuousEngine(cfg, params, sampling=seeded,
                             engine_config=self.PAGED, dtypes=FP32),
            retry_backoff_s=0.0,
        )
        try:
            base = [uni.submit(p, seed=50 + i)
                    for i, p in enumerate(self.PROMPTS)]
        finally:
            uni.shutdown()
        pre = ContinuousScheduler(
            ContinuousEngine(
                cfg, params, sampling=seeded,
                engine_config=dataclasses.replace(
                    self.PAGED, pool_role="prefill"
                ),
                dtypes=FP32,
            ),
            retry_backoff_s=0.0,
        )
        dec = ContinuousScheduler(
            ContinuousEngine(
                cfg, params, sampling=seeded,
                engine_config=dataclasses.replace(
                    self.PAGED, pool_role="decode"
                ),
                dtypes=FP32,
            ),
            retry_backoff_s=0.0,
        )
        try:
            got = []
            for i, p in enumerate(self.PROMPTS):
                if i == 1:  # fault fires mid-import, inside donation
                    faults.arm("migrate", times=1)
                info = {}
                toks = pre.submit(p, seed=50 + i, info=info, timeout=120)
                pkt = info.get("migrate_packet")
                got.append(
                    dec.submit_migrated(pkt, timeout=120)
                    if pkt is not None else toks
                )
            assert faults.armed() == {}, "migrate fault never fired"
            assert got == base
            assert pre.engine.kv_pool.blocks_in_use() == 0, (
                pre.engine.kv_pool.stats()
            )
            assert dec.engine.kv_pool.blocks_in_use() == 0, (
                dec.engine.kv_pool.stats()
            )
        finally:
            pre.shutdown()
            dec.shutdown()


class TestSchedulerLifecycle:
    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    )
    def test_submit_after_worker_death_fails_fast(self, tiny):
        """Satellite: a dead worker must not let submit() enqueue into a
        queue nobody drains (the caller would block forever)."""
        cfg, params, _ = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng)
        try:
            # kill the worker with an error its loop does not guard
            eng.free_slots = None  # TypeError on next call
            try:
                sched.submit([3, 17, 42], timeout=30)
            except BaseException:  # noqa: BLE001 — delivery form is not the point
                pass
            sched._worker.join(timeout=30)
            assert not sched._worker.is_alive()
            # post-mortem submits fail fast instead of blocking forever
            t0 = time.monotonic()
            with pytest.raises(RuntimeError, match="shut down"):
                sched.submit([5, 5], timeout=None)
            assert time.monotonic() - t0 < 5.0
        finally:
            sched.shutdown()


# ---------------------------------------------------------------------------
# HTTP integration: 429 shape, Retry-After, 504, breaker readiness, degraded
# ---------------------------------------------------------------------------
class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode("utf-8", "replace")


def make_service(resilience=None, prompt_buckets=(128, 256), max_seq_len=4096 + 256,
                 lookahead=None):
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(
        model=llama_cfg, encoder=enc_cfg,
        resilience=resilience or ResilienceConfig(),
        lookahead=lookahead or LookaheadConfig(),
    )
    engine = InferenceEngine(
        llama_cfg,
        init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
        sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
        engine_config=EngineConfig(
            prompt_buckets=prompt_buckets, max_batch_size=2,
            max_seq_len=max_seq_len,
        ),
        dtypes=FP32,
    )
    encoder = EncoderRunner(
        enc_cfg,
        init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32, 64), max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size)
    svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
    svc.ready = True
    texts = ["alpha beta gamma", "delta epsilon zeta"]
    vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
    store.add(list(vecs), [
        {"filename": "f", "chunk_id": i, "text": t} for i, t in enumerate(texts)
    ])
    return svc


@pytest.fixture(scope="module")
def http_service():
    return make_service()


class TestHttpShedding:
    def test_429_body_shape_and_retry_after_header(self, http_service):
        svc = http_service
        client = create_app(svc).test_client()
        gate = svc.admission
        old = (gate.max_concurrency, gate.max_queue)
        gate.max_concurrency, gate.max_queue = 1, 0
        try:
            with gate.admit():  # the one slot is taken; queue cap is 0
                r = client.post("/generate", json={"prompt": "alpha"})
            assert r.status_code == 429
            body = r.get_json()
            assert body["reason"] == "queue_full"
            assert body["error"] == "server overloaded"
            assert body["retry_after_s"] == pytest.approx(1.0)
            assert int(r.headers["Retry-After"]) >= 1
            # the shed is counted
            snap = svc.metrics.snapshot()
            assert snap["rag_admission_rejected_total"] >= 1
        finally:
            gate.max_concurrency, gate.max_queue = old

    def test_shed_requests_count_toward_availability_family(self, http_service):
        svc = http_service
        fam = svc.metrics.get_family("rag_http_requests_total")
        before = sum(
            c.value for labels, c in fam.items() if dict(labels).get("code") == "429"
        )
        client = create_app(svc).test_client()
        gate = svc.admission
        old = (gate.max_concurrency, gate.max_queue)
        gate.max_concurrency, gate.max_queue = 1, 0
        try:
            with gate.admit():
                client.post("/generate", json={"prompt": "alpha"})
        finally:
            gate.max_concurrency, gate.max_queue = old
        after = sum(
            c.value for labels, c in fam.items() if dict(labels).get("code") == "429"
        )
        assert after == before + 1

    def test_breaker_open_flips_healthz_readiness_and_sheds_503(self, http_service):
        svc = http_service
        client = create_app(svc).test_client()
        assert client.get("/healthz").status_code == 200
        for _ in range(svc.breaker.threshold):
            svc.breaker.record_reset()
        try:
            r = client.get("/healthz")
            assert r.status_code == 503
            body = r.get_json()
            assert body["breaker_open"] is True
            assert body["status"] == "draining"
            # liveness is NOT affected: draining, not restarting
            assert client.get("/healthz?live=1").status_code == 200
            # and /generate sheds with 503 + Retry-After
            r = client.post("/generate", json={"prompt": "alpha"})
            assert r.status_code == 503
            assert r.get_json()["reason"] == "breaker_open"
            assert "Retry-After" in r.headers
        finally:
            svc.breaker._events.clear()
        assert client.get("/healthz").status_code == 200

    def test_deadline_404_shapes(self, http_service):
        client = create_app(http_service).test_client()
        # malformed deadline -> 400, not silently defaulted
        r = client.post("/generate", json={"prompt": "a", "deadline_ms": "soon"})
        assert r.status_code == 400
        r = client.post("/generate", json={"prompt": "a", "deadline_ms": -5})
        assert r.status_code == 400
        # non-finite values must be 400, not an OverflowError-500 ("inf")
        # or a silent never-expiring request ("nan")
        for bad in ("inf", "nan", "-inf"):
            r = client.post("/generate", json={"prompt": "a", "deadline_ms": bad})
            assert r.status_code == 400, (bad, r.get_json())
        # a microscopic budget -> 504 naming the stage it died at
        r = client.post("/generate", json={"prompt": "alpha", "deadline_ms": 0.001})
        assert r.status_code == 504
        body = r.get_json()
        assert body["stage"] in ("queue", "retrieve", "assemble", "generate")
        snap = http_service.metrics.snapshot()
        assert snap["rag_deadline_exceeded_total"] >= 1

    def test_header_deadline_is_honored(self, http_service):
        client = create_app(http_service).test_client()
        r = client.post(
            "/generate", json={"prompt": "alpha"},
            headers={"x-request-deadline-ms": "0.001"},
        )
        assert r.status_code == 504

    def test_normal_request_unaffected_and_undegraded(self, http_service):
        client = create_app(http_service).test_client()
        r = client.post("/generate", json={"prompt": "alpha"})
        assert r.status_code == 200
        body = r.get_json()
        assert "generated_text" in body
        assert "degraded" not in body

    def test_debug_faults_endpoint_gated_on_env(self, http_service, monkeypatch):
        client = create_app(http_service).test_client()
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        assert client.get("/debug/faults").status_code == 403
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        r = client.get("/debug/faults")
        assert r.status_code == 200
        assert r.get_json()["armed"] == {}
        r = client.post("/debug/faults", json={"site": "embed", "times": 3})
        assert r.status_code == 200
        assert r.get_json()["armed"] == {"embed": 3}
        assert client.post(
            "/debug/faults", json={"site": "nope"}
        ).status_code == 400
        r = client.post("/debug/faults", json={"clear": True})
        assert r.get_json()["armed"] == {}

    def test_store_fault_surfaces_as_500_not_hang(self, http_service):
        client = create_app(http_service).test_client()
        faults.arm("store_lookup", times=1)
        r = client.post("/generate", json={"prompt": "alpha"})
        assert r.status_code == 500
        assert "injected fault" in r.get_json()["error"]
        # disarmed: next request serves
        assert client.post(
            "/generate", json={"prompt": "alpha"}
        ).status_code == 200


class TestDegradedMarking:
    def test_prefix_cache_failure_marks_response_degraded(self):
        # bucket must fit the byte-tokenized system head + tail with >= 16
        # tokens of context room, or the prefixed path never engages
        svc = make_service(prompt_buckets=(128, 1024), max_seq_len=1024 + 128)

        class BrokenCache:
            def prefix_for(self, segments):
                raise RuntimeError("cache exploded")

        svc.engine.prefix_cache = BrokenCache()
        try:
            client = create_app(svc).test_client()
            r = client.post("/generate", json={"prompt": "alpha"})
            assert r.status_code == 200, r.get_json()
            body = r.get_json()
            assert body.get("degraded") is True
            assert body["degraded_reasons"] == ["prefix_cache"]
            snap = svc.metrics.snapshot()
            assert snap["rag_degraded_responses_total"] == 1
        finally:
            svc.engine.prefix_cache = None


# ---------------------------------------------------------------------------
# lookahead chaos (ISSUE 7): the lookahead_retrieve fault site + stale-
# prefetch cancellation, under the same armed-harness lane as the rest of
# this file (tests/test_lookahead.py carries the full pipeline matrix)
# ---------------------------------------------------------------------------
class TestLookaheadChaos:
    def test_lookahead_fault_falls_back_and_serves(self):
        """Armed ``lookahead_retrieve``: the speculation's worker faults,
        the serving tail's join surfaces it, the request falls back to the
        INLINE retrieve path and serves the identical greedy answer — a
        failed speculation must never fail (or change) a request."""
        svc = make_service(lookahead=LookaheadConfig(enabled=True))
        try:
            client = create_app(svc).test_client()
            clean = client.post("/query", json={"prompt": "alpha"}).get_json()
            faults.arm("lookahead_retrieve", times=1)
            faulted = client.post("/query", json={"prompt": "alpha"}).get_json()
            assert faults.armed() == {}, "lookahead_retrieve never fired"
            assert faulted["generated_text"] == clean["generated_text"]
            assert svc.lookahead._m_wasted["failed"].value >= 1
            # harness healthy afterwards: the next lookahead join serves
            after = client.post("/query", json={"prompt": "alpha"}).get_json()
            assert after["generated_text"] == clean["generated_text"]
        finally:
            svc.shutdown()

    def test_superseded_prestage_returns_every_block(self, tiny):
        """Stale-prefetch cancellation, both substrates: a speculation that
        loses before admission releases every prefix-cache byte AND every
        registered pool block it warmed — zero leaks, idempotent."""
        cfg, params, _ = tiny
        pc = PrefixCacheConfig(
            enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
            suffix_buckets=(16,), hbm_budget_mb=64,
        )
        ie = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(64,), max_batch_size=2, max_seq_len=128,
                prefix_cache=pc,
            ),
            dtypes=FP32,
        )
        import dataclasses

        cont = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(
                ie.engine_config, kv_paged=True, kv_block_size=16
            ),
            dtypes=FP32,
        )
        cache = ie.prefix_cache
        bytes0 = cache.counters()["prefix_cache_bytes"]
        blocks0 = cont.kv_pool.blocks_in_use()
        segments = [
            ("head:chaos", [cfg.bos_token_id] + [7] * 15),
            ("chunk:chaos", [9] * 16),
        ]
        cp, record = cache.stage(segments)
        assert cp is not None and cp.chain_key is not None
        assert cont.prestage_prefix(cp) == "registered"
        assert cont.kv_pool.blocks_in_use() > blocks0
        # the speculation loses: release must return BOTH substrates to
        # their pre-staging footprint, and double-release must be a no-op
        # (only_unused is honest here — no admission mapped the chain)
        assert cache.release_staged(record) > 0
        assert cont.release_prestaged(cp.chain_key, only_unused=True) is True
        assert cache.counters()["prefix_cache_bytes"] == bytes0
        assert cont.kv_pool.blocks_in_use() == blocks0
        assert cache.release_staged(record) == 0
        assert cont.release_prestaged(cp.chain_key) is False


class TestKvSwapInChaos:
    def test_failed_swap_in_recomputes_and_leaks_nothing(self, tiny):
        """Armed ``kv_swap_in`` (ISSUE 8 chaos contract): a cold chunk
        whose host→HBM swap fails is rebuilt FROM TOKENS — the request
        serves the identical greedy stream — its host buffer releases with
        the failed entry, and the paged prestage path frees every block it
        took before declining. Zero leaks on both substrates."""
        import dataclasses

        cfg, params, _ = tiny
        pc = PrefixCacheConfig(
            enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
            suffix_buckets=(16,), hbm_budget_mb=64,
        )
        tiering = KVTieringConfig(enabled=True, retier_interval_s=3600.0)
        ie = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(64,), max_batch_size=2, max_seq_len=128,
                prefix_cache=pc, kv_tiering=tiering,
            ),
            dtypes=FP32,
        )
        cache = ie.prefix_cache
        segments = [
            ("head:swap", [cfg.bos_token_id] + [7] * 15),
            ("chunk:swap", [9] * 16),
        ]
        suffix = [5, 6, 7]
        cp = cache.prefix_for(segments)
        want = ie.generate_prefixed(suffix, cp)
        assert cache.force_demote("cold") == 2
        cache._assembled.clear()
        cache.assembled_bytes = 0
        faults.arm("kv_swap_in", times=2)  # BOTH segments' swaps fail
        cp2 = cache.prefix_for(segments)
        assert faults.armed() == {}, "kv_swap_in never fired"
        assert cp2 is not None and cp2.computed_tokens == cp.length
        assert len(cache.spill) == 0  # host buffers released
        assert cache.tier_stats()["swap_in_fallbacks"] == 2
        assert ie.generate_prefixed(suffix, cp2) == want

        # paged pool substrate: the prestage swap-in fault frees the
        # blocks it allocated and declines — no reset, no leak
        cont = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(
                ie.engine_config, kv_paged=True, kv_block_size=16
            ),
            dtypes=FP32,
        )
        free0 = cont.kv_pool.available()
        faults.arm("kv_swap_in", times=1)
        assert cont.prestage_prefix(cp2) is False
        assert faults.armed() == {}, "paged kv_swap_in never fired"
        assert cont.kv_pool.available() == free0
        # fault cleared: the identical prestage succeeds and releases clean
        assert cont.prestage_prefix(cp2) == "registered"
        assert cont.release_prestaged(cp2.chain_key) is True
        assert cont.kv_pool.available() == free0


class TestChunkSpliceChaos:
    def test_mid_splice_fault_recomputes_and_leaks_nothing(self, tiny):
        """Armed ``chunk_splice`` (ISSUE 12 chaos contract): a shifted
        chunk splice that dies mid-flight falls back to RECOMPUTE — the
        cache rebuilds the chunk from tokens with no entry lost and exact
        byte accounting, and the paged per-chunk assembly declines its
        plan BEFORE allocating, so the admission scatters the buffer
        instead. Zero leaked entries/blocks on both substrates."""
        import dataclasses

        cfg, params, _ = tiny
        pc = PrefixCacheConfig(
            enabled=True, max_prefix_tokens=64, segment_buckets=(16,),
            suffix_buckets=(16,), hbm_budget_mb=64, reuse="chunk",
            boundary_tokens=4, chunk_hot_min=0.0,
        )
        ie = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(64, 128), max_batch_size=2, max_seq_len=256,
                prefix_cache=pc,
            ),
            dtypes=FP32,
        )
        cache = ie.prefix_cache
        head = [int(cfg.bos_token_id)] + [7] * 15
        a, b = [9] * 16, [11] * 16
        suffix = [5, 6, 7]
        cache.prefix_for([("head:cs", head), ("A:cs", a), ("B:cs", b)])
        entries0 = len(cache._entries)
        faults.arm("chunk_splice", times=2)  # both shifted chunks
        cp = cache.prefix_for([("head:cs", head), ("B:cs", b), ("A:cs", a)])
        assert faults.armed() == {}, "chunk_splice never fired"
        counts = cache.chunk_reuse_counters()
        assert counts["splice_faults"] == 2 and counts["rerotated"] == 0
        assert len(cache._entries) == entries0
        assert cache.entry_bytes == sum(
            e.nbytes for e in cache._entries.values()
        )

        # paged substrate: the plan declines before any allocation — the
        # admission scatters the fresh buffer and every block is accounted
        cont = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(
                ie.engine_config, kv_paged=True, kv_block_size=16
            ),
            dtypes=FP32,
        )
        _, fin = cont.admit_prefixed(1, suffix, cp, max_new=4)
        while cont.has_active():
            for _r, toks in cont.step():
                fin = toks
        assert cont._chunk_regs  # exact spans registered for next time
        cache._assembled.clear()
        cache.assembled_bytes = 0
        cache._assembled_spans.clear()
        cp2 = cache.prefix_for(
            [("head:cs", head), ("B:cs", b), ("A:cs", a)]
        )
        faults.arm("chunk_splice", times=1)
        assert cont._chunk_splice_plan(cp2) is None  # declined, pre-alloc
        assert faults.armed() == {}, "paged chunk_splice never fired"
        _, fin2 = cont.admit_prefixed(2, suffix, cp2, max_new=4)
        while cont.has_active():
            for _r, toks in cont.step():
                fin2 = toks
        for k in list(cont._chunk_regs):
            cont._drop_chunk_reg(k)
        for k in list(cont._prefix_blocks):
            cont._drop_registration(k)
        assert cont.kv_pool.blocks_in_use() == 0


class TestSpecChaos:
    """ISSUE 13 chaos contracts (rides `make chaos`, tp=1 and tp=2): a
    decode-step fault landing MID-verify-window and a pool-exhaustion
    preemption of a SPECULATING row must both recover to byte-identical
    streams with zero leaked blocks — a verify window holds more in
    flight per fetch (K+1 writes, junk lanes, per-row acceptance), so
    every recovery path is re-proven with speculation live."""

    SPEC_CFG = None  # set lazily: EngineConfig is imported at module top

    @classmethod
    def _spec_cfg(cls, **over):
        import dataclasses

        base = dataclasses.replace(
            ENG_CFG, kv_paged=True, kv_block_size=16, spec_paged=True,
            spec_paged_tokens=4,
        )
        return dataclasses.replace(base, **over) if over else base

    def _run_with_mid_stream_fault(self, cfg, params, mesh=None):
        """Submit a long repeat-heavy request, arm decode_step only after
        >= 2 verify windows have run (the fault provably lands MID-verify,
        tokens already emitted by verify steps on both sides of the
        reset), and return (stream, engine, request_info)."""
        from rag_llm_k8s_tpu.obs import flight

        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=self._spec_cfg(),
            dtypes=FP32, mesh=mesh,
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        info = {}
        out = [None]
        err = [None]

        def submit():
            try:
                out[0] = sched.submit(
                    [11] * 12, max_new_tokens=40, timeout=300, info=info
                )
            except BaseException as e:  # noqa: BLE001
                err[0] = e

        try:
            th = threading.Thread(target=submit)
            th.start()
            deadline = time.monotonic() + 120
            while (
                eng.stats.spec_verify_steps < 2
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
            assert eng.stats.spec_verify_steps >= 2, (
                "no verify window ever ran — the fault would not land "
                "mid-verify; fixture is vacuous"
            )
            faults.arm("decode_step", times=1)
            th.join(timeout=300)
            assert err[0] is None, err[0]
            assert faults.armed() == {}, "decode_step fault never fired"
            assert eng.kv_pool.blocks_in_use() == 0, eng.kv_pool.stats()
            # the delivered stream's flight anchor: complete.stream_fnv
            # over exactly the bytes the caller received
            completes = [
                e for e in flight.recorder().snapshot(etype="complete")
                if e.get("rid") == info.get("request_id")
            ]
            if completes:
                assert completes[-1]["stream_fnv"] == flight.stream_hash(
                    out[0]
                )
            return out[0]
        finally:
            sched.shutdown()

    def test_decode_fault_mid_verify_window_byte_identical(self, tiny):
        cfg, params, oracle = tiny
        want = oracle.generate([[11] * 12], max_new_tokens=40)[0]
        got = self._run_with_mid_stream_fault(cfg, params)
        assert got == want

    def test_pool_exhaustion_preempts_speculating_row(self, tiny):
        """A pool sized for half the batch's decode growth: speculating
        rows preempt mid-verify-stream, resubmit (prompt + emitted), and
        every stream still matches the fault-free oracle — zero leaks."""
        prompts = [[3, 17, 42, 3, 17, 42, 3, 17], [5, 5, 8], [11] * 12,
                   [2, 9, 2, 9, 2, 9, 2]]
        cfg, params, oracle = tiny
        want = [oracle.generate([p], max_new_tokens=40)[0] for p in prompts]
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=self._spec_cfg(kv_pool_blocks=8), dtypes=FP32,
        )
        sched = ContinuousScheduler(eng)
        try:
            outs = [None] * len(prompts)
            errs = [None] * len(prompts)

            def run(i):
                try:
                    outs[i] = sched.submit(
                        prompts[i], max_new_tokens=40, timeout=300
                    )
                except BaseException as e:  # noqa: BLE001
                    errs[i] = e

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert errs == [None] * len(prompts), errs
            assert outs == want
            assert eng.stats.spec_verify_steps > 0, "nothing speculated"
            assert eng.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()

    @pytest.fixture(scope="class")
    def tp2(self, tiny):
        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg, params, oracle = tiny
        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        return cfg, shard_llama_params(params, ctx), oracle, ctx

    def test_tp2_decode_fault_mid_verify_window(self, tp2):
        """The same mid-verify fault recovery over the head-sharded
        arena: the tp split must not open a leak or divergence path."""
        cfg, params, oracle, ctx = tp2
        want = oracle.generate([[11] * 12], max_new_tokens=40)[0]
        got = self._run_with_mid_stream_fault(cfg, params, mesh=ctx)
        assert got == want

    def test_tp2_pool_exhaustion_preempts_speculating_row(self, tp2):
        cfg, params, oracle, ctx = tp2
        prompts = [[3, 17, 42, 3, 17, 42, 3, 17], [11] * 12,
                   [2, 9, 2, 9, 2, 9, 2]]
        want = [oracle.generate([p], max_new_tokens=40)[0] for p in prompts]
        # pool = MB (the construction minimum): three rows' decode growth
        # (~4 blocks each at 40 new tokens) cannot coexist — preemption
        # must fire while rows speculate
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=self._spec_cfg(kv_pool_blocks=8), dtypes=FP32,
            mesh=ctx,
        )
        sched = ContinuousScheduler(eng)
        try:
            outs = [None] * len(prompts)
            errs = [None] * len(prompts)

            def run(i):
                try:
                    outs[i] = sched.submit(
                        prompts[i], max_new_tokens=40, timeout=300
                    )
                except BaseException as e:  # noqa: BLE001
                    errs[i] = e

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert errs == [None] * len(prompts), errs
            assert outs == want
            assert eng.stats.spec_verify_steps > 0, "nothing speculated"
            assert eng.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()
