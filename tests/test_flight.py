"""Engine flight recorder (ISSUE 11): causal event journal, per-request
lifecycle timelines, trigger-driven incident bundles.

The contracts under test (obs/flight.py, docs/OBSERVABILITY.md "Engine
flight recorder"):

- **Journal**: a fixed-size ring of typed, monotonic-stamped events — the
  catalog is CLOSED (unknown types raise), the ring bounds memory, a
  disabled recorder's emit is free, and timelines reconstruct one
  request's ordered chain with inter-event deltas.
- **Chaos-lane proof** (``make flight-smoke``): with the fault harness
  forcing a reset storm, an incident bundle is produced whose timeline
  reconstructs each in-flight request's full lifecycle (admit → reset →
  resubmit → complete) BYTE-CONSISTENT with the stream the caller
  actually received (the ``complete`` event's FNV-1a stream hash equals
  the hash of the delivered tokens), and ``scripts/flightview.py``
  round-trips the bundle offline.
- **Debug-surface gating**: every ``/debug/*`` route answers 403 unless
  the process is armed (TPU_RAG_FAULTS / TPU_RAG_DEBUG) — one
  parametrized test pins the contract across ALL debug routes.
- **Spool bounds**: bundles are rate-limited per trigger and pruned past
  the spool cap; a bundle is self-contained JSON.
"""

import json
import sys
import threading
from pathlib import Path

import jax
import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    FlightConfig,
    LlamaConfig,
    ResilienceConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.server.app import RagService, create_app

from scripts import flightview  # noqa: E402

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
ENG_CFG = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# recorder primitives
# ---------------------------------------------------------------------------
class TestRecorder:
    def test_closed_catalog_rejects_unknown_types(self):
        rec = flight.FlightRecorder(capacity=8)
        with pytest.raises(ValueError, match="unknown flight event"):
            rec.emit("definitely_not_an_event")

    def test_ring_bounds_and_order(self):
        rec = flight.FlightRecorder(capacity=4)
        for i in range(10):
            rec.emit("admit", i, slot=i)
        evs = rec.snapshot()
        assert len(evs) == 4  # ring holds the newest 4
        assert [e["rid"] for e in evs] == [6, 7, 8, 9]  # oldest first
        assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
        assert rec.events_emitted == 10

    def test_disabled_recorder_journals_nothing(self):
        rec = flight.FlightRecorder(capacity=8, enabled=False)
        rec.emit("admit", 1)
        assert rec.snapshot() == [] and rec.events_emitted == 0

    def test_timeline_deltas_and_filtering(self):
        rec = flight.FlightRecorder(capacity=16)
        rec.emit("admit", 5, slot=0, tok0=9)
        rec.emit("sync_window_open", steps=4, active=1)  # rid-less context
        rec.emit("eos", 5, reason="eos", n_tokens=3)
        rec.emit("complete", 5, n_tokens=3, stream_fnv=123)
        rec.emit("admit", 6, slot=1)  # another request
        tl = rec.timeline(5)
        assert tl["schema_version"] == flight.SCHEMA_VERSION
        types = [e["type"] for e in tl["events"]]
        assert types == ["admit", "eos", "complete"]
        assert tl["events"][0]["t_ms"] == 0.0
        assert all(e["dt_ms"] >= 0.0 for e in tl["events"])
        assert "rid" not in tl["events"][0]

    def test_concurrent_emits_keep_unique_ordered_seqs(self):
        rec = flight.FlightRecorder(capacity=1024)

        def spam(rid):
            for _ in range(100):
                rec.emit("pool_alloc", rid, blocks=1, free=0)

        ts = [threading.Thread(target=spam, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        evs = rec.snapshot()
        seqs = [e["seq"] for e in evs]
        assert len(seqs) == len(set(seqs)) == 400

    def test_configure_toggles_and_rebuilds(self):
        rec0 = flight.recorder()
        cap0, en0 = rec0.capacity, rec0.enabled
        try:
            assert flight.configure(enabled=False) is rec0
            assert not flight.recorder().enabled
            rec1 = flight.configure(enabled=True, capacity=cap0 + 1)
            assert rec1 is not rec0 and rec1.capacity == cap0 + 1
        finally:
            flight.configure(enabled=en0, capacity=cap0)

    def test_stream_hash_is_order_sensitive_and_stable(self):
        a = flight.stream_hash([1, 2, 3])
        assert a == flight.stream_hash([1, 2, 3])
        assert a != flight.stream_hash([3, 2, 1])
        assert flight.stream_hash([]) == 0xCBF29CE484222325


# ---------------------------------------------------------------------------
# incident spooler
# ---------------------------------------------------------------------------
class TestIncidentSpooler:
    def _ctx(self):
        return {"journal": [{"seq": 0, "t": 1.0, "type": "reset"}],
                "metrics": {"x": 1.0}, "config_fingerprint": {"sha256": "d"},
                "traces": []}

    def test_bundle_is_self_contained_json(self, tmp_path):
        sp = flight.IncidentSpooler(str(tmp_path), cooldown_s=0.0)
        bid = sp.trigger("reset_storm", self._ctx)
        assert bid is not None
        listed = sp.list()
        assert [b["id"] for b in listed] == [bid]
        assert listed[0]["trigger"] == "reset_storm"
        bundle = sp.load(bid)
        assert bundle["schema_version"] == flight.SCHEMA_VERSION
        assert bundle["trigger"] == "reset_storm"
        assert bundle["journal"] and bundle["metrics"] == {"x": 1.0}
        # raw file parses standalone (a kubectl cp is a full post-mortem)
        raw = json.loads(Path(listed[0]["path"]).read_text())
        assert raw["id"] == bid

    def test_cooldown_suppresses_repeats_per_trigger(self, tmp_path):
        clk = FakeClock()
        sp = flight.IncidentSpooler(str(tmp_path), cooldown_s=30.0, clock=clk)
        assert sp.trigger("reset_storm", self._ctx) is not None
        assert sp.trigger("reset_storm", self._ctx) is None  # suppressed
        # a DIFFERENT trigger is not suppressed by the first one's cooldown
        assert sp.trigger("breaker_open", self._ctx) is not None
        clk.advance(31.0)
        assert sp.trigger("reset_storm", self._ctx) is not None

    def test_spool_prunes_oldest_past_cap(self, tmp_path):
        sp = flight.IncidentSpooler(str(tmp_path), max_bundles=3,
                                    cooldown_s=0.0)
        ids = [sp.trigger("deadline_exceeded", self._ctx) for _ in range(5)]
        listed = sp.list()
        assert len(listed) == 3
        assert [b["id"] for b in listed] == ids[-3:]

    def test_unknown_trigger_raises(self, tmp_path):
        sp = flight.IncidentSpooler(str(tmp_path))
        with pytest.raises(ValueError, match="unknown incident trigger"):
            sp.trigger("nope", self._ctx)

    def test_context_failure_is_contained(self, tmp_path):
        sp = flight.IncidentSpooler(str(tmp_path), cooldown_s=0.0)
        assert sp.trigger(
            "breaker_open", lambda: (_ for _ in ()).throw(RuntimeError("x"))
        ) is None
        assert sp.list() == []

    def test_config_fingerprint_is_stable_and_sensitive(self):
        a = flight.config_fingerprint(AppConfig())
        b = flight.config_fingerprint(AppConfig())
        c = flight.config_fingerprint(AppConfig(system_message="different"))
        assert a["sha256"] == b["sha256"] != c["sha256"]
        json.dumps(a)  # JSON-clean by construction


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
class TestFlightConfig:
    def test_env_round_trip(self):
        fc = FlightConfig.from_env({
            "TPU_RAG_FLIGHT": "0", "TPU_RAG_FLIGHT_EVENTS": "99",
            "TPU_RAG_FLIGHT_SPOOL": "/tmp/z", "TPU_RAG_FLIGHT_SPOOL_MAX": "2",
            "TPU_RAG_FLIGHT_COOLDOWN_S": "1.5", "TPU_RAG_DEBUG": "1",
        })
        assert fc == FlightConfig(
            enabled=False, capacity=99, spool_dir="/tmp/z", spool_max=2,
            cooldown_s=1.5, debug_endpoints=True,
        )
        assert AppConfig.from_env({}).flight == FlightConfig()

    def test_malformed_values_raise(self):
        for env in (
            {"TPU_RAG_FLIGHT": "yes"},
            {"TPU_RAG_DEBUG": "2"},
            {"TPU_RAG_FLIGHT_EVENTS": "0"},
            {"TPU_RAG_FLIGHT_SPOOL_MAX": "0"},
            {"TPU_RAG_FLIGHT_COOLDOWN_S": "-1"},
        ):
            with pytest.raises(ValueError):
                FlightConfig.from_env(env)


# ---------------------------------------------------------------------------
# HTTP surface: gating, timelines, incidents
# ---------------------------------------------------------------------------
class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode(
            "utf-8", "replace"
        )


def make_flight_service(spool_dir, breaker_resets=2, continuous=True):
    """A service whose /generate flows through a CONTINUOUS scheduler (the
    substrate the journal instruments), with the incident spool pointed at
    a test directory and a zero cooldown so every trigger spools."""
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(
        model=llama_cfg, encoder=enc_cfg,
        resilience=ResilienceConfig(breaker_reset_threshold=breaker_resets),
        flight=FlightConfig(spool_dir=str(spool_dir), cooldown_s=0.0,
                            spool_max=8),
        # a short system message keeps assembled prompts inside the
        # continuous bucket ladder, so /generate takes scheduler.submit
        system_message="Use the context.",
    )
    params = init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32)
    engine = InferenceEngine(
        llama_cfg, params, sampling=GREEDY,
        engine_config=EngineConfig(
            prompt_buckets=(128, 256), max_batch_size=2, max_seq_len=512,
        ),
        dtypes=FP32,
    )
    sched = None
    if continuous:
        ceng = ContinuousEngine(
            llama_cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(64, 256), max_batch_size=4, max_seq_len=320,
            ),
            dtypes=FP32,
        )
        sched = ContinuousScheduler(ceng, retry_backoff_s=0.0)
    encoder = EncoderRunner(
        enc_cfg, init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32, 64), max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size)
    svc = RagService(
        cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store,
        scheduler=sched,
    )
    svc.ready = True
    texts = ["alpha beta gamma", "delta epsilon zeta"]
    vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
    store.add(list(vecs), [
        {"filename": "f", "chunk_id": i, "text": t}
        for i, t in enumerate(texts)
    ])
    return svc


@pytest.fixture(scope="module")
def flight_service(tmp_path_factory):
    svc = make_flight_service(tmp_path_factory.mktemp("spool"))
    yield svc
    svc.shutdown()


class TestDebugGating:
    """Satellite: ONE 403-unless-armed contract across ALL /debug routes."""

    ROUTES = (
        "/debug/traces",
        "/debug/timeline/1",
        "/debug/incidents",
        "/debug/faults",
        "/debug/goodput",
        "/debug/quality",
    )

    @pytest.mark.parametrize("route", ROUTES)
    def test_unarmed_process_answers_403(self, flight_service, monkeypatch,
                                         route):
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        client = create_app(flight_service).test_client()
        r = client.get(route)
        assert r.status_code == 403
        assert "error" in r.get_json()

    @pytest.mark.parametrize("route", ROUTES)
    def test_armed_process_serves(self, flight_service, monkeypatch, route):
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(flight_service).test_client()
        r = client.get(route)
        # 200, or an honest 404 for an id nobody journaled — never a 403
        assert r.status_code in (200, 404)

    def test_debug_flag_arms_read_only_surface_but_not_faults(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        svc = make_flight_service(tmp_path, continuous=False)
        try:
            svc.config = AppConfig(
                model=svc.config.model, encoder=svc.config.encoder,
                flight=FlightConfig(debug_endpoints=True),
            )
            client = create_app(svc).test_client()
            assert client.get("/debug/traces").status_code == 200
            assert client.get("/debug/incidents").status_code == 200
            # fault ARMING stays strictly TPU_RAG_FAULTS-gated
            assert client.get("/debug/faults").status_code == 403
        finally:
            svc.shutdown()


class TestTimelineHttp:
    def test_generate_carries_request_id_and_inline_timeline(
        self, flight_service, monkeypatch
    ):
        client = create_app(flight_service).test_client()
        r = client.post(
            "/generate", json={"prompt": "alpha", "timeline": True}
        )
        assert r.status_code == 200
        body = r.get_json()
        assert isinstance(body.get("request_id"), int)
        tl = body["timeline"]
        assert tl["request_id"] == body["request_id"]
        types = [e["type"] for e in tl["events"]]
        # the lifecycle now begins at submission (the arrival trace
        # record, ISSUE 17); admission follows
        assert types[0] == "arrival" and "admit" in types
        assert types[-1] == "complete"

    def test_debug_timeline_endpoint_serves_the_same_chain(
        self, flight_service, monkeypatch
    ):
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(flight_service).test_client()
        body = client.post(
            "/generate", json={"prompt": "alpha"}
        ).get_json()
        rid = body["request_id"]
        r = client.get(f"/debug/timeline/{rid}")
        assert r.status_code == 200
        tl = r.get_json()
        types = [e["type"] for e in tl["events"]]
        assert "admit" in types and "complete" in types
        assert client.get("/debug/timeline/999999999").status_code == 404

    def test_untimed_response_has_no_timeline_key(self, flight_service):
        client = create_app(flight_service).test_client()
        body = client.post("/generate", json={"prompt": "alpha"}).get_json()
        assert "timeline" not in body and "request_id" in body


# ---------------------------------------------------------------------------
# the chaos-lane proof (make flight-smoke)
# ---------------------------------------------------------------------------
class TestFlightSmoke:
    def test_reset_lifecycle_is_byte_consistent_with_delivered_stream(
        self, tiny
    ):
        """admit → reset → resubmit → (re)admit → complete, and the
        complete event's stream hash equals the hash of the tokens the
        caller received — the timeline provably describes the stream."""
        cfg, params = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            faults.arm("decode_step", times=1)
            info = {}
            out = sched.submit([3, 17, 42], timeout=120, info=info)
            rid = info["request_id"]
            tl = flight.recorder().timeline(rid)
            types = [e["type"] for e in tl["events"]]
            # the fault fired mid-decode: the request was admitted, the
            # reset wiped it, the scheduler resubmitted, a second
            # admission served it to completion
            assert types.count("admit") == 2
            assert "resubmit" in types and types[-1] == "complete"
            resubmit = next(e for e in tl["events"] if e["type"] == "resubmit")
            assert resubmit["outcome"] == "resubmitted"
            complete = tl["events"][-1]
            assert complete["n_tokens"] == len(out)
            assert complete["stream_fnv"] == flight.stream_hash(out)
            # the journal (not the per-request chain) holds the reset
            assert flight.recorder().snapshot(etype="reset")
        finally:
            sched.shutdown()

    def test_reset_storm_produces_bundle_and_flightview_round_trips(
        self, tmp_path, monkeypatch
    ):
        """The acceptance path end to end: forced reset storm → breaker
        flips → incident bundles spool → /debug/incidents serves them →
        flightview reconstructs every request's lifecycle offline,
        byte-consistent with what the callers received."""
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        svc = make_flight_service(tmp_path, breaker_resets=2)
        try:
            results = {}
            for i, prompt in enumerate(([3, 17, 42], [5, 5, 8])):
                faults.arm("decode_step", times=1)
                info = {}
                results[i] = (
                    svc.scheduler.submit(prompt, timeout=120, info=info),
                    info["request_id"],
                )
            assert svc.breaker.open  # two resets: the storm flipped it
            client = create_app(svc).test_client()
            listed = client.get("/debug/incidents").get_json()["incidents"]
            triggers = {b["trigger"] for b in listed}
            assert {"reset_storm", "breaker_open"} <= triggers
            # the bundle is self-contained: fetch one and round-trip it
            # through flightview with NO live service
            bid = next(
                b["id"] for b in listed if b["trigger"] == "reset_storm"
            )
            bundle = client.get(f"/debug/incidents?id={bid}").get_json()
            assert bundle["schema_version"] == flight.SCHEMA_VERSION
            assert bundle["config_fingerprint"]["sha256"]
            assert bundle["metrics"]["rag_engine_resets_total"] >= 1
            view = flightview.build_view(flightview.load_events(bundle))
            for out, rid in results.values():
                tl = view["requests"].get(str(rid))
                if tl is None:
                    continue  # the 2nd request may post-date this bundle
                types = [e["type"] for e in tl["events"]]
                assert types[0] in ("arrival", "admit")
                if tl["complete"]:
                    complete = tl["events"][-1]
                    assert (
                        complete["attrs"]["stream_fnv"]
                        == flight.stream_hash(out)
                    )
            # request 1 completed before the storm bundle was written, so
            # ITS lifecycle must be fully reconstructed there
            out0, rid0 = results[0]
            tl0 = view["requests"][str(rid0)]
            assert tl0["complete"] and tl0["resets_survived"] >= 1
            assert view["occupancy"]["resets"] >= 1
            # the CLI renders the on-disk file standalone (ASCII + JSON)
            path = next(
                b["path"] for b in svc.incidents.list() if b["id"] == bid
            )
            assert flightview.main([path]) == 0
            assert flightview.main([path, "--json"]) == 0
        finally:
            svc.shutdown()

    def test_newer_schema_is_refused(self, tmp_path):
        p = tmp_path / "bundle.json"
        p.write_text(json.dumps(
            {"schema_version": flight.SCHEMA_VERSION + 1, "journal": []}
        ))
        with pytest.raises(SystemExit, match="newer"):
            flightview.load_events(json.loads(p.read_text()))

    def test_pool_exhausted_shed_triggers_bundle(self, tmp_path):
        from rag_llm_k8s_tpu.resilience.admission import AdmissionRejected

        svc = make_flight_service(tmp_path, continuous=False)
        try:
            gate = svc.admission
            gate.max_concurrency, gate.max_queue = 1, 4
            gate.saturation_hint = lambda: True  # dry pool, nothing warm
            with gate.admit():
                with pytest.raises(AdmissionRejected) as ei:
                    with gate.admit():  # would have to wait: shed instead
                        pass
            assert ei.value.reason == "pool_exhausted"
            triggers = {b["trigger"] for b in svc.incidents.list()}
            assert "pool_exhausted_shed" in triggers
            # the shed itself is journaled too
            assert flight.recorder().snapshot(etype="shed")
        finally:
            svc.shutdown()

    def test_deadline_504_triggers_bundle(self, tmp_path):
        svc = make_flight_service(tmp_path, continuous=False)
        try:
            client = create_app(svc).test_client()
            r = client.post(
                "/generate",
                json={"prompt": "alpha", "deadline_ms": 0.001},
            )
            assert r.status_code == 504
            triggers = {b["trigger"] for b in svc.incidents.list()}
            assert "deadline_exceeded" in triggers
        finally:
            svc.shutdown()
