"""Retrieval lookahead pipeline (rag/lookahead.py + wiring).

The load-bearing contracts:

- **Byte identity**: greedy output streams are IDENTICAL with lookahead on
  or off, sequential or overlapped — futures resolve through the same
  retrieval entry points the sequential path uses (``make lookahead-smoke``
  runs this file's smoke class in CI).
- **Overlap**: the serving tail JOINS an already-launched future; a
  resolved future costs ~0 on the critical path
  (``timings["lookahead_hit"]``, ``rag_lookahead_joins_total{outcome}``).
- **Stale-prefetch cancellation**: a superseded/expired/abandoned
  speculation releases every prefix-cache entry, assembled buffer and pool
  block it staged that nothing else consumed — zero leaks
  (``PrefixCache.release_staged``, ``ContinuousEngine.release_prestaged``).
- **Headroom gating**: speculative launches and pool pre-staging never
  starve live traffic (breaker / admission queue / pool headroom).
- **Fault containment**: a failed lookahead retrieval (armed
  ``lookahead_retrieve`` site) falls back to inline retrieval — the
  request never fails (the chaos lane re-runs this under make chaos).
"""

import dataclasses
import io
import threading
import time

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    LlamaConfig,
    LookaheadConfig,
    PrefixCacheConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.prefix_cache import PrefixCache
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.rag.lookahead import LookaheadExecutor
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.server.app import RagService, create_app

FP32 = DTypePolicy.fp32()


class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode("utf-8", "replace")


def make_pdf(text: str) -> bytes:
    content = f"BT /F1 12 Tf ({text}) Tj ET".encode()
    return b"".join([
        b"%PDF-1.4\n",
        b"1 0 obj << /Type /Catalog /Pages 2 0 R >> endobj\n",
        b"2 0 obj << /Type /Pages /Kids [3 0 R] /Count 1 >> endobj\n",
        b"3 0 obj << /Type /Page /Parent 2 0 R /Contents 4 0 R "
        b"/Resources << /Font << /F1 5 0 R >> >> >> endobj\n",
        b"4 0 obj << /Length %d >> stream\n%s\nendstream endobj\n"
        % (len(content), content),
        b"5 0 obj << /Type /Font /Subtype /Type1 /BaseFont /Helvetica >> endobj\n",
        b"%%EOF",
    ])


def _wait_for(pred, timeout=10.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# executor unit tests (stub callbacks — no models)
# ---------------------------------------------------------------------------


def _la_cfg(**kw):
    base = dict(enabled=True, max_workers=2, max_inflight=4, ttl_s=30.0)
    base.update(kw)
    return LookaheadConfig(**base)


class _Harness:
    """Stub retrieval + staging substrate with controllable latency."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.calls = []
        self.staged = []
        self.released = []
        self.headroom = True
        self.gen = 1

    def retrieve(self, text):
        if self.delay:
            time.sleep(self.delay)
        self.calls.append(text)
        return ([f"result:{text}"], 0.5)

    def prestage(self, text, result):
        handle = {"text": text}
        self.staged.append(handle)
        return handle

    def release(self, handle):
        self.released.append(handle)

    def executor(self, **cfg_kw):
        return LookaheadExecutor(
            _la_cfg(**cfg_kw),
            retrieve_fn=self.retrieve,
            prestage_fn=self.prestage,
            release_fn=self.release,
            headroom_fn=lambda: self.headroom,
            index_gen_fn=lambda: self.gen,
            # fresh registry per executor: the counter families are keyed by
            # name, so binding the shared default registry would accumulate
            # values across tests
            registry=obs_metrics.MetricsRegistry(),
        )


class TestExecutor:
    def test_launch_claim_join_hit(self):
        h = _Harness()
        ex = h.executor()
        try:
            fut = ex.launch("q1")
            assert fut is not None
            _wait_for(fut.resolved, what="future resolve")
            claimed = ex.claim("q1")
            assert claimed is fut
            r = ex.join(claimed)
            assert r == (["result:q1"], 0.5)
            assert ex._m_joins["hit"].value == 1
            # claimed future: nothing was prestaged for it to release
            assert ex.claim("q1") is None  # consumed
        finally:
            ex.shutdown()

    def test_join_on_running_future_counts_late(self):
        h = _Harness(delay=0.2)
        ex = h.executor()
        try:
            fut = ex.launch("slow")
            claimed = ex.claim("slow")
            assert claimed is fut and not fut.resolved()
            r = ex.join(claimed, timeout=5.0)
            assert r[0] == ["result:slow"]
            assert ex._m_joins["late"].value == 1
        finally:
            ex.shutdown()

    def test_launch_dedupes_by_key(self):
        h = _Harness(delay=0.2)
        ex = h.executor()
        try:
            a = ex.launch("same")
            b = ex.launch("same")
            assert a is b
            assert ex._m_launched["admission"].value == 1
        finally:
            ex.shutdown()

    def test_inflight_bound_skips(self):
        h = _Harness(delay=0.5)
        ex = h.executor(max_workers=1, max_inflight=2)
        try:
            assert ex.launch("a") is not None
            assert ex.launch("b") is not None
            assert ex.launch("c") is None  # over the bound: skipped, not queued
            assert ex._m_skipped["inflight"].value == 1
        finally:
            ex.shutdown()

    def test_speculative_launch_gates_on_headroom(self):
        h = _Harness()
        h.headroom = False
        ex = h.executor()
        try:
            assert ex.speculate("s1", "next turn") is None
            assert ex._m_skipped["headroom"].value == 1
            # admission-trigger launches are NOT speculative: they always run
            assert ex.launch("real request") is not None
        finally:
            ex.shutdown()

    def test_new_speculation_supersedes_and_releases_old(self):
        h = _Harness()
        ex = h.executor()
        try:
            f1 = ex.speculate("s1", "turn two?")
            _wait_for(lambda: f1.staging is not None, what="prestage")
            f2 = ex.speculate("s1", "different turn two?")
            assert f2 is not f1
            _wait_for(lambda: len(h.released) == 1, what="stale release")
            assert h.released[0]["text"] == "turn two?"
            assert ex._m_wasted["superseded"].value == 1
            assert ex._m_prestage_released.value == 1
        finally:
            ex.shutdown()

    def test_ttl_expiry_releases_staging(self):
        h = _Harness()
        ex = h.executor(ttl_s=0.2)
        try:
            f = ex.launch("goes stale")
            _wait_for(lambda: f.staging is not None, what="prestage")
            time.sleep(0.3)
            assert ex.sweep() == 1
            assert ex._m_wasted["expired"].value == 1
            _wait_for(lambda: len(h.released) == 1, what="expired release")
        finally:
            ex.shutdown()

    def test_abandon_releases_staging(self):
        h = _Harness()
        ex = h.executor()
        try:
            f = ex.launch("shed by admission")
            _wait_for(lambda: f.staging is not None, what="prestage")
            ex.abandon(f)
            assert ex._m_wasted["abandoned"].value == 1
            _wait_for(lambda: len(h.released) == 1, what="abandon release")
        finally:
            ex.shutdown()

    def test_abandon_waits_for_last_waiter(self):
        """Two requests share one future (dedupe); the CREATOR is shed
        first — the future must survive for the duplicate still counting
        on it, and die only when the last waiter lets go."""
        h = _Harness(delay=0.2)
        ex = h.executor()
        try:
            a, created_a = ex.launch_tracked("shared")
            b, created_b = ex.launch_tracked("shared")
            assert a is b and created_a and not created_b
            assert a.waiters == 2
            ex.abandon(a)  # the creator is shed: one waiter remains
            assert not a.superseded
            claimed = ex.claim("shared")  # the duplicate still gets it
            assert claimed is a
            assert ex.join(claimed, timeout=5.0)[0] == ["result:shared"]
            # both shed: the future dies exactly once
            c, _ = ex.launch_tracked("both shed")
            d, _ = ex.launch_tracked("both shed")
            assert c is d
            ex.abandon(c)
            ex.abandon(c)
            assert c.superseded
            assert ex._m_wasted["abandoned"].value == 1
        finally:
            ex.shutdown()

    def test_background_sweeper_expires_without_traffic(self):
        """TTL enforcement must not depend on new launches: a future on a
        service that goes quiet expires (and releases its staging) from
        the sweeper thread alone."""
        h = _Harness()
        ex = h.executor(ttl_s=0.6)  # sweeper interval = ttl/2 = 0.3s
        try:
            f = ex.launch("quiet service")
            _wait_for(lambda: f.staging is not None, what="prestage")
            # NO further launches: only the background sweeper can expire it
            _wait_for(
                lambda: ex._m_wasted["expired"].value >= 1,
                timeout=5.0, what="background expiry",
            )
            _wait_for(lambda: len(h.released) == 1, what="staging release")
        finally:
            ex.shutdown()

    def test_deduped_launch_is_not_marked_created(self):
        """Two concurrent requests with the identical prompt share ONE
        future (waiters=2); a shed duplicate only drops its own waiter —
        it must not strand the original request on an inline retrieval."""
        h = _Harness(delay=0.2)
        ex = h.executor()
        try:
            a, created_a = ex.launch_tracked("shared prompt")
            b, created_b = ex.launch_tracked("shared prompt")
            assert a is b and created_a and not created_b
            # the duplicate was shed: its abandon drops one waiter and the
            # future lives on, so the original still claims and joins it
            ex.abandon(b)
            claimed = ex.claim("shared prompt")
            assert claimed is a
            assert ex.join(claimed, timeout=5.0)[0] == ["result:shared prompt"]
        finally:
            ex.shutdown()

    def test_expired_session_speculation_counts_waste_once(self):
        """An expired session speculation dies exactly once: the sweep
        counts it as ``expired`` and clears the session's registry slot,
        so the session's NEXT speculation must not count (or release) the
        same future again as ``superseded``."""
        h = _Harness()
        ex = h.executor(ttl_s=0.2)
        try:
            f1 = ex.speculate("s1", "turn two?")
            _wait_for(lambda: f1.staging is not None, what="prestage")
            time.sleep(0.3)
            assert ex.sweep() == 1
            f2 = ex.speculate("s1", "a different turn two?")
            assert f2 is not None and f2 is not f1
            assert ex._m_wasted["expired"].value == 1
            assert ex._m_wasted["superseded"].value == 0
            _wait_for(lambda: len(h.released) == 1, what="expired release")
            assert len(h.released) == 1  # released once, not twice
        finally:
            ex.shutdown()

    def test_stale_index_future_is_never_served(self):
        h = _Harness()
        ex = h.executor()
        try:
            f = ex.launch("pre-ingest query")
            _wait_for(f.resolved, what="resolve")
            h.gen = 2  # the index grew since launch
            assert ex.claim("pre-ingest query") is None
            assert ex._m_wasted["stale"].value == 1
        finally:
            ex.shutdown()

    def test_join_wait_expiry_is_a_join_timeout(self):
        """join()'s OWN wait expiring raises JoinTimeout (the caller's
        deadline/504 path); a WORKER-side TimeoutError (bounded coalescer
        submit) re-raises as plain TimeoutError — the caller's
        inline-fallback path — and never as JoinTimeout."""
        from rag_llm_k8s_tpu.rag.lookahead import JoinTimeout

        h = _Harness(delay=0.5)
        ex = h.executor(max_workers=1)
        try:
            ex.launch("slow")
            claimed = ex.claim("slow")
            with pytest.raises(JoinTimeout):
                ex.join(claimed, timeout=0.01)
        finally:
            ex.shutdown()

        def coalescer_wedged(text):
            raise TimeoutError("coalescer submit timed out")

        ex2 = LookaheadExecutor(
            _la_cfg(), retrieve_fn=coalescer_wedged,
            registry=obs_metrics.MetricsRegistry(),
        )
        try:
            ex2.launch("wedged")
            claimed = ex2.claim("wedged")
            with pytest.raises(TimeoutError) as ei:
                ex2.join(claimed, timeout=5.0)
            assert not isinstance(ei.value, JoinTimeout)
            # failed joins stay out of the launch-to-join histogram
            assert ex2._m_join_wait.snapshot()[2] == 0
        finally:
            ex2.shutdown()

    def test_injected_fault_surfaces_at_join_not_crash(self):
        h = _Harness()
        ex = h.executor()
        try:
            faults.arm("lookahead_retrieve", 1)
            f = ex.launch("faulted")
            claimed = ex.claim("faulted")
            with pytest.raises(faults.InjectedFault):
                ex.join(claimed, timeout=5.0)
            assert ex._m_wasted["failed"].value == 1
            # the executor stays healthy: the next launch serves normally
            f2 = ex.launch("after fault")
            assert ex.join(ex.claim("after fault"), timeout=5.0)[0] == \
                ["result:after fault"]
        finally:
            faults.clear()
            ex.shutdown()

    def test_shutdown_fails_claimed_queued_future_fast(self):
        """A CLAIMED future still queued behind a busy worker is no longer
        in the registry — shutdown must fail it from the queue drain, so a
        request blocked in join() errors fast (and falls back inline)
        instead of stalling out its whole deadline."""
        h = _Harness(delay=0.3)
        ex = h.executor(max_workers=1)
        ex.launch("busy")  # occupies the only worker
        b = ex.launch("queued behind")
        claimed = ex.claim("queued behind")
        assert claimed is b and not b.resolved()
        ex.shutdown()
        with pytest.raises(RuntimeError):
            ex.join(claimed, timeout=1.0)

    def test_speculative_dedupe_replaces_previous_speculation(self):
        """A speculative launch that DEDUPES onto an existing future still
        honors speculate()'s replace-and-release contract: the session's
        previous speculation is superseded (waste reason 'superseded', not
        a delayed 'expired'), and the slot follows the shared future."""
        h = _Harness()
        ex = h.executor()
        try:
            f_old = ex.speculate("s1", "old topic")
            _wait_for(lambda: f_old.staging is not None, what="prestage")
            f_other = ex.speculate("s2", "shared next topic")
            f_new = ex.speculate("s1", "shared next topic")  # dedupe
            assert f_new is f_other
            _wait_for(
                lambda: ex._m_wasted["superseded"].value >= 1,
                what="old speculation superseded",
            )
            assert f_old.superseded
            assert ex._session_spec["s1"] is f_other
        finally:
            ex.shutdown()

    def test_shutdown_releases_outstanding_staging(self):
        h = _Harness()
        ex = h.executor()
        f = ex.launch("unconsumed")
        _wait_for(lambda: f.staging is not None, what="prestage")
        ex.shutdown()
        assert len(h.released) == 1
        assert ex.launch("post-shutdown") is None


# ---------------------------------------------------------------------------
# prefix-cache staging (stub engine — LRU bookkeeping only)
# ---------------------------------------------------------------------------


class _StubEngine:
    def __init__(self, block_bytes=1 << 20):
        self.block_bytes = block_bytes

    def prefix_buffer_zero(self):
        return (np.zeros(1, np.int8),)

    def build_segment_kv(self, ids, ctx, off):
        return (np.zeros(self.block_bytes, np.int8),)

    def splice_prefix(self, buf, block, off):
        return buf


def _pc_cfg(**kw):
    base = dict(
        enabled=True, max_prefix_tokens=4096, segment_buckets=(64, 2048),
        suffix_buckets=(128,), hbm_budget_mb=64, assembled_cache_entries=8,
    )
    base.update(kw)
    return PrefixCacheConfig(**base)


class TestPrefixCacheStaging:
    def test_release_staged_drops_exactly_what_staging_created(self):
        cache = PrefixCache(_pc_cfg(), _StubEngine(block_bytes=64))
        head = [("head", list(range(8)))]
        cache.prefix_for(head + [("chunk:live", list(range(16)))])
        bytes_before = cache.counters()["prefix_cache_bytes"]
        entries_before = len(cache._entries)

        cp, record = cache.stage(head + [("chunk:spec", list(range(16)))])
        assert cp is not None and record is not None
        # the head entry pre-existed: only the speculative chunk is new
        assert len(record["created"]) == 1
        released = cache.release_staged(record)
        assert released >= 2  # the chunk entry + the new assembled buffer
        assert len(cache._entries) == entries_before
        assert cache.counters()["prefix_cache_bytes"] == bytes_before

    def test_consumed_staging_is_not_released(self):
        cache = PrefixCache(_pc_cfg(), _StubEngine(block_bytes=64))
        segs = [("head", list(range(8))), ("chunk:s", list(range(16)))]
        cp, record = cache.stage(segs)
        # a real request consumed the staged chain before it went stale
        cache.prefix_for(segs)
        assert cache.release_staged(record) == 0
        assert any(k[0] == "chunk:s" for k in cache._entries)

    def test_consumption_during_resolve_is_not_released(self):
        """A hit landing between an entry's creation and the resolve's
        end-of-staging bookkeeping must still count as consumption: the
        staging identity is snapshotted at CREATION (uses=0), so the
        release keeps an entry another request started reusing mid-resolve
        (snapshotting at the end would absorb the bump into uses0 and
        erase the evidence)."""
        cache_ref = []

        class _MidResolveHit(_StubEngine):
            calls = 0

            def build_segment_kv(self, ids, ctx, off):
                self.calls += 1
                if self.calls == 2:  # building B: A created, resolve open
                    cache_ref[0].prefix_for([("A", list(range(8)))])
                return super().build_segment_kv(ids, ctx, off)

        cache = PrefixCache(_pc_cfg(), _MidResolveHit(block_bytes=64))
        cache_ref.append(cache)
        cp, record = cache.stage(
            [("A", list(range(8))), ("B", list(range(16)))]
        )
        assert record is not None and len(record["created"]) == 2
        cache.release_staged(record)
        assert any(k[0] == "A" for k in cache._entries)  # consumed: kept
        assert not any(k[0] == "B" for k in cache._entries)  # stale: gone

    def test_pinned_entries_survive_release(self):
        cache = PrefixCache(_pc_cfg(), _StubEngine(block_bytes=64))
        cache.pin("head")
        cp, record = cache.stage([("head", list(range(8)))])
        cache.release_staged(record)
        assert any(k[0] == "head" for k in cache._entries)

    def test_release_staged_skips_entries_rebuilt_after_eviction(self):
        """Creation-stamp identity: if the STAGED entry was budget-evicted
        and a live request rebuilt a fresh entry at the same key (a rebuild
        also starts at uses=0), the stale release must keep the rebuild —
        the use counter alone cannot tell the two apart."""
        cache = PrefixCache(_pc_cfg(), _StubEngine(block_bytes=64))
        segs = [("chunk:reborn", list(range(16)))]
        cp, record = cache.stage(segs)
        assert record is not None and len(record["created"]) == 1
        cache.clear()  # the staged entry + memo fall to budget pressure
        cache.prefix_for(segs)  # a live request rebuilds at the same key
        bytes_live = cache.counters()["prefix_cache_bytes"]
        assert bytes_live > 0
        assert cache.release_staged(record) == 0
        assert cache.counters()["prefix_cache_bytes"] == bytes_live
        assert any(k[0] == "chunk:reborn" for k in cache._entries)

    def test_stage_of_fully_cached_chain_creates_nothing(self):
        cache = PrefixCache(_pc_cfg(), _StubEngine(block_bytes=64))
        segs = [("head", list(range(8))), ("chunk:c", list(range(16)))]
        cache.prefix_for(segs)
        cp, record = cache.stage(segs)  # memo hit
        assert cp.computed_tokens == 0
        assert record is not None and record["created"] == [] \
            and not record["memo_new"]
        before = cache.counters()["prefix_cache_bytes"]
        assert cache.release_staged(record) == 0
        assert cache.counters()["prefix_cache_bytes"] == before


# ---------------------------------------------------------------------------
# paged pool pre-staging (ContinuousEngine)
# ---------------------------------------------------------------------------


PC = PrefixCacheConfig(
    enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
    suffix_buckets=(16,), hbm_budget_mb=64,
)


class TestPoolPrestage:
    @pytest.fixture(scope="class")
    def px(self):
        cfg = LlamaConfig.tiny(vocab_size=128)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        ec = EngineConfig(
            prompt_buckets=(64,), max_batch_size=2, speculative="off",
            max_seq_len=128, prefix_cache=PC,
        )
        engine = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=ec, dtypes=FP32,
        )
        cont = ContinuousEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=dataclasses.replace(
                ec, kv_paged=True, kv_block_size=16
            ),
            dtypes=FP32,
        )
        return cfg, engine, cont

    def _drain(self, cont, rid, fin):
        outs = {}
        while cont.has_active():
            for r, toks in cont.step():
                outs[r] = toks
        return fin if fin is not None else outs[rid]

    def test_prestage_registers_blocks_and_admission_shares_them(self, px):
        """Pre-staging scatters the chain's full blocks into the pool ahead
        of ANY admission; the first prefixed admission then maps them
        copy-free (zero fresh allocations for the shared span) with greedy
        parity vs a plain full-prompt admission."""
        cfg, engine, cont = px
        rng = np.random.default_rng(11)
        head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 15)))
        chunk = list(map(int, rng.integers(3, 120, 16)))
        segments = [("head:la", head), ("chunk:la", chunk)]
        suffix = list(map(int, rng.integers(3, 120, 6)))
        cp = engine.prefix_cache.prefix_for(segments)
        assert cp.chain_key is not None and cp.length == 32

        base_in_use = cont.kv_pool.blocks_in_use()
        assert cont.prestage_prefix(cp) == "registered"
        registered = cp.length // cont.block_size
        assert cont.kv_pool.blocks_in_use() == base_in_use + registered
        # idempotent — and "resident" marks the OTHER owner, so a second
        # speculation never claims (and later releases) this registration
        assert cont.prestage_prefix(cp) == "resident"

        allocs_before = cont.kv_pool.total_allocs
        _, fin = cont.admit_prefixed(1, suffix, cp, max_new=6)
        got = self._drain(cont, 1, fin)
        # the shared span allocated NOTHING fresh — only tail/suffix/growth
        fresh = cont.kv_pool.total_allocs - allocs_before
        assert fresh < cont.kv_pool.blocks_for(cp.length + len(suffix))
        full = [t for _, seg in segments for t in seg] + suffix
        _, fin2 = cont.admit(2, full, max_new=6)
        assert got == self._drain(cont, 2, fin2)

        # the admission above MAPPED the registration: an only_unused
        # release (the lookahead's stale path) keeps it — live traffic
        # proved the speculation right
        assert cont.release_prestaged(cp.chain_key, only_unused=True) is False
        assert cont.kv_pool.blocks_in_use() == base_in_use + registered
        # unconditional stale-prefetch cancellation: the blocks return
        assert cont.release_prestaged(cp.chain_key) is True
        assert cont.kv_pool.blocks_in_use() == base_in_use
        assert cont.release_prestaged(cp.chain_key) is False  # idempotent

    def test_stale_gen_release_keeps_recreated_registration(self, px):
        """Registration-generation identity: a deferred lookahead release
        presenting the generation it staged must NOT free a registration
        that was evicted and re-created at the same chain key since —
        the re-creation belongs to live traffic (uses resets to 0 on
        re-registration, so only the generation can tell them apart)."""
        cfg, engine, cont = px
        rng = np.random.default_rng(17)
        head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 15)))
        segments = [("head:gen", head), ("chunk:gen", list(map(int, rng.integers(3, 120, 16))))]
        cp = engine.prefix_cache.prefix_for(segments)
        assert cont.prestage_prefix(cp) == "registered"
        gen1 = cont.prestage_gen(cp.chain_key)
        assert gen1 is not None
        # pressure evicts the staged registration, then it is re-created
        assert cont.release_prestaged(cp.chain_key) is True
        assert cont.prestage_prefix(cp) == "registered"
        gen2 = cont.prestage_gen(cp.chain_key)
        assert gen2 != gen1
        in_use = cont.kv_pool.blocks_in_use()
        # the stale deferred release (old generation) must be a no-op
        assert cont.release_prestaged(
            cp.chain_key, only_unused=True, gen=gen1
        ) is False
        assert cont.kv_pool.blocks_in_use() == in_use
        # the current owner still releases cleanly
        assert cont.release_prestaged(cp.chain_key, gen=gen2) is True
        assert cont.kv_pool.blocks_in_use() < in_use

    def test_prestage_respects_pool_headroom(self, px):
        """A pool without a full row's growth headroom refuses to pre-stage
        (live admissions keep their blocks) — the admission_state
        backpressure, applied to speculation."""
        cfg, engine, cont = px
        rng = np.random.default_rng(13)
        head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 15)))
        segments = [("head:tight", head)]
        cp = engine.prefix_cache.prefix_for(segments)
        tight = ContinuousEngine(
            cfg, engine.params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=dataclasses.replace(
                engine.engine_config, kv_paged=True, kv_block_size=16,
                # exactly one row's worth (MB=8): valid construction, but
                # prestage needs full_n + MB free — refused, zero taken
                kv_pool_blocks=8,
            ),
            dtypes=FP32,
        )
        assert tight.prestage_prefix(cp) is False  # no headroom: skipped
        assert tight.kv_pool.blocks_in_use() == 0


# ---------------------------------------------------------------------------
# service-level: byte identity, session pipelining, fault fallback
# ---------------------------------------------------------------------------


SERVICE_PC = PrefixCacheConfig(
    enabled=True, max_prefix_tokens=512, segment_buckets=(64, 128, 256),
    suffix_buckets=(128,), hbm_budget_mb=64,
)


def build_service(tmp, lookahead: bool, prefix_cache: bool = False,
                  ttl_s: float = 30.0):
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    ec_kw = {}
    if prefix_cache:
        ec_kw["prefix_cache"] = SERVICE_PC
    cfg = AppConfig(
        model=llama_cfg, encoder=enc_cfg, system_message="sys",
        lookahead=LookaheadConfig(enabled=lookahead, ttl_s=ttl_s),
    )
    engine = InferenceEngine(
        llama_cfg, init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
        sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
        engine_config=EngineConfig(
            prompt_buckets=(128, 512), max_batch_size=2, speculative="off",
            **ec_kw,
        ),
        dtypes=FP32,
    )
    encoder = EncoderRunner(
        enc_cfg, init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32, 64), max_batch=4,
    )
    # the path is a FILE path (save() writes tmp-then-rename onto it) —
    # never hand it an existing directory like pytest's tmp_path
    store = VectorStore(dim=enc_cfg.hidden_size, path=str(tmp / "store.idx"))
    svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
    svc.ready = True
    return svc, create_app(svc).test_client()


CORPUS = make_pdf(
    "TPU retrieval systems use interchip links for collectives and reach "
    "high decode throughput with paged caches"
)

QUERIES = [
    "what links do TPUs use?",
    "how fast is decode?",
    "what about paged caches?",
    "tell me about collectives",
]


@pytest.fixture(scope="module")
def smoke_pair(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("la")
    svc_off, c_off = build_service(tmp / "off", lookahead=False)
    svc_on, c_on = build_service(tmp / "on", lookahead=True)
    for c in (c_off, c_on):
        r = c.post("/upload_pdf", data={"file": (io.BytesIO(CORPUS), "d.pdf")},
                   content_type="multipart/form-data")
        assert r.status_code == 200, r.get_data()
    yield svc_off, c_off, svc_on, c_on
    svc_on.shutdown()
    svc_off.shutdown()


class TestSmoke:
    """``make lookahead-smoke``: sequential-vs-overlapped byte identity."""

    def test_sequential_streams_byte_identical(self, smoke_pair):
        svc_off, c_off, svc_on, c_on = smoke_pair
        for q in QUERIES:
            a = c_off.post("/query", json={"prompt": q}).get_json()
            b = c_on.post("/query", json={"prompt": q, "session_id": "s0"}).get_json()
            assert a["generated_text"] == b["generated_text"], q
            assert "lookahead_hit" in b["timings"]

    def test_concurrent_streams_byte_identical_and_overlapped(self, smoke_pair):
        svc_off, c_off, svc_on, c_on = smoke_pair

        def run_all(app_client_factory):
            out = {}
            lock = threading.Lock()

            def worker(q):
                c = app_client_factory()
                r = c.post("/query", json={"prompt": q}).get_json()
                with lock:
                    out[q] = r

            ths = [threading.Thread(target=worker, args=(q,)) for q in QUERIES]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            return out

        from rag_llm_k8s_tpu.server.app import create_app as _ca

        off = run_all(lambda: _ca(svc_off).test_client())
        on = run_all(lambda: _ca(svc_on).test_client())
        for q in QUERIES:
            assert off[q]["generated_text"] == on[q]["generated_text"], q
        # overlap really engaged: every lookahead-side request joined a
        # future it launched at the HTTP layer (hit or late, never miss)
        st = svc_on.lookahead.stats()
        assert st["joins"] >= len(QUERIES)
        assert st["overlap_rate"] > 0

    def test_explicit_prelaunch_makes_join_nearly_free(self, smoke_pair):
        _, _, svc_on, c_on = smoke_pair
        q = QUERIES[0]
        fut = svc_on.lookahead.launch(q)
        assert fut is not None
        _wait_for(fut.resolved, what="lookahead resolve")
        body = c_on.post("/query", json={"prompt": q}).get_json()
        assert body["timings"]["lookahead_hit"] == 1.0
        # join-only retrieve: orders of magnitude under the solo stage cost
        assert body["timings"]["embed_retrieve_ms"] < 50.0


class TestSessionPipelining:
    def test_speculation_prestages_next_turn_prefix(self, tmp_path):
        svc, client = build_service(tmp_path, lookahead=True, prefix_cache=True)
        try:
            r = client.post("/upload_pdf",
                            data={"file": (io.BytesIO(CORPUS), "d.pdf")},
                            content_type="multipart/form-data")
            assert r.status_code == 200
            cache = svc.engine.prefix_cache
            r1 = client.post("/query", json={
                "prompt": "what links do TPUs use?", "session_id": "sess",
            })
            assert r1.status_code == 200
            # turn N's speculation resolves + pre-stages during/after decode
            _wait_for(
                lambda: svc.lookahead.stats()["prestaged"] >= 1,
                what="speculative prestage",
            )
            hits_before = cache.counters()["prefix_cache_hits"]
            r2 = client.post("/query", json={
                "prompt": "what about those links and collectives?",
                "session_id": "sess",
            })
            assert r2.status_code == 200
            # the single-chunk corpus makes turn 2 retrieve the same chunk
            # set: its prefix resolve consumes the pre-staged chain
            assert cache.counters()["prefix_cache_hits"] > hits_before
            assert svc.lookahead._m_launched["session"].value >= 1
        finally:
            svc.shutdown()

    def test_superseded_speculation_releases_unconsumed_staging(self, tmp_path):
        svc, client = build_service(tmp_path, lookahead=True, prefix_cache=True)
        try:
            r = client.post("/upload_pdf",
                            data={"file": (io.BytesIO(CORPUS), "d.pdf")},
                            content_type="multipart/form-data")
            assert r.status_code == 200
            ex = svc.lookahead
            # speculative future whose staging nothing ever consumes
            f1 = ex.speculate("lonely", "a topic nobody asks about again")
            assert f1 is not None
            _wait_for(f1.resolved, what="speculation resolve")
            _wait_for(lambda: ex.stats()["prestaged"] >= 1, what="prestage")
            bytes_staged = svc.engine.prefix_cache.counters()["prefix_cache_bytes"]
            assert bytes_staged > 0
            f2 = ex.speculate("lonely", "an entirely different topic")
            assert f2 is not None and f2 is not f1
            _wait_for(
                lambda: ex._m_wasted["superseded"].value >= 1,
                what="supersede",
            )
            _wait_for(
                lambda: ex._m_prestage_released.value >= 1,
                what="stale release",
            )
        finally:
            svc.shutdown()


class _ImmediateSched:
    """run_on_engine stub that executes the task inline — the dispatcher's
    FIFO collapsed to synchronous, so the service wiring (prestage task →
    release task generation threading) is testable without a live loop."""

    def __init__(self, engine):
        self.engine = engine

    def run_on_engine(self, fn):
        fn(self.engine)
        return True


class TestServicePoolWiring:
    def test_release_handle_threads_registration_generation(self, tmp_path):
        """The service handle carries the registration GENERATION from the
        prestage task to the release task: a stale release (its staged
        registration was evicted and re-created since) must keep the new
        registration; the current owner's release must free it."""
        svc, client = build_service(tmp_path, lookahead=True, prefix_cache=True)
        try:
            r = client.post("/upload_pdf",
                            data={"file": (io.BytesIO(CORPUS), "d.pdf")},
                            content_type="multipart/form-data")
            assert r.status_code == 200
            cont = ContinuousEngine(
                svc.config.model, svc.engine.params,
                sampling=svc.engine.sampling,
                engine_config=dataclasses.replace(
                    svc.engine.engine_config, kv_paged=True, kv_block_size=16
                ),
                dtypes=FP32,
            )
            svc.scheduler = _ImmediateSched(cont)
            q = "what links do TPUs use?"
            res = svc._retrieve(q)
            h1 = svc._lookahead_prestage(q, res)
            assert h1 is not None and isinstance(h1["pool"], int)
            ck = h1["chain_key"]
            assert cont.kv_pool.blocks_in_use() > 0
            # pressure evicts the staged registration; live traffic
            # re-creates one at the same chain key (fresh generation)
            assert cont.release_prestaged(ck) is True
            h2 = svc._lookahead_prestage(q, res)
            assert h2 is not None and isinstance(h2["pool"], int)
            assert h2["pool"] != h1["pool"]
            # the STALE release must not free the re-created registration
            svc._lookahead_release(h1)
            assert cont.prestage_gen(ck) == h2["pool"]
            # the current owner's release frees it
            svc._lookahead_release(h2)
            assert cont.prestage_gen(ck) is None
            assert cont.kv_pool.blocks_in_use() == 0
        finally:
            svc.scheduler = None
            svc.shutdown()


class TestShedAbandon:
    def test_queue_deadline_504_abandons_future(self, tmp_path):
        """A request whose deadline expires WHILE QUEUED at the admission
        gate (504, stage=queue) never claimed its future: the handler must
        abandon it, or under sustained overload unclaimed futures pile up
        to the inflight bound and silently disable lookahead."""
        svc, client = build_service(tmp_path, lookahead=True)
        try:
            r = client.post("/upload_pdf",
                            data={"file": (io.BytesIO(CORPUS), "d.pdf")},
                            content_type="multipart/form-data")
            assert r.status_code == 200
            svc.admission.max_concurrency = 1
            svc.admission.max_queue = 1
            with svc.admission.admit():  # hold the only slot
                r = client.post("/query", json={
                    "prompt": "will expire in the queue", "deadline_ms": 60,
                })
            assert r.status_code == 504
            assert r.get_json()["stage"] == "queue"
            _wait_for(
                lambda: svc.lookahead._m_wasted["abandoned"].value >= 1,
                what="queue-expired future abandoned",
            )
        finally:
            svc.shutdown()


class TestFaultContainment:
    def test_lookahead_fault_falls_back_inline(self, tmp_path):
        """Armed ``lookahead_retrieve``: the join surfaces the fault, the
        request retrieves inline and serves the SAME greedy answer."""
        svc, client = build_service(tmp_path, lookahead=True)
        try:
            r = client.post("/upload_pdf",
                            data={"file": (io.BytesIO(CORPUS), "d.pdf")},
                            content_type="multipart/form-data")
            assert r.status_code == 200
            q = "what links do TPUs use?"
            clean = client.post("/query", json={"prompt": q}).get_json()
            faults.arm("lookahead_retrieve", 1)
            faulted = client.post("/query", json={"prompt": q}).get_json()
            assert faulted["generated_text"] == clean["generated_text"]
            assert svc.lookahead._m_wasted["failed"].value >= 1
        finally:
            faults.clear()
            svc.shutdown()


class TestConfig:
    def test_env_roundtrip(self):
        cfg = AppConfig.from_env({
            "TPU_RAG_LOOKAHEAD": "1",
            "TPU_RAG_LOOKAHEAD_WORKERS": "3",
            "TPU_RAG_LOOKAHEAD_INFLIGHT": "5",
            "TPU_RAG_LOOKAHEAD_TTL_S": "7.5",
            "TPU_RAG_LOOKAHEAD_PRESTAGE": "0",
            "TPU_RAG_LOOKAHEAD_SESSIONS": "0",
            "TPU_RAG_LOOKAHEAD_SESSION_TURNS": "4",
            "TPU_RAG_LOOKAHEAD_SESSION_MAX": "32",
            "TPU_RAG_LOOKAHEAD_SESSION_TTL_S": "120",
        })
        la = cfg.lookahead
        assert la.enabled and la.max_workers == 3 and la.max_inflight == 5
        assert la.ttl_s == 7.5
        assert not la.prestage_kv and not la.session_pipelining
        assert la.session_context_turns == 4
        assert la.session_max == 32 and la.session_ttl_s == 120.0

    def test_env_validation(self):
        with pytest.raises(ValueError):
            AppConfig.from_env({"TPU_RAG_LOOKAHEAD": "yes"})
        with pytest.raises(ValueError):
            AppConfig.from_env({"TPU_RAG_LOOKAHEAD_WORKERS": "0"})

    def test_default_off(self):
        assert not AppConfig().lookahead.enabled
        # a service built from defaults has no executor
        assert not AppConfig.from_env({}).lookahead.enabled
