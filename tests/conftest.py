"""Test harness: force an 8-virtual-device CPU platform BEFORE jax import.

The reference has no tests (survey §4); this suite follows the survey's
recommended strategy — mesh/sharding code runs on CPU-simulated devices so
multi-chip paths are exercised without a TPU slice.
"""

import os

# Must be set before jax (or anything importing jax) loads. Force-set (not
# setdefault): the ambient environment may point JAX_PLATFORMS at a TPU tunnel,
# but the suite is designed for the 8-virtual-device CPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The environment's sitecustomize may have imported jax already (freezing the
# platform config from env), so env vars alone are not enough — update the
# live config too.
jax.config.update("jax_platforms", "cpu")


def set_mesh(mesh):
    """Ambient-mesh context, version-portable: ``jax.set_mesh`` on jax>=0.7,
    entering the Mesh itself (the historical spelling with the same
    axis-name-resolution semantics for traced collectives) before that."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture(scope="session")
def mesh8(devices8):
    from rag_llm_k8s_tpu.core import MeshConfig
    from rag_llm_k8s_tpu.core.mesh import make_mesh

    return make_mesh(MeshConfig(dp=2, sp=1, tp=4), devices=devices8)


@pytest.fixture(scope="session")
def mesh_tp8(devices8):
    from rag_llm_k8s_tpu.core import MeshConfig
    from rag_llm_k8s_tpu.core.mesh import make_mesh

    return make_mesh(MeshConfig(dp=1, sp=1, tp=8), devices=devices8)
