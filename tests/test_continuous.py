"""Continuous (slot-based) batching: greedy parity with the one-shot engine,
mid-generation admission, slot reuse, and the scheduler's no-head-of-line
guarantee (BASELINE config #5)."""

import threading
import time

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
ENG_CFG = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    oracle = InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
    )
    return cfg, params, oracle


def make_engine(cfg, params):
    return ContinuousEngine(
        cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
    )


class TestContinuousEngine:
    def test_greedy_parity_with_oneshot(self, setup):
        cfg, params, oracle = setup
        eng = make_engine(cfg, params)
        prompts = [[3, 17, 42, 7, 99], [5, 5, 8], [11] * 12]
        want = [oracle.generate([p])[0] for p in prompts]

        for rid, p in enumerate(prompts):
            _, finished = eng.admit(rid, p, GREEDY.max_new_tokens)
            assert finished is None
        results = {}
        for _ in range(GREEDY.max_new_tokens + 1):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert [results[i] for i in range(len(prompts))] == want

    def test_mid_generation_admission(self, setup):
        """A request admitted after several decode steps of another must
        produce exactly its solo greedy continuation."""
        cfg, params, oracle = setup
        eng = make_engine(cfg, params)
        p1, p2 = [3, 17, 42, 7, 99], [5, 5, 8]
        want1 = oracle.generate([p1])[0]
        want2 = oracle.generate([p2])[0]

        eng.admit(1, p1, GREEDY.max_new_tokens)
        results = {}
        for _ in range(3):  # run p1 alone for a few steps
            for rid, toks in eng.step():
                results[rid] = toks
        eng.admit(2, p2, GREEDY.max_new_tokens)  # joins mid-flight
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results[1] == want1
        assert results[2] == want2

    def test_slot_reuse_is_clean(self, setup):
        """A slot freed by a finished request must not leak stale KV into
        the next occupant."""
        cfg, params, oracle = setup
        eng = make_engine(cfg, params)
        rng = np.random.RandomState(0)
        for round_i in range(3):  # same slot reused every round (B=4, 1 req)
            p = rng.randint(2, cfg.vocab_size, 10).tolist()
            want = oracle.generate([p])[0]
            _, finished = eng.admit(round_i, p, GREEDY.max_new_tokens)
            results = {}
            while eng.has_active():
                for rid, toks in eng.step():
                    results[rid] = toks
            assert results[round_i] == want, f"round {round_i}"

    def test_more_requests_than_slots(self, setup):
        cfg, params, oracle = setup
        eng = make_engine(cfg, params)
        sched = ContinuousScheduler(eng)
        try:
            prompts = [[3, 17, 42], [5, 5, 8], [9, 9], [2, 4, 6, 8], [7] * 5, [1]]
            want = [oracle.generate([p])[0] for p in prompts]
            outs = [None] * len(prompts)

            def run(i):
                outs[i] = sched.submit(prompts[i], timeout=120)

            threads = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert outs == want
        finally:
            sched.shutdown()


class TestNoHeadOfLineBlocking:
    def test_late_arrival_completes_before_long_job(self, setup):
        """THE continuous-batching property: a short request arriving while a
        long one is mid-generation finishes first — it does not wait for the
        long request's slot to free (the coalescing scheduler made it wait
        for the whole previous batch)."""
        cfg, params, _ = setup
        eng = ContinuousEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=40),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=4, max_seq_len=64
            ),
            dtypes=FP32,
        )
        sched = ContinuousScheduler(eng)
        try:
            order = []
            lock = threading.Lock()

            def run(name, prompt, max_new):
                sched.submit(prompt, max_new_tokens=max_new, timeout=120)
                with lock:
                    order.append((name, eng.steps))

            t_long = threading.Thread(target=run, args=("long", [3, 17, 42], 40))
            t_long.start()
            # let the long request decode a few steps before the short arrives
            while eng.steps < 3:
                time.sleep(0.01)
            t_short = threading.Thread(target=run, args=("short", [5, 5], 4))
            t_short.start()
            t_short.join(timeout=120)
            t_long.join(timeout=120)
            assert [n for n, _ in order] == ["short", "long"]
            # and the short one finished long before the long one's last step
            steps = dict(order)
            assert steps["short"] < steps["long"]
        finally:
            sched.shutdown()


class TestPerRequestSeed:
    def test_seeded_request_is_batch_invariant(self, setup):
        """A seeded sampling request draws identically whether it runs solo
        or shares the batch with other requests (per-row position-keyed
        PRNG), and different seeds diverge."""
        cfg, params, _ = setup
        samp = SamplingConfig(do_sample=True, temperature=1.0, top_p=1.0,
                              max_new_tokens=6)

        def fresh():
            return ContinuousEngine(
                cfg, params, sampling=samp, engine_config=ENG_CFG, dtypes=FP32
            )

        def run(eng, reqs):
            results = {}
            for rid, (p, seed) in enumerate(reqs):
                _, fin = eng.admit(rid, p, samp.max_new_tokens, seed=seed)
                assert fin is None
            while eng.has_active():
                for rid, toks in eng.step():
                    results[rid] = toks
            return results

        p = [3, 17, 42, 7]
        solo = run(fresh(), [(p, 123)])[0]
        # same request with two noisy companions in the batch
        shared = run(fresh(), [(p, 123), ([5, 5], None), ([9, 9, 9], None)])[0]
        assert solo == shared  # batchmates must not perturb seeded draws
        other = run(fresh(), [(p, 124)])[0]
        assert other != solo  # different seed -> different draws

    def test_scheduler_honors_seed(self, setup):
        cfg, params, _ = setup
        samp = SamplingConfig(do_sample=True, temperature=1.0, top_p=1.0,
                              max_new_tokens=6)
        eng = ContinuousEngine(
            cfg, params, sampling=samp, engine_config=ENG_CFG, dtypes=FP32
        )
        sched = ContinuousScheduler(eng)
        try:
            a = sched.submit([3, 17, 42], seed=7, timeout=120)
            b = sched.submit([3, 17, 42], seed=7, timeout=120)
            c = sched.submit([3, 17, 42], seed=8, timeout=120)
            assert a == b
            assert c != a
        finally:
            sched.shutdown()


class TestDispatcherSurvivesStepFailure:
    def test_step_error_recovers_transparently_by_default(self, setup):
        """ISSUE 4: a transient device error inside step() is INVISIBLE to
        the caller — the scheduler resets, resubmits the in-flight request
        (token budget reduced by what was already emitted), and the result
        still matches the solo greedy oracle."""
        cfg, params, oracle = setup
        want = oracle.generate([[3, 17, 42]])[0]
        eng = make_engine(cfg, params)
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            boom = RuntimeError("synthetic device failure")
            real_step = eng.step
            calls = {"n": 0}

            def flaky_step():
                calls["n"] += 1
                if calls["n"] == 2:
                    raise boom
                return real_step()

            eng.step = flaky_step
            out = sched.submit([3, 17, 42], timeout=120)
            # the failure really happened AND the resubmission seamlessly
            # continued the emitted stream (greedy: identical to solo)
            assert calls["n"] >= 2
            assert out == want
            # still serving afterwards
            eng.step = real_step
            out2 = sched.submit([5, 5, 8], timeout=120)
            assert isinstance(out2, list) and out2
        finally:
            sched.shutdown()

    def test_step_error_fails_waiters_with_retries_disabled(self, setup):
        """retries=0 restores the fail-on-first-fault contract: the error
        reaches in-flight callers and the scheduler keeps serving."""
        cfg, params, _ = setup
        eng = make_engine(cfg, params)
        sched = ContinuousScheduler(eng, retries=0)
        try:
            boom = RuntimeError("synthetic device failure")
            real_step = eng.step
            calls = {"n": 0}

            def flaky_step():
                calls["n"] += 1
                if calls["n"] == 2:
                    raise boom
                return real_step()

            eng.step = flaky_step
            with pytest.raises(RuntimeError, match="synthetic device failure"):
                sched.submit([3, 17, 42], timeout=120)
            eng.step = real_step
            # the dispatcher must still be alive and serving
            out = sched.submit([5, 5, 8], timeout=120)
            assert isinstance(out, list) and out
        finally:
            sched.shutdown()


class TestContinuousOnMesh:
    def test_tp_mesh_greedy_parity(self, setup):
        """Continuous batching on a tp>1 mesh with SHARDED params: the
        executables must be lowered with the state shardings they receive
        (an unsharded lowering rejects every admit with 'sharding does not
        match' → EngineStateLost on each request — a total serving outage
        of the default scheduler on any multi-chip deployment)."""
        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg, params, oracle = setup
        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        placed = shard_llama_params(params, ctx)
        eng = ContinuousEngine(
            cfg, placed, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32,
            mesh=ctx,
        )
        prompts = [[3, 17, 42, 7, 99], [5, 5, 8]]
        want = [oracle.generate([p])[0] for p in prompts]
        for rid, p in enumerate(prompts):
            _, fin = eng.admit(rid, p, GREEDY.max_new_tokens)
            assert fin is None
        results = {}
        for _ in range(GREEDY.max_new_tokens + 1):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert [results[i] for i in range(len(prompts))] == want

    def test_tp_mesh_int8_kv(self, setup):
        """Same mesh path with the int8 cache: sharded scale planes ride
        along (kv-head axis over tp)."""
        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg, params, _ = setup
        import dataclasses

        ec = dataclasses.replace(ENG_CFG, kv_quant="int8")
        ref = InferenceEngine(
            cfg, params, sampling=GREEDY, engine_config=ec, dtypes=FP32
        ).generate([[3, 17, 42]])[0]
        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        eng = ContinuousEngine(
            cfg, shard_llama_params(params, ctx), sampling=GREEDY,
            engine_config=ec, dtypes=FP32, mesh=ctx,
        )
        _, fin = eng.admit(1, [3, 17, 42], GREEDY.max_new_tokens)
        assert fin is None
        results = {}
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results[1] == ref


class TestResetRebuildsDeviceState:
    def test_recovery_after_donated_buffers_invalidated(self, setup):
        """A step failing DURING device execution has already consumed its
        donated inputs (cache, kv_len, last_tok, active). reset() must
        rebuild them, or the engine serves 'Array has been deleted' forever
        while reporting healthy."""
        cfg, params, _ = setup
        eng = make_engine(cfg, params)
        _, fin = eng.admit(1, [3, 17, 42], GREEDY.max_new_tokens)
        assert fin is None
        eng.step()
        # simulate the donation outcome of a mid-execution failure
        for buf in (*eng._cache, eng._kv_len, eng._last_tok, eng._active):
            buf.delete()
        eng.reset()
        # the engine must serve again, correctly
        oracle = InferenceEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32
        )
        want = oracle.generate([[5, 5, 8]])[0]
        _, fin = eng.admit(2, [5, 5, 8], GREEDY.max_new_tokens)
        assert fin is None
        results = {}
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results[2] == want


class TestShutdownDrainsWaiters:
    def test_inflight_callers_unblock_on_shutdown(self, setup):
        """shutdown() while requests are mid-generation must error them out,
        not leave timeout=None callers blocked forever."""
        cfg, params, _ = setup
        eng = ContinuousEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=2000),
            engine_config=EngineConfig(
                prompt_buckets=(16,), max_batch_size=4, max_seq_len=2048
            ),
            dtypes=FP32,
        )
        sched = ContinuousScheduler(eng)
        errors = []

        def run():
            try:
                sched.submit([3, 17, 42], timeout=None)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=run)
        t.start()
        while eng.steps < 2:  # definitely mid-generation
            time.sleep(0.01)
        sched.shutdown()
        t.join(timeout=30)
        assert not t.is_alive(), "caller still blocked after shutdown"
        assert errors and "shut down" in str(errors[0])


class TestMultiStepSync:
    """decode_sync_steps > 1: k decode steps run as ONE device program
    (lax.scan) with a single [k, B] host fetch — outputs must be identical
    to per-step sync, including EOS mid-window and budget mid-window."""

    def _engine(self, cfg, params, k, sampling=GREEDY, eng_cfg=ENG_CFG):
        import dataclasses
        return ContinuousEngine(
            cfg, params, sampling=sampling,
            engine_config=dataclasses.replace(eng_cfg, decode_sync_steps=k),
            dtypes=FP32,
        )

    def _drain(self, eng, reqs):
        results = {}
        for rid, p, mn in reqs:
            _, finished = eng.admit(rid, p, mn)
            if finished is not None:
                results[rid] = finished
        for _ in range(200):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        return results

    def test_greedy_parity_with_per_step_sync(self, setup):
        cfg, params, oracle = setup
        prompts = [[3, 17, 42, 7, 99], [5, 5, 8], [11] * 12, [2, 9]]
        want = {i: oracle.generate([p])[0] for i, p in enumerate(prompts)}
        for k in (3, 8):
            eng = self._engine(cfg, params, k)
            got = self._drain(eng, [(i, p, GREEDY.max_new_tokens) for i, p in enumerate(prompts)])
            assert got == want, f"k={k}"

    def test_budget_ends_mid_window(self, setup):
        """max_new not a multiple of k: the extra window steps the device ran
        past the budget must be discarded, not emitted."""
        cfg, params, oracle = setup
        p = [3, 17, 42, 7, 99]
        want = oracle.generate([p], max_new_tokens=5)[0]
        eng = self._engine(cfg, params, 4)
        got = self._drain(eng, [(1, p, 5)])
        assert got[1] == want
        assert len(got[1]) == len(want) == 5

    def test_mid_flight_admission_between_windows(self, setup):
        cfg, params, oracle = setup
        p1, p2 = [3, 17, 42, 7, 99], [5, 5, 8]
        want1 = oracle.generate([p1])[0]
        want2 = oracle.generate([p2])[0]
        eng = self._engine(cfg, params, 3)
        eng.admit(1, p1, GREEDY.max_new_tokens)
        results = {}
        for rid, toks in eng.step():  # one 3-step window with p1 alone
            results[rid] = toks
        eng.admit(2, p2, GREEDY.max_new_tokens)  # joins between windows
        for _ in range(200):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert results == {1: want1, 2: want2}

    def test_sampled_parity_with_per_step_sync(self, setup):
        """Seeded sampling: draws are (seed, position)-keyed, so the window
        size must not change what a request samples."""
        cfg, params, _ = setup
        sampling = SamplingConfig(do_sample=True, temperature=0.8, top_p=0.9,
                                  max_new_tokens=8, seed=7)
        p = [3, 17, 42, 7, 99]
        e1 = self._engine(cfg, params, 1, sampling=sampling)
        _, f1 = e1.admit(1, p, 8, seed=123)
        assert f1 is None
        r1 = self._drain_one(e1, 1)
        e4 = self._engine(cfg, params, 4, sampling=sampling)
        _, f4 = e4.admit(1, p, 8, seed=123)
        assert f4 is None
        r4 = self._drain_one(e4, 1)
        assert r1 == r4

    @staticmethod
    def _drain_one(eng, rid):
        for _ in range(200):
            for got_rid, toks in eng.step():
                if got_rid == rid:
                    return toks
            if not eng.has_active():
                break
        raise AssertionError("request never completed")

    def test_eos_mid_window_freezes_row(self, setup):
        """A row that samples EOS mid-window must stop there (post-EOS window
        tokens discarded) while a batchmate keeps decoding — k=1 parity is
        the oracle. The EOS id is chosen from the greedy stream itself so the
        hit genuinely lands mid-window."""
        import dataclasses
        cfg, params, oracle = setup
        p1, p2 = [3, 17, 42, 7, 99], [5, 5, 8]
        stream = oracle.generate([p1])[0]
        eos_tok = stream[4]  # EOS strikes at the 5th token: mid-window for k=4
        cfg_eos = dataclasses.replace(cfg, eos_token_ids=(eos_tok,))
        outs = {}
        for k in (1, 4):
            eng = self._engine(cfg_eos, params, k)
            outs[k] = self._drain(eng, [(1, p1, 8), (2, p2, 8)])
        assert outs[1] == outs[4]
        assert len(outs[1][1]) < 8, "EOS never fired — the fixture is vacuous"
        assert outs[1][1] == stream[:len(outs[1][1])]


class TestBatchedAdmission:
    """admit_many: a group of queued requests prefills together (one batched
    forward per bucket chunk, one first-token fetch) — results must be
    identical to admitting each request alone."""

    def test_group_equals_solo_admission(self, setup):
        cfg, params, oracle = setup
        prompts = [[3, 17, 42, 7, 99], [5, 5, 8], [11] * 12, [2, 9]]
        want = {i: oracle.generate([p])[0] for i, p in enumerate(prompts)}
        eng = make_engine(cfg, params)
        outs = eng.admit_many(
            [(i, p, GREEDY.max_new_tokens, None) for i, p in enumerate(prompts)]
        )
        results = {i: fin for (i, p), (_, fin) in zip(enumerate(prompts), outs) if fin}
        for _ in range(200):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert results == want

    def test_mixed_buckets_in_one_group(self, setup):
        """Requests landing in different buckets split into per-bucket
        chunks but still admit in one call."""
        cfg, params, oracle = setup
        prompts = [[3] * 4, [7] * 20, [9] * 5, [4] * 30]  # buckets 16 and 32
        want = {i: oracle.generate([p])[0] for i, p in enumerate(prompts)}
        eng = make_engine(cfg, params)
        eng.admit_many([(i, p, GREEDY.max_new_tokens, None) for i, p in enumerate(prompts)])
        results = {}
        for _ in range(200):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert results == want

    def test_seeded_draws_independent_of_grouping(self, setup):
        cfg, params, _ = setup
        sampling = SamplingConfig(do_sample=True, temperature=0.8, top_p=0.9,
                                  max_new_tokens=6, seed=0)
        p1, p2 = [3, 17, 42], [5, 9, 2, 7]

        def run(grouped):
            eng = ContinuousEngine(cfg, params, sampling=sampling,
                                   engine_config=ENG_CFG, dtypes=FP32)
            if grouped:
                eng.admit_many([(1, p1, 6, 11), (2, p2, 6, 22)])
            else:
                eng.admit(1, p1, 6, seed=11)
                eng.admit(2, p2, 6, seed=22)
            results = {}
            for _ in range(100):
                for rid, toks in eng.step():
                    results[rid] = toks
                if not eng.has_active():
                    break
            return results

        assert run(True) == run(False)

    def test_early_eos_in_group_frees_slot(self, setup):
        """A request whose FIRST token is EOS finishes inside the group and
        its slot is immediately reusable."""
        cfg, params, oracle = setup
        import dataclasses
        p_live, p_dead = [5, 5, 8], [3, 17, 42, 7, 99]
        first = oracle.generate([p_dead], max_new_tokens=1)[0][0]
        cfg_eos = dataclasses.replace(cfg, eos_token_ids=(first,))
        oracle2 = InferenceEngine(cfg_eos, params, sampling=GREEDY,
                                  engine_config=ENG_CFG, dtypes=FP32)
        want_live = oracle2.generate([p_live])[0]
        eng = ContinuousEngine(cfg_eos, params, sampling=GREEDY,
                               engine_config=ENG_CFG, dtypes=FP32)
        outs = eng.admit_many([(1, p_dead, 8, None), (2, p_live, 8, None)])
        assert outs[0][1] == []  # finished instantly at EOS
        assert outs[1][1] is None
        assert len(eng.free_slots()) == ENG_CFG.max_batch_size - 1
        results = {}
        for _ in range(100):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert results == {2: want_live}

    def test_scheduler_groups_concurrent_submits(self, setup):
        """Concurrent scheduler submits land as grouped admissions (fewer
        prefill fetches) with unchanged results."""
        cfg, params, oracle = setup
        prompts = [[3, 17, 42, 7, 99], [5, 5, 8], [11] * 12, [2, 9]]
        want = [oracle.generate([p])[0] for p in prompts]
        eng = make_engine(cfg, params)
        sched = ContinuousScheduler(eng)
        results = [None] * len(prompts)

        def run(i):
            results[i] = sched.submit(prompts[i], timeout=120)

        threads = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sched.shutdown()
        assert results == want

    def test_chunk_failure_isolated_to_its_items(self, setup, monkeypatch):
        """A failed admission chunk fails ONLY its own requests; other
        chunks' admissions stand and decode to completion."""
        cfg, params, oracle = setup
        eng = make_engine(cfg, params)
        p16, p32 = [3] * 4, [7] * 20  # buckets 16 and 32
        want16 = oracle.generate([p16])[0]
        real = eng._admit_chunk

        def flaky(S, chunk, rows, results):
            if S == 32:
                raise RuntimeError("synthetic chunk failure")
            return real(S, chunk, rows, results)

        monkeypatch.setattr(eng, "_admit_chunk", flaky)
        outs = eng.admit_many([(1, p16, GREEDY.max_new_tokens, None),
                               (2, p32, GREEDY.max_new_tokens, None)])
        assert not isinstance(outs[0], BaseException)
        assert isinstance(outs[1], RuntimeError)
        results = {}
        for _ in range(100):
            for rid, toks in eng.step():
                results[rid] = toks
            if not eng.has_active():
                break
        assert results == {1: want16}
        # the single-admit wrapper re-raises per-item errors
        monkeypatch.setattr(eng, "_admit_chunk", flaky)
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="synthetic"):
            eng.admit(3, p32, 4)


class TestAdmitChunkFailureReleasesRows:
    """A failure AFTER the batched insert spliced rows device-active (e.g.
    the tok0 fetch dying) must not leave those rows decoding garbage
    forever with no host _Slot to retire them: _admit_chunk deactivates the
    chunk's rows on device and resets their slots before per-chunk
    isolation swallows the error (ADVICE r4 #1)."""

    def test_post_insert_failure_deactivates_rows(self, setup):
        cfg, params, _ = setup
        eng = make_engine(cfg, params)

        class BoomList(list):
            def __setitem__(self, i, v):
                raise RuntimeError("boom")

        prompts = [[3, 17, 42], [5, 5, 8]]
        prepared = []
        for i, p in enumerate(prompts):
            key = jax.random.PRNGKey(i)
            prepared.append((i, i, 16, p, 4, key))
        with pytest.raises(RuntimeError, match="boom"):
            eng._admit_chunk(16, prepared, [0, 1], BoomList([None, None]))
        # rows released on device AND on host
        assert not np.asarray(eng._active)[:2].any()
        assert all(not s.active for s in eng.slots)
        # the engine still serves: a real admission on the same rows works
        outs = eng.admit_many([(9, [3, 17, 42], 4, None)])
        assert outs[0][1] is None or isinstance(outs[0][1], list)
        for _ in range(50):
            done = eng.step()
            if done:
                assert done[0][0] == 9
                break
        else:
            raise AssertionError("request 9 never completed")
