"""Regenerate the committed tokenizer.json parity fixtures.

The HF ``tokenizers`` Unigram trainer is nondeterministic run-to-run
(multithreaded EM), and Viterbi path scores for punctuation runs like
``!!!`` can tie at float-ulp level, so parity tests against a FRESHLY
trained model are flaky by construction. Training once and committing the
resulting ``tokenizer.json`` files makes the parity suite deterministic
while still comparing against the live Rust engine at test time.

Run from the repo root:  python tests/fixtures/gen_tokenizers.py
"""

import os

from tokenizers import Regex, Tokenizer, normalizers
from tokenizers.decoders import ByteLevel as ByteLevelDecoder
from tokenizers.models import BPE, Unigram
from tokenizers.pre_tokenizers import ByteLevel, Metaspace
from tokenizers.trainers import BpeTrainer, UnigramTrainer

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "tokenizers")

CORPUS = [
    "The Technology Radar is a snapshot of tools, techniques, platforms and languages.",
    "Retrieval-augmented generation improves factuality of large language models.",
    "TPU v5e slices communicate over ICI links; XLA emits the collectives.",
    "def split_text(text, chunk_size=1000, overlap=200):",
    "Hello world! 12345 -- naive tokenization tests, with punctuation...",
    "Multilingual text: cafe, uber, naive.",
] * 8

MULTI_CORPUS = CORPUS + [
    "기술 레이더는 도구, 기법, 플랫폼의 스냅샷입니다.",
    "검색 증강 생성은 대규모 언어 모델의 사실성을 개선합니다.",
    "日本語のテキストも正しく分割されるべきです。",
    "café naïve über résumé — ça va?",
    "emoji test 🚀 🧭 fin",
] * 8


def gen_bpe(path: str, corpus, vocab_size=400, special=()):
    tok = Tokenizer(BPE(unk_token=None))
    tok.pre_tokenizer = ByteLevel(add_prefix_space=False, use_regex=True)
    tok.decoder = ByteLevelDecoder()
    trainer = BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=list(special),
        initial_alphabet=ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(path)


def gen_unigram(path: str, corpus, vocab_size=300, normalized=False):
    tok = Tokenizer(Unigram())
    if normalized:
        # declarative equivalent of bge-m3's Precompiled nmt_nfkc charsmap
        # (the trainer cannot emit a Precompiled node)
        tok.normalizer = normalizers.Sequence(
            [
                normalizers.NFKC(),
                normalizers.Replace(Regex(r"\s+"), " "),
                normalizers.Strip(),
            ]
        )
    tok.pre_tokenizer = Metaspace()
    trainer = UnigramTrainer(
        vocab_size=vocab_size,
        special_tokens=["<s>", "</s>", "<unk>"],
        unk_token="<unk>",
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(path)


def main():
    os.makedirs(OUT, exist_ok=True)
    gen_bpe(
        os.path.join(OUT, "bpe_ascii.json"),
        CORPUS,
        special=["<|begin_of_text|>", "<|end_of_text|>"],
    )
    gen_bpe(os.path.join(OUT, "bpe_multi.json"), MULTI_CORPUS)
    gen_unigram(os.path.join(OUT, "unigram_plain.json"), CORPUS)
    gen_unigram(
        os.path.join(OUT, "unigram_norm.json"), MULTI_CORPUS, vocab_size=600,
        normalized=True,
    )
    print("fixtures written to", OUT)




# ---------------------------------------------------------------------------
# TRUE-SCALE fixtures (VERDICT r3 #6): Llama-3-scale 128k byte-level BPE and
# a ~250k-piece Unigram, the vocab sizes the reference's real tokenizer.json
# files carry (Meta-Llama-3.1-8B: 128k BPE; bge-m3/XLM-R: 250k Unigram —
# rag.py:25,33). Zero egress: the corpus is the environment's own Python
# sources (~0.5 GB available), the BPE is TRAINED with the live Rust
# tokenizers engine, and the Unigram spec is synthesized from corpus word/
# continuation statistics (EM training adds nothing for parity testing —
# what matters is a quarter-million-piece vocab with realistic score spread
# flowing through trie construction, Viterbi, and unk handling).
#
# These are NOT committed (tests/fixtures/tokenizers_scale/ is gitignored;
# ~13 MB, rebuilt deterministically in ~40 s and cached per environment).

SCALE_OUT = os.path.join(HERE, "tokenizers_scale")


def _harvest_corpus(target_mb: float = 64.0):
    """Deterministic sample of the environment's Python sources."""
    import glob
    import random
    import site

    roots = [os.path.dirname(os.__file__)] + site.getsitepackages()
    paths = []
    for root in roots:
        paths += glob.glob(os.path.join(root, "**", "*.py"), recursive=True)
    paths.sort()
    random.Random(0).shuffle(paths)
    texts, total = [], 0
    for p in paths:
        try:
            with open(p, encoding="utf-8", errors="ignore") as f:
                t = f.read()
        except OSError:
            continue
        texts.append(t)
        total += len(t)
        if total > target_mb * 1e6:
            break
    return texts


def gen_scale_bpe(path: str, texts, vocab_size: int = 128000):
    tok = Tokenizer(BPE(unk_token=None))
    tok.pre_tokenizer = ByteLevel(add_prefix_space=False, use_regex=True)
    tok.decoder = ByteLevelDecoder()
    trainer = BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<|begin_of_text|>", "<|end_of_text|>"],
        initial_alphabet=ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(texts, trainer)
    tok.save(path)
    return tok.get_vocab_size()


def gen_scale_unigram(path: str, texts, n_pieces: int = 250000):
    import collections
    import math

    words = collections.Counter()
    chars = collections.Counter()
    for t in texts:
        for w in t.split():
            w = w[:16]
            words["▁" + w] += 1
            if len(w) > 1:
                words[w] += 1  # continuation piece (mid-word match)
        chars.update(t.replace(" ", "▁"))
    total = sum(words.values()) + sum(chars.values())
    vocab = [("<unk>", 0.0)]
    seen = {"<unk>"}
    for ch, c in chars.items():  # full char coverage first
        if ch not in seen:
            vocab.append((ch, math.log(max(c, 1) / total)))
            seen.add(ch)
    for w, c in words.most_common():
        if len(vocab) >= n_pieces:
            break
        if w not in seen:
            vocab.append((w, math.log(c / total)))
            seen.add(w)
    tok = Tokenizer(Unigram(vocab=vocab, unk_id=0))
    tok.pre_tokenizer = Metaspace()
    tok.save(path)
    return len(vocab)


def gen_scale(out: str = SCALE_OUT):
    os.makedirs(out, exist_ok=True)
    texts = _harvest_corpus()
    nb = gen_scale_bpe(os.path.join(out, "bpe_128k.json"), texts)
    nu = gen_scale_unigram(os.path.join(out, "unigram_250k.json"), texts)
    print(f"scale fixtures written to {out}: bpe vocab {nb}, unigram pieces {nu}")
    return out


if __name__ == "__main__":
    import sys
    if "--scale" in sys.argv:
        gen_scale()
        sys.exit(0)
    main()
