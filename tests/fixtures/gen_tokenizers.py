"""Regenerate the committed tokenizer.json parity fixtures.

The HF ``tokenizers`` Unigram trainer is nondeterministic run-to-run
(multithreaded EM), and Viterbi path scores for punctuation runs like
``!!!`` can tie at float-ulp level, so parity tests against a FRESHLY
trained model are flaky by construction. Training once and committing the
resulting ``tokenizer.json`` files makes the parity suite deterministic
while still comparing against the live Rust engine at test time.

Run from the repo root:  python tests/fixtures/gen_tokenizers.py
"""

import os

from tokenizers import Regex, Tokenizer, normalizers
from tokenizers.decoders import ByteLevel as ByteLevelDecoder
from tokenizers.models import BPE, Unigram
from tokenizers.pre_tokenizers import ByteLevel, Metaspace
from tokenizers.trainers import BpeTrainer, UnigramTrainer

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "tokenizers")

CORPUS = [
    "The Technology Radar is a snapshot of tools, techniques, platforms and languages.",
    "Retrieval-augmented generation improves factuality of large language models.",
    "TPU v5e slices communicate over ICI links; XLA emits the collectives.",
    "def split_text(text, chunk_size=1000, overlap=200):",
    "Hello world! 12345 -- naive tokenization tests, with punctuation...",
    "Multilingual text: cafe, uber, naive.",
] * 8

MULTI_CORPUS = CORPUS + [
    "기술 레이더는 도구, 기법, 플랫폼의 스냅샷입니다.",
    "검색 증강 생성은 대규모 언어 모델의 사실성을 개선합니다.",
    "日本語のテキストも正しく分割されるべきです。",
    "café naïve über résumé — ça va?",
    "emoji test 🚀 🧭 fin",
] * 8


def gen_bpe(path: str, corpus, vocab_size=400, special=()):
    tok = Tokenizer(BPE(unk_token=None))
    tok.pre_tokenizer = ByteLevel(add_prefix_space=False, use_regex=True)
    tok.decoder = ByteLevelDecoder()
    trainer = BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=list(special),
        initial_alphabet=ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(path)


def gen_unigram(path: str, corpus, vocab_size=300, normalized=False):
    tok = Tokenizer(Unigram())
    if normalized:
        # declarative equivalent of bge-m3's Precompiled nmt_nfkc charsmap
        # (the trainer cannot emit a Precompiled node)
        tok.normalizer = normalizers.Sequence(
            [
                normalizers.NFKC(),
                normalizers.Replace(Regex(r"\s+"), " "),
                normalizers.Strip(),
            ]
        )
    tok.pre_tokenizer = Metaspace()
    trainer = UnigramTrainer(
        vocab_size=vocab_size,
        special_tokens=["<s>", "</s>", "<unk>"],
        unk_token="<unk>",
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(path)


def main():
    os.makedirs(OUT, exist_ok=True)
    gen_bpe(
        os.path.join(OUT, "bpe_ascii.json"),
        CORPUS,
        special=["<|begin_of_text|>", "<|end_of_text|>"],
    )
    gen_bpe(os.path.join(OUT, "bpe_multi.json"), MULTI_CORPUS)
    gen_unigram(os.path.join(OUT, "unigram_plain.json"), CORPUS)
    gen_unigram(
        os.path.join(OUT, "unigram_norm.json"), MULTI_CORPUS, vocab_size=600,
        normalized=True,
    )
    print("fixtures written to", OUT)


if __name__ == "__main__":
    main()
