"""Tensor-parallel paged serving (ISSUE 6): the head-sharded block-pool
arena + shard-aware paged kernels over the tp mesh axis.

The load-bearing contract is the acceptance pin: paged tp=2 greedy decode
streams are BYTE-IDENTICAL to both dense tp=2 and paged tp=1 on
mixed-length right-padded batches — the tp split changes only WHERE each
kv head's bytes live (every device holds K/tp heads of every physical
block), never an attended value. Around it: shard_map'd interpret-mode
kernel↔oracle parity under the exact serving partition specs
(ops.attention.paged_partition_specs), block accounting under preemption
at tp=2, the per-device arena gauge, and the construction validation that
replaced PR 5's blanket tp>1 rejection.

Runs on the conftest-forced 8-virtual-device CPU platform (the
``make tp2-smoke`` lane runs exactly this file).
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    MeshConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.core.mesh import make_mesh
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
ENG = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64)
PAGED = dataclasses.replace(ENG, kv_paged=True, kv_block_size=16)
PROMPTS = [[3, 17, 42, 7, 99], [5, 5, 8], [11] * 12, [2, 9]]

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 (virtual) devices for tp=2"
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()  # 4 q heads / 2 kv heads: tp=2 tiles exactly
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    oracle = InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=ENG, dtypes=FP32
    )
    ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
    placed = shard_llama_params(params, ctx)
    return cfg, params, placed, ctx, oracle


def drain(eng, reqs):
    """admit_many + step-to-completion → {rid: tokens}."""
    results = {}
    outs = eng.admit_many([(rid, p, mn, None) for rid, p, mn in reqs])
    for (rid, _, _), res in zip(reqs, outs):
        if isinstance(res, BaseException):
            raise res
        _, fin = res
        if fin is not None:
            results[rid] = fin
    for _ in range(300):
        for rid, toks in eng.step():
            results[rid] = toks
        if not eng.has_active():
            break
    return results


# ---------------------------------------------------------------------------
# engine parity (THE acceptance pin)
# ---------------------------------------------------------------------------


class TestPagedTpParity:
    def test_tp2_streams_match_dense_tp2_and_paged_tp1(self, setup):
        """Byte-identical greedy streams across paged tp=2 / dense tp=2 /
        paged tp=1 on a mixed-length batch, with zero leaked blocks and
        the arena REALLY head-sharded (K/tp kv heads per device shard)."""
        cfg, params, placed, ctx, oracle = setup
        want = {i: oracle.generate([p])[0] for i, p in enumerate(PROMPTS)}
        reqs = [(i, p, GREEDY.max_new_tokens) for i, p in enumerate(PROMPTS)]

        paged1 = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED, dtypes=FP32
        )
        assert drain(paged1, reqs) == want
        assert paged1.kv_pool.blocks_in_use() == 0

        dense2 = ContinuousEngine(
            cfg, placed, sampling=GREEDY, engine_config=ENG, dtypes=FP32,
            mesh=ctx,
        )
        assert drain(dense2, reqs) == want

        paged2 = ContinuousEngine(
            cfg, placed, sampling=GREEDY, engine_config=PAGED, dtypes=FP32,
            mesh=ctx,
        )
        shard = paged2._cache[0].addressable_shards[0].data.shape
        assert shard[2] == cfg.num_kv_heads // ctx.tp, shard
        assert drain(paged2, reqs) == want
        assert paged2.kv_pool.blocks_in_use() == 0

    def test_tp2_multi_step_sync_and_mid_flight_admission(self, setup):
        """k>1 sync windows over the sharded arena + a request joining
        mid-generation: same streams as the solo oracle."""
        cfg, _, placed, ctx, oracle = setup
        p1, p2 = PROMPTS[0], PROMPTS[2]
        want1 = oracle.generate([p1])[0]
        want2 = oracle.generate([p2])[0]
        eng = ContinuousEngine(
            cfg, placed, sampling=GREEDY,
            engine_config=dataclasses.replace(PAGED, decode_sync_steps=4),
            dtypes=FP32, mesh=ctx,
        )
        eng.admit(1, p1, GREEDY.max_new_tokens)
        results = {}
        for rid, toks in eng.step():
            results[rid] = toks
        eng.admit(2, p2, GREEDY.max_new_tokens)  # joins mid-flight
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results == {1: want1, 2: want2}
        assert eng.kv_pool.blocks_in_use() == 0

    def test_tp2_int8_arena_matches_dense(self, setup):
        """The _q8 paged kernels shard the same way: int8 arena + sharded
        scale planes on the mesh reproduce the dense int8 stream."""
        cfg, params, placed, ctx, _ = setup
        eng8 = dataclasses.replace(ENG, prompt_buckets=(32,), kv_quant="int8")
        paged8 = dataclasses.replace(eng8, kv_paged=True, kv_block_size=32)
        reqs = [(i, p, 8) for i, p in enumerate(PROMPTS[:2])]
        d = drain(
            ContinuousEngine(
                cfg, params, sampling=GREEDY, engine_config=eng8, dtypes=FP32
            ),
            reqs,
        )
        p = drain(
            ContinuousEngine(
                cfg, placed, sampling=GREEDY, engine_config=paged8,
                dtypes=FP32, mesh=ctx,
            ),
            reqs,
        )
        assert d == p

    def test_tp2_preemption_resumes_with_parity_and_zero_leak(self, setup):
        """Pool exhaustion mid-decode on the SHARDED arena: preemption,
        resubmission, and block accounting are tp-oblivious (the allocator
        is per-row and replicated host-side) — every stream matches the
        solo oracle and the pool drains to zero."""
        cfg, _, placed, ctx, oracle = setup
        want = [oracle.generate([p], max_new_tokens=40)[0] for p in PROMPTS]
        tight = dataclasses.replace(PAGED, kv_pool_blocks=8)
        eng = ContinuousEngine(
            cfg, placed, sampling=GREEDY, engine_config=tight, dtypes=FP32,
            mesh=ctx,
        )
        sched = ContinuousScheduler(eng)
        try:
            outs = [None] * len(PROMPTS)
            errs = [None] * len(PROMPTS)

            def run(i):
                try:
                    outs[i] = sched.submit(
                        PROMPTS[i], max_new_tokens=40, timeout=300
                    )
                except BaseException as e:  # noqa: BLE001
                    errs[i] = e

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(PROMPTS))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert errs == [None] * len(PROMPTS), errs
            assert outs == want
            assert eng.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()

    def test_per_device_arena_gauge_reports_the_split(self, setup):
        """rag_kv_pool_device_bytes: one child per mesh device, each
        reading exactly arena_global / tp (the head-sharded HBM claim)."""
        cfg, _, placed, ctx, _ = setup
        eng = ContinuousEngine(
            cfg, placed, sampling=GREEDY, engine_config=PAGED, dtypes=FP32,
            mesh=ctx,
        )
        reg = obs_metrics.MetricsRegistry()
        eng.bind_metrics(reg)
        total = sum(p.nbytes for p in eng._cache)
        n_dev = len(list(ctx.mesh.devices.flat))
        # dp=4 × tp=2: every device holds a (K/tp) shard — 1/tp of the
        # GLOBAL arena each (replication across dp does not dilute a
        # device's resident bytes)
        per_dev = {k: v for k, v in eng._arena_device_bytes.items()}
        assert len(per_dev) == n_dev
        assert all(v == total / ctx.tp for v in per_dev.values()), per_dev
        text = reg.render_prometheus()
        assert "rag_kv_pool_device_bytes" in text

    def test_validate_tp_layout_replaces_the_blanket_rejection(self, setup):
        """tp that does not divide the kv-head count fails at construction
        with the head-sharding constraint spelled out; a dividing tp (the
        other tests here) constructs — the old 'does not support tp>1'
        error is gone."""
        cfg, params, _, _, _ = setup
        ctx4 = make_mesh(MeshConfig(dp=2, sp=1, tp=4))
        with pytest.raises(ValueError, match="divisible by"):
            ContinuousEngine(
                cfg, shard_llama_params(params, ctx4), sampling=GREEDY,
                engine_config=PAGED, dtypes=FP32, mesh=ctx4,
            )
        # the config-level validator is the engine's source of truth
        PAGED.validate_tp_layout(2, cfg.num_kv_heads)  # divides: no raise
        with pytest.raises(ValueError, match="kv-head"):
            PAGED.validate_tp_layout(4, cfg.num_kv_heads)
        ENG.validate_tp_layout(4, cfg.num_kv_heads)  # dense: tp-agnostic


# ---------------------------------------------------------------------------
# shard_map'd kernel ↔ oracle parity (interpret mode, the SERVING specs)
# ---------------------------------------------------------------------------


class TestShardedPagedKernelParity:
    """The shard-aware kernels under the exact partition rules serving
    lowers (ops.attention.paged_partition_specs): each shard streams its
    local K/tp head slice of the arena; the stitched output must match the
    unsharded XLA oracle bit-for-near-bit. The TPU lane re-runs compiled;
    interpret mode pins the kernel LOGIC per shard on CPU."""

    def _mesh(self):
        return make_mesh(MeshConfig(dp=4, sp=1, tp=2)).mesh

    def _tables(self, B, MB, bs, kv_len):
        tables = np.zeros((B, MB), np.int32)
        phys = 1
        for b in range(B):
            for j in range(-(-int(kv_len[b]) // bs)):
                tables[b, j] = phys
                phys += 1
        return tables

    def test_sharded_paged_decode_matches_oracle(self):
        from jax.experimental.shard_map import shard_map

        from rag_llm_k8s_tpu.ops.attention import (
            paged_decode_attention,
            paged_decode_attention_xla,
            paged_partition_specs,
        )

        rng = np.random.default_rng(0)
        B, H, K, hd, bs, MB = 3, 4, 2, 16, 16, 4
        L, N = 2, 1 + 3 * MB
        ka = jnp.asarray(rng.standard_normal((L, N, K, bs, hd)).astype(np.float32))
        va = jnp.asarray(rng.standard_normal((L, N, K, bs, hd)).astype(np.float32))
        kv_len = np.array([5, 33, 64], np.int32)
        tables = self._tables(B, MB, bs, kv_len)
        q = jnp.asarray(rng.standard_normal((B, 1, H, hd)).astype(np.float32))
        in_specs, out_spec = paged_partition_specs("decode")
        fn = shard_map(
            lambda q_, k_, v_, t_, l_, lay_: paged_decode_attention(
                q_, k_, v_, t_, l_, lay_, interpret=True
            ),
            mesh=self._mesh(), in_specs=in_specs, out_specs=out_spec,
            check_rep=False,
        )
        for lay in range(L):
            lay1 = jnp.asarray(lay, jnp.int32).reshape(1)
            got = fn(q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len), lay1)
            want = paged_decode_attention_xla(
                q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len), lay1
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-5
            )

    def test_sharded_paged_chunk_matches_oracle(self):
        from jax.experimental.shard_map import shard_map

        from rag_llm_k8s_tpu.ops.attention import (
            paged_chunk_attention,
            paged_chunk_attention_xla,
            paged_partition_specs,
        )

        rng = np.random.default_rng(1)
        B, S, H, K, hd, bs, MB = 2, 8, 4, 2, 16, 16, 4
        L, N = 2, 1 + 2 * MB
        ka = jnp.asarray(rng.standard_normal((L, N, K, bs, hd)).astype(np.float32))
        va = jnp.asarray(rng.standard_normal((L, N, K, bs, hd)).astype(np.float32))
        kv_len = np.array([20, 41], np.int32)
        wi = kv_len - S
        tables = self._tables(B, MB, bs, kv_len)
        q = jnp.asarray(rng.standard_normal((B, S, H, hd)).astype(np.float32))
        in_specs, out_spec = paged_partition_specs("chunk")
        fn = shard_map(
            lambda q_, k_, v_, t_, l_, lay_, wi_: paged_chunk_attention(
                q_, k_, v_, t_, l_, lay_, wi_, bq=4, interpret=True
            ),
            mesh=self._mesh(), in_specs=in_specs, out_specs=out_spec,
            check_rep=False,
        )
        lay1 = jnp.asarray(1, jnp.int32).reshape(1)
        got = fn(
            q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len), lay1,
            jnp.asarray(wi),
        )
        want = paged_chunk_attention_xla(
            q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len), lay1,
            jnp.asarray(wi),
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_sharded_paged_q8_decode_matches_oracle(self):
        from jax.experimental.shard_map import shard_map

        from rag_llm_k8s_tpu.ops.attention import (
            paged_decode_attention_q8,
            paged_decode_attention_xla_q8,
            paged_partition_specs,
        )

        rng = np.random.default_rng(2)
        B, H, K, hd, bs, MB = 2, 4, 2, 16, 32, 2
        L, N = 2, 1 + 2 * MB
        ka = rng.integers(-127, 128, (L, N, K, bs, hd)).astype(np.int8)
        va = rng.integers(-127, 128, (L, N, K, bs, hd)).astype(np.int8)
        ks = rng.uniform(0.001, 0.02, (L, N, K, bs)).astype(np.float32)
        vs = rng.uniform(0.001, 0.02, (L, N, K, bs)).astype(np.float32)
        kv_len = np.array([10, 50], np.int32)
        tables = self._tables(B, MB, bs, kv_len)
        q = jnp.asarray(rng.standard_normal((B, 1, H, hd)).astype(np.float32))
        in_specs, out_spec = paged_partition_specs("decode", q8=True)
        fn = shard_map(
            lambda q_, k_, v_, ks_, vs_, t_, l_, lay_: paged_decode_attention_q8(
                q_, k_, v_, ks_, vs_, t_, l_, lay_, interpret=True
            ),
            mesh=self._mesh(), in_specs=in_specs, out_specs=out_spec,
            check_rep=False,
        )
        lay1 = jnp.asarray(0, jnp.int32).reshape(1)
        args = (
            q, jnp.asarray(ka), jnp.asarray(va), jnp.asarray(ks),
            jnp.asarray(vs), jnp.asarray(tables), jnp.asarray(kv_len), lay1,
        )
        got = fn(*args)
        want = paged_decode_attention_xla_q8(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)

    def test_sharded_paged_q8_chunk_matches_oracle(self):
        """The fused q8 paged chunk kernel (it replaced PR 5's gather
        oracle) under the SERVING partition specs — warm-tier chunked
        prefill is shard-aware like every other paged path."""
        from jax.experimental.shard_map import shard_map

        from rag_llm_k8s_tpu.ops.attention import (
            paged_chunk_attention_q8,
            paged_chunk_attention_xla_q8,
            paged_partition_specs,
        )

        rng = np.random.default_rng(3)
        B, S, H, K, hd, bs, MB = 2, 8, 4, 2, 16, 16, 4
        L, N = 2, 1 + 2 * MB
        ka = rng.integers(-127, 128, (L, N, K, bs, hd)).astype(np.int8)
        va = rng.integers(-127, 128, (L, N, K, bs, hd)).astype(np.int8)
        ks = rng.uniform(0.001, 0.02, (L, N, K, bs)).astype(np.float32)
        vs = rng.uniform(0.001, 0.02, (L, N, K, bs)).astype(np.float32)
        kv_len = np.array([20, 41], np.int32)
        wi = kv_len - S
        tables = self._tables(B, MB, bs, kv_len)
        q = jnp.asarray(rng.standard_normal((B, S, H, hd)).astype(np.float32))
        in_specs, out_spec = paged_partition_specs("chunk", q8=True)
        fn = shard_map(
            lambda q_, k_, v_, ks_, vs_, t_, l_, lay_, wi_: (
                paged_chunk_attention_q8(
                    q_, k_, v_, ks_, vs_, t_, l_, lay_, wi_, bq=4,
                    interpret=True,
                )
            ),
            mesh=self._mesh(), in_specs=in_specs, out_specs=out_spec,
            check_rep=False,
        )
        lay1 = jnp.asarray(1, jnp.int32).reshape(1)
        args = (
            q, jnp.asarray(ka), jnp.asarray(va), jnp.asarray(ks),
            jnp.asarray(vs), jnp.asarray(tables), jnp.asarray(kv_len), lay1,
            jnp.asarray(wi),
        )
        got = fn(*args)
        want = paged_chunk_attention_xla_q8(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)

    def test_unknown_mode_spec_is_refused(self):
        from rag_llm_k8s_tpu.ops.attention import paged_partition_specs

        # the q8 chunk spec EXISTS since the fused kernel landed
        in_specs, _ = paged_partition_specs("chunk", q8=True)
        assert len(in_specs) == 9
        with pytest.raises(ValueError, match="unknown mode"):
            paged_partition_specs("prefill")
