"""8B-geometry streaming-load proof (CI-sized).

The reference serves Meta-Llama-3.1-8B from a 4-shard safetensors layout
(/root/reference/llm/download_model.py:14-25). These tests prove the
framework's streaming loader + TP placement at TRUE 8B tensor shapes —
hidden 4096, intermediate 14336, 32 q / 8 kv heads, vocab 128 256, bf16 on
disk — with the layer count reduced to 2 so CI stays fast (the streaming
claim is exactly that host memory does NOT scale with layer count; the
full-depth run lives in scripts/validate_8b.py, results in docs/8B.md).
"""

import dataclasses
import os
import resource

import jax
import jax.numpy as jnp
import numpy as np
import psutil
import pytest

from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
from rag_llm_k8s_tpu.models.loader import load_safetensors_params
from rag_llm_k8s_tpu.parallel.sharding import make_streaming_put
from rag_llm_k8s_tpu.utils.synth import write_synth_checkpoint

CFG_8B_L2 = dataclasses.replace(LlamaConfig.llama_3_1_8b(), num_layers=2)
GB = 1 << 30


@pytest.fixture(scope="module")
def synth_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("synth8b")
    paths = write_synth_checkpoint(str(out), CFG_8B_L2, n_shards=4)
    assert len(paths) == 4  # the real PVC layout: 4 shard files
    return str(out)


class TestStreaming8B:
    def test_tp_streamed_load_shapes_shardings_and_memory(self, synth_dir, mesh_tp8):
        """Stream the 4-shard checkpoint onto the 8-device mesh: every tensor
        must arrive TP-sharded at true 8B shapes in bf16, with transient host
        overhead bounded by a couple of single tensors — NOT the checkpoint
        size (the reference's from_pretrained materializes the whole model)."""
        ckpt_bytes = sum(
            os.path.getsize(os.path.join(synth_dir, f))
            for f in os.listdir(synth_dir)
        )
        assert ckpt_bytes > 2 * GB  # true-shape sanity: L=2 slice is ~3 GB

        proc = psutil.Process()
        # ru_maxrss is a process-LIFETIME high-water mark: snapshot it before
        # the load so the assertion measures this load's transient, not
        # whatever earlier tests in the same process peaked at
        peak_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        put = make_streaming_put(mesh_tp8, dtype=jnp.bfloat16)
        params = load_safetensors_params(
            synth_dir, CFG_8B_L2, DTypePolicy(), put=put
        )
        rss_after = proc.memory_info().rss
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

        # ---- geometry: stacked [L, ...] at true 8B shapes, bf16 ----------
        c = CFG_8B_L2
        lay = params["layers"]
        assert params["embedding"].shape == (c.vocab_size, c.hidden_size)
        assert lay["attn"]["wq"]["kernel"].shape == (
            2, c.hidden_size, c.num_heads * c.head_dim
        )
        assert lay["attn"]["wk"]["kernel"].shape == (
            2, c.hidden_size, c.num_kv_heads * c.head_dim
        )
        assert lay["mlp"]["w_gate"]["kernel"].shape == (
            2, c.hidden_size, c.intermediate_size
        )
        assert params["lm_head"].shape == (c.hidden_size, c.vocab_size)
        assert params["embedding"].dtype == jnp.bfloat16
        assert lay["mlp"]["w_gate"]["kernel"].dtype == jnp.bfloat16

        # ---- sharding: the big matmuls actually split over tp=8 ----------
        for leaf in (
            lay["attn"]["wq"]["kernel"],
            lay["mlp"]["w_gate"]["kernel"],
            params["lm_head"],
        ):
            shard_bytes = leaf.addressable_shards[0].data.nbytes
            assert shard_bytes * 8 == leaf.nbytes, leaf.sharding

        # ---- memory: transient overhead, not checkpoint-sized ------------
        # on the CPU mesh the PLACED params necessarily stay resident in
        # host RAM (they'd leave for HBM on real chips), so the streaming
        # claim is about the TRANSIENT above the final resident set: at most
        # a couple of vocab-sized tensors (embed read + lm_head transpose),
        # never the multi-GB whole-checkpoint spike from_pretrained makes.
        embed_bytes = c.vocab_size * c.hidden_size * 2
        transient = peak - max(rss_after, peak_before)
        assert transient < 3 * embed_bytes + 512 * (1 << 20), (
            f"transient host overhead {transient / GB:.2f} GB suggests the "
            f"loader materialized more than a streamed group"
        )

    def test_loaded_tree_runs_a_forward(self, synth_dir, mesh_tp8):
        """The placed 8B-shaped tree must actually execute one sharded
        forward step (zero weights → finite zero logits)."""
        from rag_llm_k8s_tpu.models.llama import LlamaModel, make_kv_cache

        put = make_streaming_put(mesh_tp8, dtype=jnp.bfloat16)
        params = load_safetensors_params(
            synth_dir, CFG_8B_L2, DTypePolicy(), put=put
        )
        model = LlamaModel(CFG_8B_L2, DTypePolicy(), attn_impl="xla")
        B, S = 1, 8
        cache = make_kv_cache(CFG_8B_L2, B, 128, jnp.bfloat16)
        logits, _ = jax.jit(
            lambda p, t: model.apply(
                {"params": p}, t, jnp.broadcast_to(jnp.arange(S), (B, S)),
                cache, jnp.zeros((B,), jnp.int32), jnp.full((B,), S, jnp.int32),
                jnp.int32(0), last_logit_only=True,
            )
        )(params, jnp.ones((B, S), jnp.int32))
        assert np.isfinite(np.asarray(logits)).all()
