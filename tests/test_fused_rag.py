"""Single-fetch RAG serving: device-side prompt assembly (generate_rag).

The contract under test: the prompt assembled ON DEVICE from the packed
retrieve output + the store's chunk-token sidecar is token-identical to the
host's piecewise assembly (`RagService._piecewise_prompt`), so greedy
generation over either is identical; budget overflow drops trailing chunks
(token-truncating the first when it alone overflows) the same way on both
sides; and the serving path pays ONE device→host fetch per solo query.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.batching import BatchScheduler
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.server.app import RagService

FP32 = DTypePolicy.fp32()


class ByteTokenizer:
    vocab_size = 300
    eos_id = None

    def encode(self, text):
        return [2 + (b % 250) for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(97 + (int(i) % 26)) for i in ids)


def make_engine(speculative="off", buckets=(256,), max_new=8):
    cfg = LlamaConfig.tiny(vocab_size=300)
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, InferenceEngine(
        cfg,
        params,
        sampling=SamplingConfig(do_sample=False, max_new_tokens=max_new),
        engine_config=EngineConfig(
            prompt_buckets=buckets, max_batch_size=4, speculative=speculative
        ),
        dtypes=FP32,
    )


def seg_ids(tok, md):
    return tok.encode(
        f"Document '{md.get('filename')}' (chunk {md.get('chunk_id')}): "
        f"{md.get('text')}\n\n"
    )


def make_store(tok, texts):
    store = VectorStore(dim=8)
    rng = np.random.default_rng(7)
    store.add(
        [rng.standard_normal(8).astype(np.float32) for _ in texts],
        [{"filename": "f.pdf", "chunk_id": i, "text": t} for i, t in enumerate(texts)],
    )
    store.attach_token_source(lambda md: seg_ids(tok, md))
    return store


def host_assemble(a, segs, b, S):
    """The budget rule both sides must implement."""
    avail = S - len(a) - len(b)
    ids = list(a)
    used = 0
    for j, s in enumerate(segs):
        if used + len(s) <= avail:
            ids.extend(s)
            used += len(s)
        else:
            if j == 0:
                ids.extend(s[:avail])
            break
    return ids + list(b)


def packed_for(idx_order, k):
    """A packed [1, 2k] retrieve output with chosen ranking."""
    d = np.linspace(0.1, 0.9, k, dtype=np.float32)
    row = np.concatenate([d, np.asarray(idx_order[:k], np.float32)])
    return jnp.asarray(row[None, :])


class TestGenerateRagMatchesHostAssembly:
    @pytest.mark.parametrize("speculative", ["off", "prompt_lookup"])
    def test_greedy_identical_to_host_ids(self, speculative):
        tok = ByteTokenizer()
        cfg, engine = make_engine(speculative=speculative)
        store = make_store(tok, ["alpha beta gamma", "delta epsilon", "zeta eta"])
        toks_dev, lens_dev = store.token_snapshot()
        a = [cfg.bos_token_id] + tok.encode("SYS\n\nContext: ")
        b = tok.encode("\n\nUser: what?\n\nChatbot:")
        packed = packed_for([2, 0, 1], k=3)
        segs = [seg_ids(tok, store._metadata[i]) for i in (2, 0, 1)]
        want_ids = host_assemble(a, segs, b, S=256)
        want = engine.generate([want_ids])[0]
        got = engine.generate_rag(
            np.asarray(a, np.int32), np.asarray(b, np.int32),
            packed, toks_dev, lens_dev, n_chunks=3,
        )
        assert got == want

    def test_budget_drops_trailing_chunks(self):
        tok = ByteTokenizer()
        cfg, engine = make_engine(buckets=(128,))
        texts = ["x " * 30, "y " * 30, "z " * 30]  # each ~60 tokens + header
        store = make_store(tok, texts)
        toks_dev, lens_dev = store.token_snapshot()
        a = [cfg.bos_token_id] + tok.encode("S: ")
        b = tok.encode("\n\nUser: q\n\nChatbot:")
        packed = packed_for([0, 1, 2], k=3)
        segs = [seg_ids(tok, store._metadata[i]) for i in (0, 1, 2)]
        want_ids = host_assemble(a, segs, b, S=128)
        # the budget really dropped something (or the test proves nothing)
        assert len(want_ids) < len(a) + sum(map(len, segs)) + len(b)
        want = engine.generate([want_ids])[0]
        got = engine.generate_rag(
            np.asarray(a, np.int32), np.asarray(b, np.int32),
            packed, toks_dev, lens_dev, n_chunks=3,
        )
        assert got == want

    def test_first_chunk_alone_overflowing_truncates(self):
        tok = ByteTokenizer()
        cfg, engine = make_engine(buckets=(64,))
        store = make_store(tok, ["w " * 100])  # segment >> bucket
        toks_dev, lens_dev = store.token_snapshot()
        a = [cfg.bos_token_id] + tok.encode("S: ")
        b = tok.encode("\n\nU: q\n\nChatbot:")
        packed = packed_for([0], k=1)
        seg = seg_ids(tok, store._metadata[0])
        want_ids = host_assemble(a, [seg], b, S=64)
        assert len(want_ids) == 64  # exactly full: truncation engaged
        want = engine.generate([want_ids])[0]
        got = engine.generate_rag(
            np.asarray(a, np.int32), np.asarray(b, np.int32),
            packed, toks_dev, lens_dev, n_chunks=1,
        )
        assert got == want


class TestFusedService:
    def _service(self, buckets=(256,), rag_fused=True):
        llama_cfg = LlamaConfig.tiny(vocab_size=300)
        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        cfg = AppConfig(
            model=llama_cfg, encoder=enc_cfg, system_message="SYS"
        )
        engine = InferenceEngine(
            llama_cfg,
            init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
            sampling=SamplingConfig(do_sample=False, max_new_tokens=4),
            engine_config=EngineConfig(
                prompt_buckets=buckets, max_batch_size=4, rag_fused=rag_fused
            ),
            dtypes=FP32,
        )
        encoder = EncoderRunner(
            enc_cfg,
            init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
            dtypes=FP32, length_buckets=(32,), max_batch=4,
        )
        store = VectorStore(dim=enc_cfg.hidden_size)
        scheduler = BatchScheduler(engine, max_wait_ms=25.0)
        svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(),
                         store, scheduler=scheduler)
        svc.ready = True
        texts = ["alpha beta gamma", "delta epsilon", "zeta eta theta"]
        vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
        store.add(list(vecs), [
            {"filename": "f", "chunk_id": i, "text": t} for i, t in enumerate(texts)
        ])
        return svc

    def test_solo_takes_single_fetch_and_matches_host_path(self):
        svc = self._service()
        try:
            solo = svc.answer("alpha beta")
            assert svc.metrics.snapshot().get("query_single_fetch") == 1
            assert "context" in solo and solo["generated_text"]

            # the batched HOST path (what a burst runs): piecewise ids
            # through the ordinary engine — greedy, it must answer
            # identically to the device-assembled solo path
            results, _ = svc._retrieve("alpha beta")
            context, ids = svc._piecewise_prompt("alpha beta", results)
            out = svc.engine.generate([ids])[0]
            from rag_llm_k8s_tpu.rag.prompt import extract_answer

            host_text = extract_answer(svc.llm_tokenizer.decode(out))
            assert host_text == solo["generated_text"]
            assert context == solo["context"]

            # concurrent answers agree too (whichever path each took)
            got = {}

            def run(tag):
                got[tag] = svc.answer("alpha beta")["generated_text"]

            threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert set(got.values()) == {solo["generated_text"]}
        finally:
            svc.shutdown()

    def test_sidecar_disabled_when_config_off(self):
        svc = self._service(rag_fused=False)
        try:
            out = svc.answer("alpha beta")
            assert out["generated_text"]
            assert "query_single_fetch" not in svc.metrics.snapshot()
        finally:
            svc.shutdown()

    def test_head_tail_overflow_falls_back_to_host_path(self):
        # bucket too small for head+tail+16: the device branch must decline
        # and the host path still answer
        svc = self._service(buckets=(32,))
        try:
            out = svc.answer("alpha beta gamma delta epsilon")
            assert out["generated_text"]
            assert "query_single_fetch" not in svc.metrics.snapshot()
        finally:
            svc.shutdown()

    def test_token_snapshot_splices_incrementally(self):
        """Adds after the first sidecar build must splice O(batch) (not a
        full rebuild) while the bucket holds, and force a full rebuild
        when the row bucket outgrows."""
        tok = ByteTokenizer()
        store = make_store(tok, [f"chunk {i} words" for i in range(3)])
        toks0, lens0 = store.token_snapshot()
        assert store.transfer_stats.get("tok_full_uploads") == 1
        rng = np.random.default_rng(3)
        # add within the 512-row bucket -> splice, same plane shape
        store.add(
            [rng.standard_normal(8).astype(np.float32)],
            [{"filename": "f.pdf", "chunk_id": 99, "text": "a new chunk"}],
        )
        toks1, lens1 = store.token_snapshot()
        assert store.transfer_stats.get("tok_row_splices") == 1
        assert toks1.shape == toks0.shape
        want = seg_ids(tok, {"filename": "f.pdf", "chunk_id": 99, "text": "a new chunk"})
        got = np.asarray(toks1[3][: int(lens1[3])]).tolist()
        assert got == want
        # rows 0-2 untouched by the splice
        np.testing.assert_array_equal(np.asarray(toks1[:3]), np.asarray(toks0[:3]))
        # a row longer than the Lc bucket -> full rebuild at a wider plane
        store.add(
            [rng.standard_normal(8).astype(np.float32)],
            [{"filename": "f.pdf", "chunk_id": 100, "text": "w " * 300}],
        )
        toks2, lens2 = store.token_snapshot()
        assert store.transfer_stats.get("tok_full_uploads") == 2
        assert toks2.shape[1] > toks0.shape[1]
        assert int(lens2[4]) > 128

    def test_near_capacity_splice_rebuilds_instead_of_clamping(self):
        """A padded splice block that would overrun the row bucket must fall
        back to a full rebuild: dynamic_update_slice CLAMPS an overflowing
        start index, which would silently shift the new rows onto earlier
        real rows (wrong chunk text in every later fused prompt)."""
        tok = ByteTokenizer()
        store = make_store(tok, [f"c{i}" for i in range(509)])
        toks0, lens0 = store.token_snapshot()
        cap = toks0.shape[0]
        assert cap == 512 and store.transfer_stats.get("tok_full_uploads") == 1
        rng = np.random.default_rng(5)
        # 3 adds: n = 512 <= cap, but the padded block (4 rows) at offset
        # 509 would overrun — must NOT splice
        store.add(
            [rng.standard_normal(8).astype(np.float32) for _ in range(3)],
            [
                {"filename": "f.pdf", "chunk_id": 600 + i, "text": f"new {i}"}
                for i in range(3)
            ],
        )
        toks1, lens1 = store.token_snapshot()
        assert store.transfer_stats.get("tok_row_splices") is None
        assert store.transfer_stats.get("tok_full_uploads") == 2
        for i in range(512):
            want = seg_ids(tok, store._metadata[i])
            got = np.asarray(toks1[i][: int(lens1[i])]).tolist()
            assert got == want, f"row {i} corrupted"

    def test_single_fetch_serves_over_tp2_mesh(self, devices8):
        """The production deployment pins TPU_RAG_MESH=tp=8 — the single-
        fetch path must serve over a mesh (replicated placement for the
        per-query inputs, a once-per-snapshot broadcast for the sidecar)
        and answer token-identically to the meshless fused service."""
        import dataclasses

        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        llama_cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=300), num_kv_heads=2
        )
        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        cfg = AppConfig(model=llama_cfg, encoder=enc_cfg, system_message="SYS")
        params = init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32)
        from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params as init_enc

        enc_params = init_enc(jax.random.PRNGKey(1), enc_cfg, FP32)
        texts = ["alpha beta gamma", "delta epsilon", "zeta eta theta"]

        def serve(mesh_ctx, eng_params):
            engine = InferenceEngine(
                llama_cfg, eng_params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=4),
                engine_config=EngineConfig(prompt_buckets=(256,), max_batch_size=2),
                dtypes=FP32, mesh=mesh_ctx,
            )
            encoder = EncoderRunner(
                enc_cfg, enc_params, dtypes=FP32, length_buckets=(32,), max_batch=4
            )
            store = VectorStore(dim=enc_cfg.hidden_size)
            svc = RagService(
                cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store,
                scheduler=BatchScheduler(engine, max_wait_ms=20.0),
            )
            svc.ready = True
            vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
            store.add(list(vecs), [
                {"filename": "f", "chunk_id": i, "text": t}
                for i, t in enumerate(texts)
            ])
            return svc

        ctx = make_mesh(MeshConfig(dp=1, sp=1, tp=2), devices=devices8[:2])
        svc_mesh = serve(ctx, shard_llama_params(params, ctx))
        svc_solo = serve(None, params)
        try:
            got = svc_mesh.answer("alpha beta")
            want = svc_solo.answer("alpha beta")
            assert svc_mesh.metrics.snapshot().get("query_single_fetch") == 1
            assert svc_solo.metrics.snapshot().get("query_single_fetch") == 1
            assert got["generated_text"] == want["generated_text"]
            assert got["context"] == want["context"]
            # second query reuses the cached replicated sidecar
            svc_mesh.answer("zeta eta")
            assert len(svc_mesh.engine._sidecar_placed) == 1
        finally:
            svc_mesh.shutdown()
            svc_solo.shutdown()

    def test_teardown_releases_engine_and_sidecar(self):
        """A long-lived store must not retain the dead service's engine (a
        bound-method token source did exactly that — the params graph
        stayed HBM-resident and OOMed the next model's build) nor keep the
        device sidecar pair alive past shutdown."""
        import gc
        import weakref

        svc = self._service()
        store = svc.store
        svc.answer("alpha beta")  # sidecar attached + device pair built
        assert store._tok_dev is not None
        svc.shutdown()
        ref = weakref.ref(svc.engine)
        del svc
        gc.collect()
        assert ref() is None, "engine retained after service teardown"
        assert store._tok_dev is None  # device pair released
        # host rows survive for the next service sharing the tokenizer
        assert any(r is not None for r in store._chunk_tokens)

    def test_token_snapshot_survives_save_load(self, tmp_path):
        tok = ByteTokenizer()
        store = make_store(tok, ["one two", "three four"])
        toks0, lens0 = store.token_snapshot()
        path = str(tmp_path / "idx")
        store.path = path
        store.save()
        loaded = VectorStore.load(path)
        loaded.attach_token_source(lambda md: seg_ids(tok, md))
        toks1, lens1 = loaded.token_snapshot()
        np.testing.assert_array_equal(np.asarray(lens0), np.asarray(lens1))
        np.testing.assert_array_equal(np.asarray(toks0), np.asarray(toks1))
