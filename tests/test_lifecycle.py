"""Crash-safe lifecycle (ISSUE 19): graceful drain, durable flight WAL,
and warm restart that resumes in-flight requests.

Four layers, bottom-up:

- **durability primitives** — ``durable_write``'s tmp-fsync-rename
  discipline and the segment-rotated ``FlightWAL`` (rotation, pruning,
  epoch bumps, torn-tail-tolerant ``scan_wal``, the recorder tee);
- **the drain machine** — ``AdmissionController.drain`` shedding queued
  and new work with 503 ``reason="draining"``, and the
  ``LifecycleCoordinator`` state machine proven with injected
  clock/sleep/active_fn (clean drain, deadline overrun with a
  ``drain_timeout`` incident, idempotence);
- **restore plumbing** — ``sim/replay.extract_inflight`` /
  ``build_restore_report``, the prefix cache's warmth manifest, and the
  service-level ``restore_from_wal`` resuming a hand-built dead epoch
  byte-identically to an uninterrupted oracle;
- **the chaos pin** — a real SIGKILL mid-decode in a subprocess with two
  requests in flight, a second process restoring against the same WAL
  dir, and every delivered stream equal to the uninterrupted run
  (``make restart-smoke``).

The drain HTTP contract (503 + Retry-After while in-flight completes
with zero 500s) runs through the real WSGI app (``make drain-smoke``).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    FlightConfig,
    KVTieringConfig,
    LlamaConfig,
    PrefixCacheConfig,
    ResilienceConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.prefix_cache import PrefixCache
from rag_llm_k8s_tpu.engine.tiering import HostSpillStore
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.resilience.admission import AdmissionController, AdmissionRejected
from rag_llm_k8s_tpu.resilience.lifecycle import (
    DRAINED,
    DRAINING,
    SERVING,
    LifecycleCoordinator,
)
from rag_llm_k8s_tpu.server.app import RagService, create_app
from rag_llm_k8s_tpu.sim import replay

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
ENG_CFG = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64)


@pytest.fixture(autouse=True)
def _detach_wal():
    """The recorder is process-global; never leak a test's WAL tee into
    the next test (or another file's tests)."""
    yield
    flight.configure(wal=None)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# durable_write
# ---------------------------------------------------------------------------
class TestDurableWrite:
    def test_round_trip_and_no_tmp_residue(self, tmp_path):
        path = str(tmp_path / "state.json")
        flight.durable_write(path, {"a": 1, "nested": [1, 2, 3]})
        with open(path) as f:
            assert json.load(f) == {"a": 1, "nested": [1, 2, 3]}
        # the tmp staging file must not survive the rename
        assert os.listdir(tmp_path) == ["state.json"]

    def test_overwrite_replaces_atomically(self, tmp_path):
        path = str(tmp_path / "state.json")
        flight.durable_write(path, {"gen": 1})
        flight.durable_write(path, {"gen": 2})
        with open(path) as f:
            assert json.load(f) == {"gen": 2}


# ---------------------------------------------------------------------------
# FlightWAL: rotation, pruning, epochs, torn tails, recorder tee
# ---------------------------------------------------------------------------
def _ev(seq, etype, rid=None, **attrs):
    d = {"seq": seq, "t": seq / 10.0, "type": etype}
    if rid is not None:
        d["rid"] = rid
    d.update(attrs)
    return d


class TestFlightWAL:
    def test_segment_rotation_and_scan_order(self, tmp_path):
        wal = flight.FlightWAL(str(tmp_path), segment_events=4)
        for i in range(10):
            wal.append(_ev(i, "arrival", rid=i, prompt_len=2, max_new=4))
        wal.close()
        names = sorted(os.listdir(tmp_path))
        assert names == [
            "wal_00000001_000001.jsonl",
            "wal_00000001_000002.jsonl",
            "wal_00000001_000003.jsonl",
        ]
        epochs = flight.scan_wal(str(tmp_path))
        assert list(epochs) == [1]
        assert [e["seq"] for e in epochs[1]] == list(range(10))
        assert wal.appends == 10 and wal.dropped == 0

    def test_prune_drops_oldest_past_max_segments(self, tmp_path):
        wal = flight.FlightWAL(str(tmp_path), segment_events=2,
                               max_segments=2)
        for i in range(9):
            wal.append(_ev(i, "arrival", rid=i))
        wal.close()
        names = sorted(os.listdir(tmp_path))
        assert len(names) == 2
        # only the NEWEST segments survive
        assert names[-1] == "wal_00000001_000005.jsonl"
        events = flight.scan_wal(str(tmp_path))[1]
        assert [e["seq"] for e in events] == [6, 7, 8]

    def test_epoch_bumps_per_incarnation_and_stays_frozen(self, tmp_path):
        w1 = flight.FlightWAL(str(tmp_path))
        w1.append(_ev(1, "arrival", rid=1))
        w1.close()
        w2 = flight.FlightWAL(str(tmp_path))
        assert w2.epoch == 2
        w2.append(_ev(1, "arrival", rid=9))
        w2.close()
        epochs = flight.scan_wal(str(tmp_path))
        assert sorted(epochs) == [1, 2]
        # the dead epoch's contents are exactly as the "crash" left them
        assert epochs[1][0]["rid"] == 1 and epochs[2][0]["rid"] == 9

    def test_scan_skips_torn_tail(self, tmp_path):
        wal = flight.FlightWAL(str(tmp_path))
        wal.append(_ev(1, "arrival", rid=1))
        wal.append(_ev(2, "token_emit", rid=1, toks=[7, 8]))
        wal.close()
        # a SIGKILL mid-append leaves a partial final line
        name = sorted(os.listdir(tmp_path))[-1]
        with open(tmp_path / name, "a") as f:
            f.write('{"seq": 3, "type": "tok')
        events = flight.scan_wal(str(tmp_path))[1]
        assert [e["seq"] for e in events] == [1, 2]

    def test_append_never_raises_counts_drops(self, tmp_path):
        wal = flight.FlightWAL(str(tmp_path / "gone"))
        os.rmdir(tmp_path / "gone")
        wal.append(_ev(1, "arrival"))  # dir vanished: logged + counted
        assert wal.dropped == 1

    def test_recorder_tees_into_wal(self, tmp_path):
        wal = flight.FlightWAL(str(tmp_path))
        flight.configure(enabled=True, wal=wal)
        assert flight.wal_enabled()
        flight.emit("arrival", 7, prompt_len=3, max_new=4)
        flight.emit("token_emit", 7, toks=[11, 12])
        events = flight.scan_wal(str(tmp_path))[wal.epoch]
        assert [e["type"] for e in events] == ["arrival", "token_emit"]
        assert all(e["rid"] == 7 for e in events)
        assert events[1]["toks"] == [11, 12]
        # seq/t survive the tee (scan re-sorts by seq across segments)
        assert events[0]["seq"] < events[1]["seq"]
        flight.configure(wal=None)
        assert not flight.wal_enabled()

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError, match="segment_events"):
            flight.FlightWAL(str(tmp_path), segment_events=0)
        with pytest.raises(ValueError, match="max_segments"):
            flight.FlightWAL(str(tmp_path), max_segments=1)


# ---------------------------------------------------------------------------
# admission draining
# ---------------------------------------------------------------------------
class TestAdmissionDraining:
    def test_new_requests_shed_503_with_drain_retry_after(self):
        gate = AdmissionController(max_concurrency=2, max_queue=2)
        gate.drain(retry_after_s=4.5)
        assert gate.draining
        with pytest.raises(AdmissionRejected) as ei:
            with gate.admit():
                pass
        assert ei.value.reason == "draining"
        assert ei.value.status == 503
        assert ei.value.retry_after_s == pytest.approx(4.5)

    def test_queued_waiter_is_woken_and_shed(self):
        gate = AdmissionController(max_concurrency=1, max_queue=4)
        entered = threading.Event()
        outcome = {}

        def queued():
            entered.set()
            try:
                with gate.admit():
                    outcome["admitted"] = True
            except AdmissionRejected as e:
                outcome["reason"] = e.reason

        with gate.admit():  # the one slot is taken
            t = threading.Thread(target=queued)
            t.start()
            entered.wait(5)
            deadline = time.monotonic() + 5
            while gate.waiting == 0 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert gate.waiting == 1
            gate.drain()  # default retry_after: the gate's own
            t.join(5)
        assert outcome == {"reason": "draining"}
        # the in-flight slot released normally — draining sheds QUEUED
        # work, never the work already past the gate
        assert gate.active == 0


# ---------------------------------------------------------------------------
# LifecycleCoordinator state machine (injected clock/sleep/active)
# ---------------------------------------------------------------------------
class TestLifecycleCoordinator:
    def test_clean_drain_runs_persist_then_exit(self):
        active = [3]
        calls = []
        lc = LifecycleCoordinator(
            deadline_s=10.0, active_fn=lambda: active[0],
            persist_fn=lambda: calls.append("persist"),
            exit_fn=lambda: calls.append("exit"),
            incident_hook=lambda t: calls.append(("incident", t)),
            clock=FakeClock(), sleep=lambda _dt: active.__setitem__(
                0, max(0, active[0] - 1)),
        )
        assert lc.state == SERVING and not lc.draining
        assert lc.begin_drain("sigterm")
        assert lc.wait_drained(5)
        assert lc.state == DRAINED and lc.reason == "sigterm"
        assert not lc.timed_out and lc.stragglers == 0
        assert calls == ["persist", "exit"]  # no incident on a clean pass

    def test_deadline_overrun_sheds_and_spools_drain_timeout(self):
        clk = FakeClock()
        calls = []
        lc = LifecycleCoordinator(
            deadline_s=1.0, active_fn=lambda: 2,  # wedged forever
            persist_fn=lambda: calls.append("persist"),
            incident_hook=lambda t: calls.append(("incident", t)),
            clock=clk, sleep=lambda _dt: clk.advance(0.5),
        )
        assert lc.begin_drain("http")
        assert lc.wait_drained(5)
        assert lc.timed_out and lc.stragglers == 2
        # incident BEFORE persist: the bundle captures the overrun journal
        assert calls == [("incident", "drain_timeout"), "persist"]

    def test_begin_drain_is_idempotent_first_reason_wins(self):
        lc = LifecycleCoordinator(
            deadline_s=5.0, active_fn=lambda: 0, clock=FakeClock(),
            sleep=lambda _dt: None,
        )
        assert lc.begin_drain("sigterm")
        assert not lc.begin_drain("http")  # preStop racing SIGTERM
        assert lc.reason == "sigterm"
        assert lc.wait_drained(5)

    def test_drain_flips_admission_gate(self):
        gate = AdmissionController(max_concurrency=2, max_queue=2)
        lc = LifecycleCoordinator(
            admission=gate, deadline_s=5.0, retry_after_s=2.5,
            clock=FakeClock(), sleep=lambda _dt: None,
        )
        assert lc.begin_drain()
        assert gate.draining
        with pytest.raises(AdmissionRejected) as ei:
            with gate.admit():
                pass
        assert ei.value.retry_after_s == pytest.approx(2.5)
        assert lc.wait_drained(5)

    def test_broken_active_fn_cannot_stall_exit(self):
        def boom():
            raise RuntimeError("probe died")

        lc = LifecycleCoordinator(
            deadline_s=5.0, active_fn=boom, clock=FakeClock(),
            sleep=lambda _dt: None,
        )
        assert lc.begin_drain()
        assert lc.wait_drained(5)  # treated as 0 in flight

    def test_events_journaled(self):
        flight.configure(enabled=True)
        lc = LifecycleCoordinator(
            deadline_s=5.0, active_fn=lambda: 0, clock=FakeClock(),
            sleep=lambda _dt: None,
        )
        lc.begin_drain("sigterm")
        lc.wait_drained(5)
        evs = flight.recorder().snapshot(etype="drain")
        phases = [e["phase"] for e in evs[-2:]]
        assert phases == ["begin", "complete"]


# ---------------------------------------------------------------------------
# extract_inflight / build_restore_report (sim/replay.py)
# ---------------------------------------------------------------------------
class TestExtractInflight:
    def _epoch1(self):
        return [
            _ev(1, "arrival", rid=1, prompt_len=3, max_new=6,
                ids=[5, 6, 7], seed=11, tenant="acme"),
            _ev(2, "token_emit", rid=1, toks=[20, 21]),
            _ev(3, "token_emit", rid=1, toks=[22]),
            _ev(4, "arrival", rid=2, prompt_len=4, max_new=6),  # no ids
            _ev(5, "arrival", rid=3, prompt_len=2, max_new=6, ids=[8, 9]),
            _ev(6, "complete", rid=3, n_tokens=6, stream_fnv=123),
            _ev(7, "arrival", rid=4, prompt_len=2, max_new=6, ids=[8, 9]),
            _ev(8, "resubmit", rid=4, outcome="gave_up", n_emitted=0),
            _ev(9, "drain", phase="begin", reason="sigterm", in_flight=2),
        ]

    def test_inflight_records_concat_token_emits(self):
        got = replay.extract_inflight(self._epoch1())
        assert got["arrivals"] == 4
        assert got["terminal"] == {"complete": 1, "gave_up": 1}
        recs = {r["rid"]: r for r in got["inflight"]}
        assert sorted(recs) == [1, 2]
        r1 = recs[1]
        assert r1["prompt"] == [5, 6, 7]
        assert r1["emitted"] == [20, 21, 22]
        assert not r1["synthetic_prompt"]
        assert r1["seed"] == 11 and r1["tenant"] == "acme"
        # lengths-only arrival: deterministic filler, marked synthetic
        r2 = recs[2]
        assert r2["synthetic_prompt"] and len(r2["prompt"]) == 4

    def test_restore_report_cross_epoch(self):
        epoch2 = [
            _ev(1, "restore", phase="rehydrate", key="doc:1", tokens=64),
            _ev(2, "restore", phase="resume", orig_rid=1, orig_epoch=1,
                n_emitted=3),
            _ev(3, "restore", phase="skip", orig_rid=2,
                reason="synthetic_prompt"),
            _ev(4, "arrival", rid=5, prompt_len=3, max_new=6, ids=[5, 6, 7]),
            _ev(5, "complete", rid=5, n_tokens=6, stream_fnv=9),
        ]
        rep = replay.build_restore_report({1: self._epoch1(), 2: epoch2})
        assert [e["epoch"] for e in rep["epochs"]] == [1, 2]
        e1, e2 = rep["epochs"]
        assert e1["arrivals"] == 4 and e1["completes"] == 1
        assert [r["rid"] for r in e1["inflight_at_end"]] == [1, 2]
        assert e1["drain"][0]["phase"] == "begin"
        assert e2["restored"] == [
            {"rid": None, "orig_rid": 1, "orig_epoch": 1, "n_emitted": 3}
        ]
        assert e2["rehydrated"] == [{"key": "doc:1", "tokens": 64}]
        assert e2["skipped"] == [
            {"orig_rid": 2, "reason": "synthetic_prompt"}
        ]


# ---------------------------------------------------------------------------
# warmth manifest (prefix cache + host spill store)
# ---------------------------------------------------------------------------
class _StubEngine:
    def __init__(self, block_bytes=8):
        self.block_bytes = block_bytes

    def prefix_buffer_zero(self):
        return (np.zeros(1, np.int8),)

    def build_segment_kv(self, ids, ctx, off):
        return (np.zeros(self.block_bytes, np.int8),)

    def splice_prefix(self, buf, block, off):
        return buf


def _pc_cfg(**kw):
    base = dict(
        enabled=True, max_prefix_tokens=4096, segment_buckets=(64, 2048),
        suffix_buckets=(128,), hbm_budget_mb=4, assembled_cache_entries=2,
    )
    base.update(kw)
    return PrefixCacheConfig(**base)


class TestWarmthManifest:
    def test_hotness_ranked_ids_round_trip(self):
        cache = PrefixCache(_pc_cfg(), _StubEngine(),
                            tiering=KVTieringConfig(enabled=True))
        hot = [("hot", list(range(16)))]
        cold = [("cold", list(range(8)))]
        for _ in range(4):
            cache.prefix_for(hot)
        cache.prefix_for(cold)
        man = cache.warmth_manifest(top_n=8)
        assert [r["key"] for r in man] == ["hot", "cold"]
        assert man[0]["ids"] == list(range(16))
        assert man[0]["tokens"] == 16
        assert man[0]["score"] > man[1]["score"]
        # top_n truncation (scores decay in real time, so compare keys)
        assert [r["key"] for r in cache.warmth_manifest(top_n=1)] == ["hot"]

    def test_spilled_flag_marks_host_spill_residents(self):
        cache = PrefixCache(
            _pc_cfg(), _StubEngine(),
            tiering=KVTieringConfig(enabled=True, host_spill_mb=1),
        )
        cache.prefix_for([("a", list(range(8)))])
        cache.prefix_for([("b", list(range(8)))])
        # park "a"'s planes in the host store the way a cold demotion does
        # (entry keys are (chunk_key, slot) tuples)
        cache.spill.put(("a", 0), (np.zeros(16, np.int8),), {"tier": "cold"})
        man = {r["key"]: r for r in cache.warmth_manifest()}
        assert man["a"]["spilled"] and not man["b"]["spilled"]

    def test_host_spill_manifest_inventory(self):
        store = HostSpillStore(budget_mb=1)
        store.put("k1", (np.zeros(4, np.int8),), {"layer": 0})
        store.put("k2", (np.zeros(8, np.int8),))
        man = store.manifest()
        assert [r["key"] for r in man] == ["k1", "k2"]  # oldest first
        assert man[0]["nbytes"] == 4 and man[0]["meta"] == {"layer": 0}
        assert man[1]["nbytes"] == 8


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------
class TestLifecycleConfig:
    def test_wal_knobs_round_trip(self):
        fl = FlightConfig.from_env({
            "TPU_RAG_FLIGHT_WAL": "1",
            "TPU_RAG_FLIGHT_WAL_DIR": "/pvc/wal",
            "TPU_RAG_FLIGHT_WAL_SEGMENT_EVENTS": "128",
            "TPU_RAG_FLIGHT_WAL_SEGMENTS": "16",
            "TPU_RAG_FLIGHT_WAL_RESTORE": "0",
            "TPU_RAG_FLIGHT_WAL_RESTORE_CHUNKS": "3",
        })
        assert fl.wal and fl.wal_dir == "/pvc/wal"
        assert fl.wal_segment_events == 128 and fl.wal_segments == 16
        assert not fl.wal_restore and fl.wal_restore_chunks == 3

    def test_wal_defaults_off(self):
        fl = FlightConfig.from_env({})
        assert not fl.wal and fl.wal_restore

    def test_wal_knob_validation(self):
        with pytest.raises(ValueError, match="SEGMENT_EVENTS"):
            FlightConfig.from_env({"TPU_RAG_FLIGHT_WAL_SEGMENT_EVENTS": "0"})
        with pytest.raises(ValueError, match="WAL_SEGMENTS"):
            FlightConfig.from_env({"TPU_RAG_FLIGHT_WAL_SEGMENTS": "1"})
        with pytest.raises(ValueError, match="RESTORE_CHUNKS"):
            FlightConfig.from_env(
                {"TPU_RAG_FLIGHT_WAL_RESTORE_CHUNKS": "-1"})

    def test_drain_knobs_round_trip(self):
        cfg = AppConfig.from_env({
            "TPU_RAG_DRAIN_DEADLINE_S": "12.5",
            "TPU_RAG_DRAIN_RETRY_AFTER_S": "0.5",
        })
        assert cfg.resilience.drain_deadline_s == pytest.approx(12.5)
        assert cfg.resilience.drain_retry_after_s == pytest.approx(0.5)
        with pytest.raises(ValueError, match="DRAIN_DEADLINE_S"):
            AppConfig.from_env({"TPU_RAG_DRAIN_DEADLINE_S": "0"})


# ---------------------------------------------------------------------------
# HTTP drain contract (make drain-smoke)
# ---------------------------------------------------------------------------
class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode(
            "utf-8", "replace"
        )


def make_lifecycle_service(tmp_path, resilience=None, flight_cfg=None,
                           continuous=False):
    """make_service (tests/test_resilience.py) with the lifecycle knobs
    exposed: drain deadlines, a test-local incident spool, optionally a
    WAL-backed flight recorder and a continuous scheduler (the restore
    path's substrate)."""
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(
        model=llama_cfg, encoder=enc_cfg,
        resilience=resilience or ResilienceConfig(),
        flight=flight_cfg or FlightConfig(
            spool_dir=str(tmp_path / "spool"), cooldown_s=0.0,
        ),
    )
    params = init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32)
    engine = InferenceEngine(
        llama_cfg, params, sampling=GREEDY,
        engine_config=EngineConfig(
            prompt_buckets=(128, 256), max_batch_size=2,
            max_seq_len=4096 + 256,
        ),
        dtypes=FP32,
    )
    sched = None
    if continuous:
        ceng = ContinuousEngine(
            llama_cfg, params, sampling=GREEDY, engine_config=ENG_CFG,
            dtypes=FP32,
        )
        sched = ContinuousScheduler(ceng, retry_backoff_s=0.0)
    encoder = EncoderRunner(
        enc_cfg, init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32, 64), max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size)
    svc = RagService(
        cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store,
        scheduler=sched,
    )
    svc.ready = True
    texts = ["alpha beta gamma", "delta epsilon zeta"]
    vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
    store.add(list(vecs), [
        {"filename": "f", "chunk_id": i, "text": t}
        for i, t in enumerate(texts)
    ])
    return svc


class TestHttpDrain:
    def test_drain_sheds_new_work_while_inflight_completes(self, tmp_path):
        svc = make_lifecycle_service(
            tmp_path,
            resilience=ResilienceConfig(drain_deadline_s=30.0,
                                        drain_retry_after_s=3.0),
        )
        try:
            client = create_app(svc).test_client()
            # make the in-flight window deterministic: the request holds
            # its admission slot until the test says otherwise
            release = threading.Event()
            orig_answer = svc.answer

            def slow_answer(*a, **k):
                body = orig_answer(*a, **k)
                release.wait(30)
                return body

            svc.answer = slow_answer
            results = []
            t = threading.Thread(target=lambda: results.append(
                client.post("/generate", json={"prompt": "alpha"})
            ))
            t.start()
            deadline = time.monotonic() + 10
            while svc.admission.active == 0 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert svc.admission.active == 1

            r = client.post("/drain")
            assert r.status_code == 202
            body = r.get_json()
            assert body["state"] == DRAINING and body["started"]
            assert body["active"] == 1
            # second POST: idempotent report, not a second drain
            r2 = client.post("/drain")
            assert r2.status_code == 200 and not r2.get_json()["started"]

            # readiness flips (endpoints stop routing); liveness holds
            # (the kubelet must NOT restart a pod mid-drain)
            h = client.get("/healthz")
            assert h.status_code == 503
            hb = h.get_json()
            assert hb["status"] == "draining" and hb["draining"]
            assert client.get("/healthz?live=1").status_code == 200

            # new work sheds 503 reason="draining" + the drain Retry-After
            shed = client.post("/generate", json={"prompt": "alpha"})
            assert shed.status_code == 503
            sb = shed.get_json()
            assert sb["reason"] == "draining"
            assert sb["error"] == "server draining"
            assert sb["retry_after_s"] == pytest.approx(3.0)
            assert int(shed.headers["Retry-After"]) >= 3

            # the in-flight request finishes under the deadline: 200, not
            # a 5xx — the whole point of draining over killing
            release.set()
            t.join(30)
            assert results and results[0].status_code == 200
            assert svc.lifecycle.wait_drained(10)
            assert svc.lifecycle.state == DRAINED
            assert not svc.lifecycle.timed_out
        finally:
            release.set()
            svc.shutdown()

    def test_drain_deadline_overrun_spools_incident(self, tmp_path):
        spool = tmp_path / "spool"
        svc = make_lifecycle_service(
            tmp_path,
            resilience=ResilienceConfig(drain_deadline_s=0.3),
        )
        try:
            flight.configure(enabled=True)
            flight.emit("arrival", 1, prompt_len=1, max_new=1)
            with svc.admission.admit():  # wedged in-flight work
                assert svc.lifecycle.begin_drain("http")
                assert svc.lifecycle.wait_drained(10)
            assert svc.lifecycle.timed_out
            assert svc.lifecycle.stragglers == 1
            bundles = [
                n for n in os.listdir(spool) if n.endswith(".json")
            ]
            assert bundles, "drain_timeout must spool an incident bundle"
            with open(spool / sorted(bundles)[-1]) as f:
                bundle = json.load(f)
            assert bundle["trigger"] == "drain_timeout"
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# service-level warm restart (in-process, deterministic)
# ---------------------------------------------------------------------------
class TestServiceRestore:
    def _service_with_wal(self, tmp_path):
        wal_dir = str(tmp_path / "wal")
        return make_lifecycle_service(
            tmp_path,
            flight_cfg=FlightConfig(
                spool_dir=str(tmp_path / "spool"), cooldown_s=0.0,
                wal=True, wal_dir=wal_dir, arrival_ids=True,
            ),
            continuous=True,
        ), wal_dir

    def test_restore_resumes_byte_identical_to_oracle(self, tmp_path):
        # epoch 1: a dead incarnation that had rid 1 in flight with the
        # first tokens already emitted. The emitted prefix must be what
        # the engine REALLY emits (the WAL only ever holds true history),
        # so compute the oracle first on an identical engine.
        prompt = [5, 6, 7, 8]
        oracle_eng = ContinuousEngine(
            LlamaConfig.tiny(vocab_size=300),
            init_llama_params(
                jax.random.PRNGKey(0), LlamaConfig.tiny(vocab_size=300),
                FP32),
            sampling=GREEDY, engine_config=ENG_CFG, dtypes=FP32,
        )
        oracle_sched = ContinuousScheduler(oracle_eng, retry_backoff_s=0.0)
        try:
            oracle = oracle_sched.submit(prompt, max_new_tokens=8,
                                         timeout=60)
        finally:
            oracle_sched.shutdown()
        assert len(oracle) == 8

        wal_dir = str(tmp_path / "wal")
        w1 = flight.FlightWAL(wal_dir)
        w1.append(_ev(1, "arrival", rid=1, prompt_len=len(prompt),
                      max_new=8, ids=prompt))
        w1.append(_ev(2, "token_emit", rid=1, toks=oracle[:3]))
        w1.append(_ev(3, "arrival", rid=2, prompt_len=3, max_new=8))
        w1.close()

        svc, _ = self._service_with_wal(tmp_path)
        try:
            assert svc.flight_wal is not None and svc.flight_wal.epoch == 2
            summary = svc.restore_from_wal(wait=True)
            assert summary["resumed"] == 1
            # lengths-only arrival: skipped, journaled as such
            assert summary["skipped"] == 1
            assert summary["results"][1] == oracle
            # the resumed request completed INTO the new epoch's WAL —
            # a second crash would reconstruct the full stream from it
            epochs = flight.scan_wal(wal_dir)
            e2 = epochs[2]
            assert any(e["type"] == "complete" for e in e2)
            skips = [e for e in e2 if e["type"] == "restore"
                     and e.get("phase") == "skip"]
            assert skips and skips[0]["reason"] == "synthetic_prompt"
        finally:
            svc.shutdown()

    def test_restore_disabled_by_knob(self, tmp_path):
        w1 = flight.FlightWAL(str(tmp_path / "wal"))
        w1.append(_ev(1, "arrival", rid=1, prompt_len=2, max_new=4,
                      ids=[5, 6]))
        w1.close()
        svc = make_lifecycle_service(
            tmp_path,
            flight_cfg=FlightConfig(
                spool_dir=str(tmp_path / "spool"), wal=True,
                wal_dir=str(tmp_path / "wal"), wal_restore=False,
            ),
            continuous=True,
        )
        try:
            summary = svc.restore_from_wal(wait=True)
            assert summary == {"resumed": 0, "skipped": 0,
                               "rehydrated": 0, "results": {}}
        finally:
            svc.shutdown()

    def test_persist_writes_warmth_manifest_durably(self, tmp_path):
        svc, wal_dir = self._service_with_wal(tmp_path)
        try:
            staged = [("doc:0", [4, 5, 6, 7])]

            class FakeCache:
                def warmth_manifest(self, top_n=8):
                    return [{"key": k, "ids": ids, "tokens": len(ids),
                             "score": 1.0, "spilled": False}
                            for k, ids in staged[:top_n]]

                def prefix_for(self, segments):
                    calls.append(segments)
                    return object()

            calls = []
            svc.engine.prefix_cache = FakeCache()
            svc._persist_for_restart()
            path = os.path.join(wal_dir, "warmth_manifest.json")
            with open(path) as f:
                doc = json.load(f)
            assert doc["entries"][0]["key"] == "doc:0"
            # ...and the next incarnation pre-stages exactly those ids
            flight.configure(enabled=True)
            n = svc._rehydrate_warmth(svc.config.flight)
            assert n == 1
            assert calls == [[("doc:0", [4, 5, 6, 7])]]
            rehy = [e for e in flight.recorder().snapshot(etype="restore")
                    if e.get("phase") == "rehydrate"]
            assert rehy and rehy[-1]["tokens"] == 4
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# the chaos pin: SIGKILL mid-decode, restore, byte-identical streams
# (make restart-smoke)
# ---------------------------------------------------------------------------
_CHAOS_COMMON = """
import sys, time, threading
import jax
from rag_llm_k8s_tpu.core.config import (
    DTypePolicy, EngineConfig, LlamaConfig, SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import (
    ContinuousEngine, ContinuousScheduler,
)
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight

FP32 = DTypePolicy.fp32()
CFG = LlamaConfig.tiny()
ENG_CFG = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4,
                       max_seq_len=64)
SAMP = SamplingConfig(do_sample=False, max_new_tokens=40)
PROMPTS = ([5, 6, 7, 8], [9, 10, 11, 12])

def build_engine():
    params = init_llama_params(jax.random.PRNGKey(0), CFG, FP32)
    return ContinuousEngine(CFG, params, sampling=SAMP,
                            engine_config=ENG_CFG, dtypes=FP32)
"""

_CHAOS_VICTIM = _CHAOS_COMMON + """
wal_dir = sys.argv[1]
eng = build_engine()
# throttle decode so the parent's SIGKILL reliably lands mid-stream
orig_step = eng.step
def slow_step(*a, **k):
    time.sleep(0.05)
    return orig_step(*a, **k)
eng.step = slow_step
flight.configure(enabled=True, arrival_ids=True,
                 wal=flight.FlightWAL(wal_dir))
sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
for p in PROMPTS:
    threading.Thread(
        target=lambda p=p: sched.submit(p, max_new_tokens=40, timeout=600),
        daemon=True,
    ).start()
print("VICTIM-UP", flush=True)
time.sleep(600)  # the parent SIGKILLs us mid-decode
"""

_CHAOS_RESTORER = _CHAOS_COMMON + """
import json
from rag_llm_k8s_tpu.sim import replay

wal_dir, out_path = sys.argv[1], sys.argv[2]
eng = build_engine()
wal = flight.FlightWAL(wal_dir)
flight.configure(enabled=True, arrival_ids=True, wal=wal)
sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
epochs = flight.scan_wal(wal_dir)
dead = [e for e in sorted(epochs) if e < wal.epoch]
records = replay.extract_inflight(epochs[dead[-1]])["inflight"]
out = {}
for rec in records:
    flight.emit("restore", phase="resume", orig_rid=rec["rid"],
                orig_epoch=dead[-1], n_emitted=len(rec["emitted"]))
    toks = sched.submit(rec["prompt"], max_new_tokens=rec["max_new"],
                        resume_emitted=rec["emitted"], timeout=600)
    out[str(rec["rid"])] = {
        "prompt": rec["prompt"], "tokens": toks,
        "n_emitted": len(rec["emitted"]),
    }
with open(out_path, "w") as f:
    json.dump(out, f)
sched.shutdown()
print("RESTORED", flush=True)
"""


class TestCrashRestartChaos:
    def test_sigkill_mid_decode_then_byte_identical_resume(
            self, tmp_path, tiny_oracle_streams):
        """The acceptance pin: SIGKILL a process with two requests
        mid-decode, restore a fresh process against the same WAL dir,
        and require every delivered stream byte-identical to an
        uninterrupted run — prefill work and already-decoded tokens are
        not re-earned, they are replayed from the WAL."""
        wal_dir = str(tmp_path / "wal")
        victim_py = tmp_path / "victim.py"
        victim_py.write_text(_CHAOS_VICTIM)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo_root)
        victim = subprocess.Popen(
            [sys.executable, str(victim_py), wal_dir],
            cwd=os.path.dirname(os.path.dirname(__file__)), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            # wait until BOTH requests have proven token_emit progress in
            # the WAL and neither has completed — the mid-decode moment
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                if victim.poll() is not None:
                    pytest.fail(
                        "victim exited early:\n" + victim.stdout.read()
                    )
                evs = flight.scan_wal(wal_dir).get(1, [])
                emitted = {e.get("rid") for e in evs
                           if e["type"] == "token_emit"}
                done = {e.get("rid") for e in evs
                        if e["type"] == "complete"}
                if len(emitted) >= 2 and not done:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("WAL never showed 2 requests mid-decode")
            os.kill(victim.pid, signal.SIGKILL)
            victim.wait(30)
        finally:
            if victim.poll() is None:
                victim.kill()

        evs = flight.scan_wal(wal_dir)[1]
        dead = replay.extract_inflight(evs)
        assert len(dead["inflight"]) == 2
        assert all(r["emitted"] for r in dead["inflight"])
        assert all(not r["synthetic_prompt"] for r in dead["inflight"])

        restorer_py = tmp_path / "restorer.py"
        restorer_py.write_text(_CHAOS_RESTORER)
        out_path = str(tmp_path / "restored.json")
        r = subprocess.run(
            [sys.executable, str(restorer_py), wal_dir, out_path],
            cwd=os.path.dirname(os.path.dirname(__file__)), env=env,
            capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        with open(out_path) as f:
            restored = json.load(f)
        assert len(restored) == 2
        oracle = tiny_oracle_streams
        for rec in restored.values():
            assert rec["n_emitted"] >= 1  # genuinely resumed, not redone
            want = oracle[tuple(rec["prompt"])]
            assert rec["tokens"] == want, (
                "resumed stream diverged from the uninterrupted oracle"
            )
        # the restart journaled its side: epoch 2 resumes + completions
        e2 = flight.scan_wal(wal_dir)[2]
        resumes = [e for e in e2 if e["type"] == "restore"
                   and e.get("phase") == "resume"]
        assert {e["orig_rid"] for e in resumes} == \
            {r["rid"] for r in dead["inflight"]}
        assert sum(1 for e in e2 if e["type"] == "complete") == 2


@pytest.fixture(scope="module")
def tiny_oracle_streams():
    """Uninterrupted greedy streams for the chaos prompts, computed on an
    engine identical to the subprocess scripts' (same config, same
    PRNGKey(0) init — cross-process deterministic)."""
    cfg = LlamaConfig.tiny()
    eng = ContinuousEngine(
        cfg, init_llama_params(jax.random.PRNGKey(0), cfg, FP32),
        sampling=SamplingConfig(do_sample=False, max_new_tokens=40),
        engine_config=ENG_CFG, dtypes=FP32,
    )
    sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
    out = {}
    try:
        for p in ([5, 6, 7, 8], [9, 10, 11, 12]):
            out[tuple(p)] = sched.submit(p, max_new_tokens=40, timeout=120)
    finally:
        sched.shutdown()
    return out
