"""Orbax sharded param-cache round trip."""

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
from rag_llm_k8s_tpu.models.checkpoint import load_params_cached, restore_params, save_params
from rag_llm_k8s_tpu.models.llama import init_llama_params

FP32 = DTypePolicy.fp32()


class TestParamCache:
    def test_save_restore_roundtrip(self, tmp_path):
        cfg = LlamaConfig.tiny()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        save_params(str(tmp_path / "ck"), params)
        restored = restore_params(str(tmp_path / "ck"), params)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params,
            restored,
        )

    def test_load_cached_populates_then_hits(self, tmp_path):
        cfg = LlamaConfig.tiny()
        params = init_llama_params(jax.random.PRNGKey(1), cfg, FP32)
        calls = []

        def convert():
            calls.append(1)
            return params

        got1 = load_params_cached(
            str(tmp_path), convert, abstract_params_fn=lambda: params
        )
        got2 = load_params_cached(
            str(tmp_path), convert, abstract_params_fn=lambda: params
        )
        assert len(calls) == 1  # second load came from the cache
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            got1,
            got2,
        )

    def test_sharded_restore(self, mesh_tp8):
        """Restore places shards per the abstract tree's NamedShardings."""
        import dataclasses
        import tempfile

        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg = dataclasses.replace(LlamaConfig.tiny(), num_heads=8, num_kv_heads=8, head_dim=8)
        params = shard_llama_params(
            init_llama_params(jax.random.PRNGKey(2), cfg, FP32), mesh_tp8
        )
        with tempfile.TemporaryDirectory() as d:
            save_params(d + "/ck", params)
            restored = restore_params(d + "/ck", params)
        wq = restored["layers"]["attn"]["wq"]["kernel"]
        assert wq.sharding == params["layers"]["attn"]["wq"]["kernel"].sharding
