"""Shadow-traffic quality auditor (ISSUE 15): online divergence tracking
for every approximation in the serving path.

The contracts under test (obs/shadow.py, engine.score_exact,
docs/OBSERVABILITY.md "Shadow quality auditor"):

- **Exact replay**: ``score_exact`` is a teacher-forced forward whose
  argmax chain reproduces the greedy decode stream bit-for-bit — so
  byte-identity traffic (exact-chain prefix reuse, paged speculation)
  audits at divergence rate 0.0, non-vacuously.
- **Tolerance**: FORCED warm-tier (int8) serving audits within the
  pinned 0.15 logit tolerance — the divergence evidence (minimal
  explaining logit perturbation) can never exceed the per-logit drift
  the warm contract already bounds — and the audit's attribution names
  ``warm_tier``.
- **Same report, two sources**: ``GET /debug/quality`` (live state) and
  ``scripts/flightview.py --quality`` (offline ``shadow_audit`` journal
  events) render through ONE function and agree figure for figure.
- **Bursts**: the second diverged audit inside the burst window spools a
  ``quality_divergence`` incident bundle.
- **Discipline**: sampling/backlog/headroom/eligibility skips are
  counted honestly; the auditor never queues unboundedly and never
  fails the response it rides on.
"""

import dataclasses
import json
import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EngineConfig,
    FlightConfig,
    KVTieringConfig,
    LlamaConfig,
    PrefixCacheConfig,
    SamplingConfig,
    ShadowConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.obs import shadow as obs_shadow
from rag_llm_k8s_tpu.obs import slo as obs_slo
from rag_llm_k8s_tpu.server.app import RagService, create_app

from scripts import flightview  # noqa: E402

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=10)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(vocab_size=128)
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params


def _oneshot(cfg, params, **ec_kw):
    ec = EngineConfig(
        prompt_buckets=(64,), max_batch_size=2, max_seq_len=256,
        speculative="off", **ec_kw,
    )
    return InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=ec, dtypes=FP32
    )


class _FixedRng:
    """Deterministic sampler: yields the given values in order."""

    def __init__(self, values):
        self._values = list(values)

    def random(self):
        return self._values.pop(0) if self._values else 1.0


def _auditor(score_fn, sample_rate=1.0, **kw):
    return obs_shadow.ShadowAuditor(
        ShadowConfig(sample_rate=sample_rate), score_fn=score_fn, **kw
    )


# ---------------------------------------------------------------------------
# state / report primitives (pure, jax-free)
# ---------------------------------------------------------------------------
class TestStateAndReport:
    def test_record_and_render(self):
        st = obs_shadow.new_state()
        obs_shadow.record(st, {
            "outcome": "clean", "n": 8, "err": 0.0,
            "approx": ["prefix_reuse"],
        })
        obs_shadow.record(st, {
            "outcome": "diverged", "n": 4, "pos": 3, "err": 0.12,
            "approx": ["warm_tier", "prefix_reuse"],
        })
        obs_shadow.record(st, {"outcome": "skipped", "reason": "sampled"})
        rep = obs_shadow.render_report(st)
        assert rep["audits"] == {
            "clean": 1, "diverged": 1, "skipped": 1, "failed": 0,
        }
        assert rep["divergence_rate"] == 0.5
        assert rep["skips"] == {"sampled": 1}
        assert rep["attribution"]["prefix_reuse"] == {
            "clean": 1, "diverged": 1,
        }
        assert rep["attribution"]["warm_tier"] == {"clean": 0, "diverged": 1}
        assert rep["tokens_compared"] == 12
        assert rep["logit_err"]["max"] == 0.12
        # 0.12 lands in the le_0.15 bucket — the tolerance bound
        assert rep["logit_err"]["hist"]["le_0.15"] == 1
        assert rep["first_divergence_token"]["hist"]["le_4"] == 1

    def test_no_approx_counts_as_none(self):
        st = obs_shadow.new_state()
        obs_shadow.record(st, {"outcome": "clean", "n": 2, "err": 0.0})
        assert obs_shadow.render_report(st)["attribution"]["none"] == {
            "clean": 1, "diverged": 0,
        }

    def test_state_from_events_matches_live_record(self):
        evs = [
            {"seq": 2, "type": "shadow_audit", "outcome": "diverged",
             "n": 3, "pos": 2, "err": 0.3, "approx": ["splice"]},
            {"seq": 1, "type": "shadow_audit", "outcome": "clean",
             "n": 5, "err": 0.0, "approx": []},
            {"seq": 3, "type": "goodput_window", "kind": "decode"},
        ]
        st = obs_shadow.state_from_events(evs)
        live = obs_shadow.new_state()
        obs_shadow.record(live, evs[1])
        obs_shadow.record(live, evs[0])
        assert obs_shadow.render_report(st) == obs_shadow.render_report(live)

    def test_quantiles_from_hist(self):
        st = obs_shadow.new_state()
        for err in (0.01, 0.01, 0.01, 2.0):
            obs_shadow.record(
                st, {"outcome": "diverged", "n": 1, "pos": 0, "err": err}
            )
        rep = obs_shadow.render_report(st)
        assert rep["logit_err"]["p50"] == 0.01
        # quantiles report BUCKET BOUNDS (2.0 lands in the le_2.5 bucket)
        assert rep["logit_err"]["p99"] == 2.5
        # overflow quantile falls back to the tracked max
        obs_shadow.record(
            st, {"outcome": "diverged", "n": 1, "pos": 0, "err": 7.5}
        )
        rep = obs_shadow.render_report(st)
        assert rep["logit_err"]["max"] == 7.5


# ---------------------------------------------------------------------------
# auditor discipline (fake score_fn — no device work)
# ---------------------------------------------------------------------------
class TestAuditorDiscipline:
    @staticmethod
    def _score_clean(prompt, emitted):
        return {
            "argmax": list(emitted),
            "max_logit": [1.0] * len(emitted),
            "chosen_logit": [1.0] * len(emitted),
        }

    def test_sampler_selects_by_rate(self):
        aud = _auditor(
            self._score_clean, sample_rate=0.5,
            rng=_FixedRng([0.4, 0.6, 0.4]),
        )
        try:
            assert aud.observe([1, 2], prompt_ids=[3]) is True
            assert aud.observe([1, 2], prompt_ids=[3]) is False  # 0.6 >= 0.5
            assert aud.observe([1, 2], prompt_ids=[3]) is True
            assert aud.drain()
            st = aud.stats()
            assert st["seen"] == 3 and st["selected"] == 2
            assert st["audits_clean"] == 2
        finally:
            aud.shutdown()

    def test_ineligible_counts_sampled_skip_only_when_selected(self):
        aud = _auditor(
            self._score_clean, sample_rate=0.5, rng=_FixedRng([0.9, 0.1]),
        )
        try:
            # unsampled: NOT a skip
            aud.observe([1], prompt_ids=[2], eligible=False)
            # selected + ineligible: counted
            aud.observe([1], prompt_ids=[2], eligible=False)
            assert aud.drain()
            st = aud.stats()
            assert st["skip_sampled"] == 1.0
            assert st["audits_skipped"] == 1.0
        finally:
            aud.shutdown()

    def test_empty_and_missing_prompt_skip(self):
        aud = _auditor(self._score_clean)
        try:
            aud.observe([], prompt_ids=[1], force=True)
            aud.observe([1], prompt_fn=lambda: None, force=True)
            aud.observe([1], prompt_fn=lambda: 1 / 0, force=True)
            assert aud.drain()
            st = aud.stats()
            assert st["skip_empty"] == 1.0
            assert st["skip_no_prompt"] == 2.0
        finally:
            aud.shutdown()

    def test_backlog_bound_skips_instead_of_queueing(self):
        import threading

        gate = threading.Event()

        def slow(prompt, emitted):
            gate.wait(5.0)
            return self._score_clean(prompt, emitted)

        aud = obs_shadow.ShadowAuditor(
            ShadowConfig(sample_rate=1.0, backlog=1), score_fn=slow,
        )
        try:
            aud.observe([1], prompt_ids=[2], force=True)  # worker takes it
            time.sleep(0.1)  # let the worker pop it (inflight, queue empty)
            aud.observe([1], prompt_ids=[2], force=True)  # queued
            aud.observe([1], prompt_ids=[2], force=True)  # over backlog
            st = aud.stats()
            assert st["skip_backlog"] >= 1.0
            gate.set()
            assert aud.drain()
        finally:
            gate.set()
            aud.shutdown()

    def test_headroom_never_clears_skips(self):
        aud = obs_shadow.ShadowAuditor(
            ShadowConfig(sample_rate=1.0), score_fn=self._score_clean,
            headroom_fn=lambda: False,
        )
        aud._HEADROOM_TRIES = 2  # keep the poll budget test-sized
        try:
            aud.observe([1], prompt_ids=[2], force=True)
            assert aud.drain()
            assert aud.stats()["skip_headroom"] == 1.0
        finally:
            aud.shutdown()

    def test_oversize_valueerror_is_a_skip_not_a_failure(self):
        def oversize(prompt, emitted):
            raise ValueError("too long")

        aud = _auditor(oversize)
        try:
            aud.observe([1], prompt_ids=[2], force=True)
            assert aud.drain()
            st = aud.stats()
            assert st["skip_oversize"] == 1.0 and st["audits_failed"] == 0.0
        finally:
            aud.shutdown()

    def test_crash_is_contained_as_failed(self):
        def boom(prompt, emitted):
            raise RuntimeError("device fell over")

        aud = _auditor(boom)
        try:
            aud.observe([1], prompt_ids=[2], force=True)
            assert aud.drain()
            assert aud.stats()["audits_failed"] == 1.0
        finally:
            aud.shutdown()

    def test_burst_hook_fires_on_second_divergence_in_window(self):
        def diverge(prompt, emitted):
            return {
                "argmax": [t + 1 for t in emitted],
                "max_logit": [1.0] * len(emitted),
                "chosen_logit": [0.9] * len(emitted),
            }

        clock = {"t": 0.0}
        bursts = []
        aud = obs_shadow.ShadowAuditor(
            ShadowConfig(sample_rate=1.0, burst_window_s=10.0),
            score_fn=diverge,
            on_burst=lambda: bursts.append(1),
            clock=lambda: clock["t"],
        )
        try:
            aud.observe([1], prompt_ids=[2], force=True)
            assert aud.drain()
            assert not bursts  # one divergence is routine
            clock["t"] = 20.0  # the first stamp ages out of the window
            aud.observe([1], prompt_ids=[2], force=True)
            assert aud.drain()
            assert not bursts
            clock["t"] = 25.0  # second divergence INSIDE the window
            aud.observe([1], prompt_ids=[2], force=True)
            assert aud.drain()
            assert bursts == [1]
        finally:
            aud.shutdown()

    def test_on_result_receives_the_journal_payload(self):
        got = []
        aud = _auditor(
            self._score_clean, on_result=lambda rid, ev: got.append((rid, ev))
        )
        try:
            aud.observe([5, 6], prompt_ids=[1], approx=("spec_verify",),
                        request_id=42, force=True)
            assert aud.drain()
            rid, ev = got[0]
            assert rid == 42
            assert ev["outcome"] == "clean" and ev["n"] == 2
            assert ev["approx"] == ["spec_verify"]
            # the live state folded EXACTLY this payload (round-trip anchor)
            st = obs_shadow.state_from_events(
                [dict(ev, type="shadow_audit", seq=0)]
            )
            assert st["audits"]["clean"] == 1
        finally:
            aud.shutdown()


# ---------------------------------------------------------------------------
# the exact-path scorer (engine.score_exact)
# ---------------------------------------------------------------------------
class TestScoreExact:
    def test_argmax_chain_matches_greedy_stream(self, tiny):
        cfg, params = tiny
        eng = _oneshot(cfg, params)
        prompt = [cfg.bos_token_id, 5, 9, 12, 7, 7, 9]
        out = eng.generate([prompt])[0]
        assert out
        score = eng.score_exact(prompt, out)
        assert [int(t) for t in score["argmax"]] == out
        gaps = score["max_logit"] - score["chosen_logit"]
        assert float(np.max(gaps)) == 0.0  # delivered IS the exact argmax

    def test_perturbed_stream_locates_the_divergence(self, tiny):
        cfg, params = tiny
        eng = _oneshot(cfg, params)
        prompt = [cfg.bos_token_id, 5, 9, 12, 7, 7, 9]
        out = eng.generate([prompt])[0]
        bad = list(out)
        bad[3] = (bad[3] + 1) % cfg.vocab_size
        s = eng.score_exact(prompt, bad)
        assert int(s["argmax"][3]) != bad[3]
        assert [int(t) for t in s["argmax"][:3]] == bad[:3]
        gap = float(s["max_logit"][3] - s["chosen_logit"][3])
        assert gap > 0.0

    def test_oversize_raises_value_error(self, tiny):
        cfg, params = tiny
        eng = _oneshot(cfg, params)
        cap = eng.engine_config.max_chunked_prompt
        with pytest.raises(ValueError):
            eng.score_exact([1] * (cap + 1), [2])
        with pytest.raises(ValueError):
            eng.score_exact([1, 2, 3], [])

    def test_long_sequence_chunks_through_the_scorer(self, tiny):
        """A sequence longer than the largest prompt bucket still scores
        (the scorer's own chunked path) and stays consistent with the
        engine's chunked-prefill greedy stream."""
        cfg, params = tiny
        eng = _oneshot(cfg, params)
        prompt = [cfg.bos_token_id] + [3 + (i % 40) for i in range(90)]
        out = eng.generate([prompt])[0]
        assert out
        score = eng.score_exact(prompt, out)
        assert [int(t) for t in score["argmax"]] == out


# ---------------------------------------------------------------------------
# approximation fingerprints
# ---------------------------------------------------------------------------
PC = PrefixCacheConfig(
    enabled=True, hbm_budget_mb=64, max_prefix_tokens=128,
    segment_buckets=(16, 32, 64), suffix_buckets=(16, 32),
)


def _segments(cfg, rng, tag):
    head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 7)))
    chunk = list(map(int, rng.integers(3, 120, 11)))
    return [(f"head:{tag}", head), (f"chunk:{tag}", chunk)]


class TestFingerprints:
    def test_fresh_build_is_unfingerprinted_then_reuse_marks(self, tiny):
        cfg, params = tiny
        eng = _oneshot(cfg, params, prefix_cache=PC)
        rng = np.random.default_rng(3)
        segments = _segments(cfg, rng, "fp")
        cp0 = eng.prefix_cache.prefix_for(segments)
        assert cp0.approx == ()  # everything built fresh: no approximation
        # memo re-serve: the whole chain came from cache
        cp1 = eng.prefix_cache.prefix_for(segments)
        assert "prefix_reuse" in cp1.approx
        # non-memo hit path too: drop the assembled buffer, keep entries
        eng.prefix_cache._assembled.clear()
        eng.prefix_cache.assembled_bytes = 0
        cp2 = eng.prefix_cache.prefix_for(segments)
        assert "prefix_reuse" in cp2.approx
        assert cp2.computed_tokens == 0

    def test_forced_warm_marks_warm_tier(self, tiny):
        cfg, params = tiny
        tiering = KVTieringConfig(
            enabled=True, warm_below=1e9, cold_below=0.01,
            half_life_s=3600.0, retier_interval_s=3600.0,
        )
        eng = _oneshot(cfg, params, prefix_cache=PC, kv_tiering=tiering)
        rng = np.random.default_rng(5)
        segments = _segments(cfg, rng, "warmfp")
        cache = eng.prefix_cache
        cache.prefix_for(segments)
        assert cache.force_demote("warm") == 2
        cache._assembled.clear()
        cache.assembled_bytes = 0
        cp = cache.prefix_for(segments)
        assert "warm_tier" in cp.approx and "prefix_reuse" in cp.approx
        # a memo re-serve of the warm-built buffer keeps the fingerprint
        cp2 = cache.prefix_for(segments)
        assert "warm_tier" in cp2.approx

    @pytest.mark.parametrize("ledger_on", [True, False])
    def test_continuous_spec_stamps_info_approx(self, tiny, ledger_on):
        """The spec_verify fingerprint comes from ENGINE state, so
        turning the goodput ledger off (an unrelated observability knob)
        must not erase speculation attribution from shadow audits."""
        from rag_llm_k8s_tpu.core.config import GoodputConfig

        cfg, params = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64,
                kv_paged=True, kv_block_size=16,
                spec_paged=True, spec_paged_tokens=4,
                goodput=GoodputConfig(enabled=ledger_on),
            ),
            dtypes=FP32,
        )
        sched = ContinuousScheduler(eng)
        try:
            info = {}
            out = sched.submit(
                [5, 7, 5, 7, 5, 7, 5, 7, 5, 7], max_new_tokens=10,
                timeout=120, info=info,
            )
            assert out
            assert "spec_verify" in info.get("approx", ())
            assert not eng._spec_rids  # popped at delivery, never leaked
        finally:
            sched.shutdown()


# ---------------------------------------------------------------------------
# config + SLO wiring
# ---------------------------------------------------------------------------
class TestConfig:
    def test_env_round_trip(self):
        cfg = AppConfig.from_env({
            "TPU_RAG_SHADOW": "0",
            "TPU_RAG_SHADOW_SAMPLE_RATE": "0.5",
            "TPU_RAG_SHADOW_BACKLOG": "3",
            "TPU_RAG_SHADOW_BURST_WINDOW_S": "60",
            "TPU_RAG_SLO_QUALITY_OBJECTIVE": "0.9",
            "TPU_RAG_SLO_QUALITY_LOGIT_ERR": "0.3",
        })
        assert cfg.shadow == ShadowConfig(
            enabled=False, sample_rate=0.5, backlog=3, burst_window_s=60.0,
        )
        assert cfg.slo.quality_objective == 0.9
        assert cfg.slo.quality_logit_err == 0.3

    def test_defaults_on_at_five_percent(self):
        sh = AppConfig().shadow
        assert sh.enabled is True
        assert sh.sample_rate <= 0.05

    @pytest.mark.parametrize("env", [
        {"TPU_RAG_SHADOW": "2"},
        {"TPU_RAG_SHADOW_SAMPLE_RATE": "1.5"},
        {"TPU_RAG_SHADOW_BACKLOG": "0"},
        {"TPU_RAG_SHADOW_BURST_WINDOW_S": "0"},
    ])
    def test_invalid_values_raise(self, env):
        with pytest.raises(ValueError):
            ShadowConfig.from_env(env)

    def test_slo_quality_hostile_env_falls_back(self):
        cfg = AppConfig.from_env({
            "TPU_RAG_SLO_QUALITY_OBJECTIVE": "1.5",
            "TPU_RAG_SLO_QUALITY_LOGIT_ERR": "bogus",
        })
        assert cfg.slo.quality_objective == 0.99
        assert cfg.slo.quality_logit_err == 0.15

    def test_default_specs_include_the_quality_slo(self):
        specs = {s.name: s for s in obs_slo.default_specs()}
        q = specs["quality_p99_logit_err"]
        assert q.metric == "rag_quality_logit_err"
        assert q.kind == "latency"
        assert q.objective == 0.99 and q.threshold_s == 0.15


# ---------------------------------------------------------------------------
# smoke (make shadow-smoke)
# ---------------------------------------------------------------------------
class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode(
            "utf-8", "replace"
        )


def _drain_shadow(svc_or_aud):
    aud = getattr(svc_or_aud, "shadow", svc_or_aud)
    assert aud.drain(timeout=60.0), "shadow audits did not finish"
    return aud


class TestShadowSmoke:
    """`make shadow-smoke`: forced-sample shadow audits on the tiny
    config — byte-identity traffic audits clean, forced-warm audits
    within the pinned tolerance with the right attribution, and a
    divergence burst spools a bundle flightview round-trips."""

    def test_spec_on_greedy_audits_clean_with_attribution(self, tiny):
        """Greedy paged-speculation traffic through the continuous
        scheduler audits at divergence rate 0.0 — the spec byte-identity
        contract observed on 'live' traffic — attributed to spec_verify
        (non-vacuously: the request really drafted)."""
        cfg, params = tiny
        oneshot = _oneshot(cfg, params)
        aud = _auditor(oneshot.score_exact)
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64,
                kv_paged=True, kv_block_size=16,
                spec_paged=True, spec_paged_tokens=4,
            ),
            dtypes=FP32,
        )
        sched = ContinuousScheduler(eng)
        try:
            prompts = [
                [5, 7, 5, 7, 5, 7, 5, 7, 5, 7],
                [11, 11, 11, 11, 11, 11, 11, 11],
            ]
            for p in prompts:
                info = {}
                out = sched.submit(p, max_new_tokens=10, timeout=120,
                                   info=info)
                assert out
                aud.observe(
                    out, approx=tuple(info.get("approx", ())),
                    request_id=info.get("request_id"),
                    prompt_ids=p, force=True,
                )
            _drain_shadow(aud)
            st = aud.stats()
            assert st["audits_clean"] == 2.0
            assert st["audits_diverged"] == 0.0
            assert st["divergence_rate"] == 0.0
            assert st.get("attr_spec_verify_clean", 0.0) >= 1.0, (
                "no audit carried the spec_verify fingerprint — the "
                "clean rate above is vacuous"
            )
            assert eng.stats.spec_accepted_tokens > 0
        finally:
            sched.shutdown()
            aud.shutdown()

    def test_exact_chain_reuse_audits_clean(self, tiny):
        """Exact-chain prefix-reuse traffic (memo re-serve included)
        audits at divergence rate 0.0 with prefix_reuse attributed."""
        cfg, params = tiny
        eng = _oneshot(cfg, params, prefix_cache=PC)
        aud = _auditor(eng.score_exact)
        rng = np.random.default_rng(9)
        segments = _segments(cfg, rng, "smoke")
        suffix = list(map(int, rng.integers(3, 120, 6)))
        prompt = [t for _, seg in segments for t in seg] + suffix
        try:
            for _ in range(2):  # build, then memo re-serve
                cp = eng.prefix_cache.prefix_for(segments)
                out = eng.generate_prefixed(suffix, cp)
                assert out
                aud.observe(out, approx=cp.approx, prompt_ids=prompt,
                            force=True)
            _drain_shadow(aud)
            st = aud.stats()
            assert st["audits_clean"] == 2.0 and st["audits_diverged"] == 0.0
            assert st.get("attr_prefix_reuse_clean", 0.0) >= 1.0
        finally:
            aud.shutdown()

    def test_forced_warm_audits_within_pinned_tolerance(self, tiny):
        """FORCED warm-tier serving: every audit measures within the
        pinned 0.15 logit tolerance (clean or diverged — the minimal
        explaining perturbation can never exceed the warm drift bound)
        and the audit carries the warm_tier attribution."""
        cfg, params = tiny
        tiering = KVTieringConfig(
            enabled=True, warm_below=1e9, cold_below=0.01,
            half_life_s=3600.0, retier_interval_s=3600.0,
        )
        eng = _oneshot(cfg, params, prefix_cache=PC, kv_tiering=tiering)
        aud = _auditor(eng.score_exact)
        cache = eng.prefix_cache
        rng = np.random.default_rng(13)
        try:
            audited = 0
            for tag in ("w0", "w1", "w2"):
                segments = _segments(cfg, rng, tag)
                suffix = list(map(int, rng.integers(3, 120, 6)))
                prompt = [t for _, seg in segments for t in seg] + suffix
                cache.prefix_for(segments)
                assert cache.force_demote("warm") == 2
                cache._assembled.clear()
                cache.assembled_bytes = 0
                cp = cache.prefix_for(segments)
                assert "warm_tier" in cp.approx
                out = eng.generate_prefixed(suffix, cp)
                if not out:
                    continue
                aud.observe(out, approx=cp.approx, prompt_ids=prompt,
                            force=True)
                audited += 1
            assert audited > 0
            _drain_shadow(aud)
            st = aud.stats()
            judged = st["audits_clean"] + st["audits_diverged"]
            assert judged == audited and st["audits_failed"] == 0
            # attribution names warm_tier on every judged audit
            warm = (st.get("attr_warm_tier_clean", 0.0)
                    + st.get("attr_warm_tier_diverged", 0.0))
            assert warm == judged
            # whatever diverged did so WITHIN the pinned tolerance: the
            # minimal explaining perturbation is bounded by the warm
            # tier's 0.15 per-logit drift contract
            rep = obs_shadow.render_report(aud.state())
            assert rep["logit_err"]["max"] <= 0.15 + 1e-6
        finally:
            aud.shutdown()

    def test_divergence_burst_bundle_and_flightview_round_trip(
        self, tiny, tmp_path, monkeypatch
    ):
        """A forced divergence burst spools a quality_divergence incident
        bundle, and flightview --quality rebuilds EXACTLY the report
        GET /debug/quality serves, from the bundle file alone."""
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        cfg, params = tiny
        app_cfg = AppConfig(
            model=cfg,
            flight=FlightConfig(
                spool_dir=str(tmp_path / "spool"), cooldown_s=0.0,
                debug_endpoints=True,
            ),
            shadow=ShadowConfig(sample_rate=1.0, burst_window_s=300.0),
            system_message="ctx",
        )
        engine = _oneshot(cfg, params)
        svc = RagService(
            app_cfg, engine, ByteTokenizer(), None, ByteTokenizer(), None,
        )
        svc.ready = True
        try:
            flight.recorder().clear()
            prompt = [cfg.bos_token_id, 5, 9, 12, 7, 7, 9]
            good = engine.generate([prompt])[0]
            bad = list(good)
            bad[1] = (bad[1] + 1) % cfg.vocab_size
            for _ in range(2):  # the SECOND diverged audit is the burst
                svc.shadow.observe(bad, approx=("warm_tier",),
                                   prompt_ids=prompt, force=True)
                _drain_shadow(svc)
            client = create_app(svc).test_client()
            # the burst spooled a quality_divergence bundle
            incidents = client.get("/debug/incidents").get_json()["incidents"]
            triggers = [i["trigger"] for i in incidents]
            assert "quality_divergence" in triggers
            bid = next(
                i["id"] for i in incidents
                if i["trigger"] == "quality_divergence"
            )
            bundle = client.get(f"/debug/incidents?id={bid}").get_json()
            # the journal in the bundle carries the shadow_audit facts
            types = [e["type"] for e in bundle["journal"]]
            assert types.count("shadow_audit") == 2
            assert types.count("quality_divergence") == 2
            # live report == offline report, through one renderer
            live = client.get("/debug/quality").get_json()
            assert live["enabled"] is True
            assert live["report"]["audits"]["diverged"] == 2
            assert live["report"]["attribution"]["warm_tier"]["diverged"] == 2
            bpath = tmp_path / "bundle.json"
            bpath.write_text(json.dumps(bundle))
            offline = flightview.build_quality_report(
                flightview.load_events(bundle)
            )
            assert offline == live["report"]
            # the CLI renders both forms standalone
            assert flightview.main([str(bpath), "--quality", "--json"]) == 0
            assert flightview.main([str(bpath), "--quality"]) == 0
            # and the divergences moved the metric families
            snap = svc.metrics.snapshot()
            assert snap.get("rag_quality_divergence_rate") == 1.0
        finally:
            svc.shutdown()

    def test_debug_quality_contract_and_served_audit(
        self, tiny, tmp_path, monkeypatch
    ):
        """403 unless armed; armed, a real /query rides the full serving
        path, is audited clean, and the report says so."""
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        monkeypatch.delenv("TPU_RAG_DEBUG", raising=False)
        cfg, params = tiny
        from rag_llm_k8s_tpu.core.config import EncoderConfig
        from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
        from rag_llm_k8s_tpu.index.store import VectorStore
        from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params

        enc_cfg = EncoderConfig.tiny(vocab_size=300)
        app_cfg = AppConfig(
            model=cfg, encoder=enc_cfg,
            flight=FlightConfig(spool_dir=str(tmp_path / "spool")),
            shadow=ShadowConfig(sample_rate=1.0),
            system_message="Use the context.",
        )
        engine = _oneshot(cfg, params)
        encoder = EncoderRunner(
            enc_cfg, init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
            dtypes=FP32, length_buckets=(32, 64), max_batch=4,
        )
        store = VectorStore(dim=enc_cfg.hidden_size)
        svc = RagService(
            app_cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store,
        )
        svc.ready = True
        try:
            texts = ["alpha beta gamma", "delta epsilon zeta"]
            vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
            store.add(list(vecs), [
                {"filename": "f", "chunk_id": i, "text": t}
                for i, t in enumerate(texts)
            ])
            client = create_app(svc).test_client()
            assert client.get("/debug/quality").status_code == 403
            r = client.post("/query", json={"prompt": "alpha"})
            assert r.status_code == 200
            _drain_shadow(svc)
            monkeypatch.setenv("TPU_RAG_DEBUG", "1")
            app_cfg2 = dataclasses.replace(
                app_cfg,
                flight=dataclasses.replace(
                    app_cfg.flight, debug_endpoints=True
                ),
            )
            svc.config = app_cfg2
            client = create_app(svc).test_client()
            rep = client.get("/debug/quality").get_json()
            assert rep["enabled"] is True
            assert rep["sampling"]["seen"] >= 1
            assert rep["report"]["audits"]["diverged"] == 0
            assert rep["report"]["audits"]["failed"] == 0
            judged = (rep["report"]["audits"]["clean"]
                      + rep["report"]["audits"]["skipped"])
            assert judged >= 1
        finally:
            svc.shutdown()
