"""Disaggregated prefill/decode pools + prefix-affinity routing (ISSUE 20).

The load-bearing contract is the hand-off pin: a request admitted on a
prefill-role engine, exported as a migration packet, and imported by a
decode-role engine produces the BYTE-IDENTICAL stream a unified engine
produces — greedy and seeded (the packet carries the row's unfolded rng
key, kv frontier, and last token, so every (seed, position)-keyed draw
lands on the same values), at tp=1 and tp=2 (the arena layout is
identical across roles, so migration is block-table surgery plus one
device copy) — and neither engine leaks a block. Around it: the router's
affinity scoring actually concentrating repeat chunk compositions
(non-vacuous hit rate), health gating and unified fallback, session
stickiness, and the offline pool-sizing arithmetic
(``policy.pool_split`` / ``simulator.pool_plan``).

``TestSmoke`` is the ``make disagg-smoke`` lane (wired into ``make ci``);
the tp=2 class rides the conftest-forced 8-virtual-device CPU platform;
the mid-migration chaos reset rides ``make chaos`` in
tests/test_resilience.py (fault site ``migrate``).
"""

import dataclasses
import importlib.util
import os
import threading

import jax
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    MeshConfig,
    RouterConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.core.mesh import make_mesh
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.server.router import NoReplicaAvailable, Replica, Router

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
SEEDED = SamplingConfig(do_sample=True, temperature=0.8, top_p=0.9,
                        max_new_tokens=8)
PAGED = EngineConfig(
    prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64,
    kv_paged=True, kv_block_size=16,
)
PROMPTS = [[5, 6, 7, 8, 9, 10, 11], [12, 13, 14], [3] * 20, [9] * 25]


def _load_sim(name):
    here = os.path.join(os.path.dirname(__file__), "..",
                        "rag_llm_k8s_tpu", "sim", name + ".py")
    spec = importlib.util.spec_from_file_location("_rt_" + name,
                                                  os.path.normpath(here))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params


def _pair(cfg, params, sampling, **eng_kw):
    """A routed prefill-role + decode-role scheduler pair."""
    pre = ContinuousScheduler(
        ContinuousEngine(
            cfg, params, sampling=sampling,
            engine_config=dataclasses.replace(PAGED, pool_role="prefill"),
            **eng_kw,
        ),
        retry_backoff_s=0.0,
    )
    dec = ContinuousScheduler(
        ContinuousEngine(
            cfg, params, sampling=sampling,
            engine_config=dataclasses.replace(PAGED, pool_role="decode"),
            **eng_kw,
        ),
        retry_backoff_s=0.0,
    )
    return pre, dec


def _unified_streams(cfg, params, sampling, seeds, **eng_kw):
    uni = ContinuousScheduler(
        ContinuousEngine(cfg, params, sampling=sampling,
                         engine_config=PAGED, **eng_kw),
        retry_backoff_s=0.0,
    )
    try:
        return [uni.submit(p, seed=s) for p, s in zip(PROMPTS, seeds)]
    finally:
        uni.shutdown()


def _assert_no_leaks(*scheds):
    for sc in scheds:
        assert sc.engine.kv_pool.blocks_in_use() == 0, (
            f"leaked blocks on {sc.engine.pool_role} engine"
        )


# ---------------------------------------------------------------------------
# the disagg-smoke lane (make disagg-smoke / make ci)
# ---------------------------------------------------------------------------
class TestSmoke:
    def test_greedy_disagg_stream_is_byte_identical(self, setup):
        cfg, params = setup
        base = _unified_streams(cfg, params, GREEDY, [None] * len(PROMPTS))
        pre, dec = _pair(cfg, params, GREEDY)
        router = Router([Replica("prefill-0", pre), Replica("decode-0", dec)])
        try:
            got = [router.submit(p) for p in PROMPTS]
            assert got == base
            _assert_no_leaks(pre, dec)
        finally:
            pre.shutdown()
            dec.shutdown()

    def test_seeded_disagg_stream_is_byte_identical(self, setup):
        """The hard half of the pin: sampled draws are (seed, position)
        keyed, and the packet carries the UNFOLDED row key + kv frontier,
        so the decode engine's draws continue the prefill engine's
        sequence exactly."""
        cfg, params = setup
        seeds = [100 + i for i in range(len(PROMPTS))]
        base = _unified_streams(cfg, params, SEEDED, seeds)
        pre, dec = _pair(cfg, params, SEEDED)
        router = Router([Replica("prefill-0", pre), Replica("decode-0", dec)])
        try:
            got = [router.submit(p, seed=s) for p, s in zip(PROMPTS, seeds)]
            assert got == base
            _assert_no_leaks(pre, dec)
        finally:
            pre.shutdown()
            dec.shutdown()

    def test_migration_events_journal_the_handoff(self, setup):
        """Every routed hand-off journals route_decision +
        migrate_begin/migrate_done with matching block counts — the
        events ``flightview --router`` aggregates."""
        cfg, params = setup
        pre, dec = _pair(cfg, params, GREEDY)
        router = Router([Replica("p0", pre), Replica("d0", dec)])
        rec = flight.recorder()
        before = len(rec.snapshot())
        try:
            router.submit([4, 5, 6, 7], chunk_keys=[("doc", 1)])
        finally:
            pre.shutdown()
            dec.shutdown()
        evs = rec.snapshot()[before:]
        types = [e["type"] for e in evs]
        assert "route_decision" in types
        rd = next(e for e in evs if e["type"] == "route_decision")
        assert rd["mode"] in ("disagg", "unified")
        if rd["mode"] == "disagg":
            beg = next(e for e in evs if e["type"] == "migrate_begin")
            done = next(e for e in evs if e["type"] == "migrate_done")
            assert beg["rid"] == done["rid"] == rd["rid"]
            assert beg["blocks"] == done["blocks"] > 0

    def test_affinity_routing_is_non_vacuous(self):
        """Two stub prefill replicas, a repeating chunk composition: after
        the first decision the router must keep routing the composition
        to the SAME replica with affinity > 0 — chunk reuse becomes a
        fleet property only if routing concentrates compositions."""
        a, b = _StubReplica("p-a"), _StubReplica("p-b")
        router = Router([a, b], RouterConfig(load_weight=0.0))
        keys = [("doc", 7), ("doc", 8)]
        first, _, aff0 = router.select("prefill", chunk_keys=keys)
        assert aff0 == 0.0  # nothing hot yet
        hits = 0
        for _ in range(6):
            r, _, aff = router.select("prefill", chunk_keys=keys)
            assert r.name == first.name
            hits += aff > 0.0
        assert hits == 6
        # a disjoint composition is NOT forced onto the hot replica once
        # load matters: with equal (stub) load it may land either side,
        # but its affinity score starts at zero
        _, _, aff_new = router.select("prefill", chunk_keys=[("doc", 99)])
        assert aff_new == 0.0

    def test_pool_split_sizes_both_tiers(self):
        policy = _load_sim("policy")
        plan = policy.pool_split(30.0, 120.0, span_s=100.0,
                                 target_util=0.6, min_each=1)
        assert plan["prefill"] == 1 and plan["decode"] == 2
        assert 0.0 < plan["prefill_util"] <= 1.0
        assert 0.0 < plan["decode_util"] <= 1.0
        # tightening the target grows both tiers, never shrinks them
        tight = policy.pool_split(30.0, 120.0, span_s=100.0,
                                  target_util=0.2)
        assert tight["prefill"] >= plan["prefill"]
        assert tight["decode"] >= plan["decode"]

    def test_pool_plan_answers_from_a_simulated_trace(self):
        """The offline sizing loop: generate a trace, simulate it, read
        how many prefill vs decode replicas the load needs."""
        sim = _load_sim("simulator")
        tg = _load_sim("tracegen")
        res = sim.simulate(tg.generate(24, seed=3), max_batch_size=8)
        plan = res["pool_plan"]
        assert plan["prefill"] >= 1 and plan["decode"] >= 1
        assert plan["prefill_s"] > 0 and plan["decode_s"] > 0
        # re-planning the same journal at a tighter target only grows
        tight = sim.pool_plan(res["journal"], target_util=0.05)
        assert tight["prefill"] >= plan["prefill"]
        assert tight["decode"] >= plan["decode"]


# ---------------------------------------------------------------------------
# router policy (stub replicas: no engines, no jax dispatch)
# ---------------------------------------------------------------------------
class _StubEngine:
    def __init__(self, role, free=4):
        self.pool_role = role
        self.B = 4
        self.kv_pool = None
        self._free = free

    def free_slots(self):
        return list(range(self._free))


class _StubScheduler:
    def __init__(self, role, free=4):
        self.engine = _StubEngine(role, free)
        self._stop = threading.Event()


class _StubBreaker:
    def __init__(self):
        self.open = False


def _StubReplica(name, role="prefill", free=4, breaker=None):
    return Replica(name, _StubScheduler(role, free), breaker=breaker)


class TestRouterPolicy:
    def test_unhealthy_replicas_take_no_traffic(self):
        brk = _StubBreaker()
        sick = _StubReplica("sick", breaker=brk)
        well = _StubReplica("well")
        router = Router([sick, well])
        brk.open = True
        for _ in range(4):
            r, _, _ = router.select("prefill")
            assert r.name == "well"
        brk.open = False  # breaker self-heals: replica is eligible again
        assert sick.healthy()

    def test_all_unhealthy_raises_no_replica(self):
        brk = _StubBreaker()
        brk.open = True
        router = Router([_StubReplica("only", breaker=brk)])
        with pytest.raises(NoReplicaAvailable):
            router.select("prefill")

    def test_stopped_scheduler_is_unhealthy(self):
        rep = _StubReplica("r0")
        assert rep.healthy()
        rep.scheduler._stop.set()
        assert not rep.healthy()

    def test_load_prefers_the_emptier_replica(self):
        full = _StubReplica("full", free=0)
        empty = _StubReplica("empty", free=4)
        router = Router([full, empty],
                        RouterConfig(affinity_weight=0.0, load_weight=1.0))
        r, _, _ = router.select("prefill")
        assert r.name == "empty"

    def test_session_sticks_within_ttl_and_expires_after(self):
        a, b = _StubReplica("a"), _StubReplica("b")
        router = Router([a, b], RouterConfig(session_ttl_s=0.2))
        r0, _, _ = router.select("prefill", session="conv-1")
        for _ in range(4):
            r, _, _ = router.select("prefill", session="conv-1")
            assert r.name == r0.name
        # expire: rewrite the stamp into the past instead of sleeping
        name, stamp = router._sessions["conv-1"]
        router._sessions["conv-1"] = (name, stamp - 1.0)
        router.select("prefill", session="conv-1")  # re-scores, re-pins
        _, fresh = router._sessions["conv-1"]
        assert fresh > stamp - 1.0

    def test_hot_chunk_registry_is_bounded(self):
        rep = _StubReplica("solo")
        router = Router([rep], RouterConfig(hot_chunks=8))
        for i in range(50):
            router.select("prefill", chunk_keys=[("doc", i)])
        assert len(router._hot["solo"]) <= 8

    def test_unified_fallback_when_no_decode_tier(self, setup):
        """A unified replica alone serves end to end through the router:
        no packet, mode=unified, stream matches a direct submit."""
        cfg, params = setup
        uni = ContinuousScheduler(
            ContinuousEngine(cfg, params, sampling=GREEDY,
                             engine_config=PAGED),
            retry_backoff_s=0.0,
        )
        router = Router([Replica("uni-0", uni)])
        try:
            got = router.submit(PROMPTS[0])
            base = uni.submit(PROMPTS[0])
            assert got == base
            _assert_no_leaks(uni)
        finally:
            uni.shutdown()

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError):
            Router([_StubReplica("x"), _StubReplica("x")])

    def test_stats_snapshot_shape(self):
        router = Router([_StubReplica("p0"),
                         _StubReplica("d0", role="decode")])
        router.select("prefill", chunk_keys=[("doc", 0)], session="s")
        st = router.stats()
        assert {r["name"] for r in st["replicas"]} == {"p0", "d0"}
        assert st["sessions"] == 1
        assert all(0.0 <= r["load"] <= 1.0 for r in st["replicas"])


# ---------------------------------------------------------------------------
# tp=2: migration is layout-preserving across the tp mesh axis
# ---------------------------------------------------------------------------
@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs >= 2 (virtual) devices for tp=2")
class TestDisaggTP2:
    @pytest.fixture(scope="class")
    def tp_setup(self):
        cfg = LlamaConfig.tiny()  # 4 q heads / 2 kv heads: tp=2 tiles
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        placed = shard_llama_params(params, ctx)
        return cfg, placed, ctx

    def test_tp2_disagg_greedy_byte_identical(self, tp_setup):
        """The packet's gather/scatter run under the arena's own
        shardings, so a head-sharded pool migrates without resharding —
        streams stay pinned to the tp=2 unified baseline."""
        cfg, placed, ctx = tp_setup
        base = _unified_streams(cfg, placed, GREEDY,
                                [None] * len(PROMPTS), mesh=ctx)
        pre, dec = _pair(cfg, placed, GREEDY, mesh=ctx)
        router = Router([Replica("tp-p0", pre), Replica("tp-d0", dec)])
        try:
            got = [router.submit(p) for p in PROMPTS]
            assert got == base
            _assert_no_leaks(pre, dec)
        finally:
            pre.shutdown()
            dec.shutdown()
