"""ISSUE 3 decision-layer tests: W3C traceparent propagation round-trips,
burn-rate math against hand-computed fixtures, the fast-burn/slow-burn
window split, the /slo endpoint, the bench regression gate, and bench.py's
budget-truncation contract."""

import json
import logging
import os
import re
import signal
import subprocess
import sys
import threading

import jax
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import logging as obs_logging
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.obs import regression
from rag_llm_k8s_tpu.obs import slo as obs_slo
from rag_llm_k8s_tpu.server.app import RagService, create_app

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FP32 = DTypePolicy.fp32()


class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode("utf-8", "replace")


# ---------------------------------------------------------------------------
# traceparent parse/emit
# ---------------------------------------------------------------------------

VALID_TP = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"


class TestTraceparent:
    def test_valid_round_trip(self):
        ctx = obs_logging.parse_traceparent(VALID_TP)
        assert ctx is not None
        assert ctx.trace_id == "4bf92f3577b34da6a3ce929d0e0e4736"
        assert ctx.span_id == "00f067aa0ba902b7"
        assert ctx.sampled is True
        assert (
            obs_logging.format_traceparent(ctx.trace_id, ctx.span_id, ctx.sampled)
            == VALID_TP
        )

    def test_unsampled_flag(self):
        ctx = obs_logging.parse_traceparent(VALID_TP[:-2] + "00")
        assert ctx is not None and ctx.sampled is False

    @pytest.mark.parametrize(
        "header",
        [
            None,
            "",
            "garbage",
            "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7",  # 3 fields
            "ff-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",  # ver ff
            "00-" + "0" * 32 + "-00f067aa0ba902b7-01",  # all-zero trace
            "00-4bf92f3577b34da6a3ce929d0e0e4736-" + "0" * 16 + "-01",  # zero span
            "00-4BF92F3577B34DA6A3CE929D0E0E4736-00f067aa0ba902b7-01",  # uppercase
            "00-4bf92f3577b34da6-00f067aa0ba902b7-01",  # short trace id
            "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01-xx",  # v00 extra
            "zz-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",  # bad version
        ],
    )
    def test_malformed_returns_none(self, header):
        assert obs_logging.parse_traceparent(header) is None

    def test_future_version_accepted_with_extra_fields(self):
        ctx = obs_logging.parse_traceparent(VALID_TP.replace("00-", "01-", 1) + "-extra")
        assert ctx is not None and ctx.trace_id.startswith("4bf9")

    def test_new_traceparent_parses(self):
        ctx = obs_logging.parse_traceparent(obs_logging.new_traceparent())
        assert ctx is not None and ctx.sampled


# ---------------------------------------------------------------------------
# burn-rate math on hand-computed fixtures (fake clock — hours in microseconds)
# ---------------------------------------------------------------------------


class TestSloConfig:
    """PR 10 satellite: the TPU_RAG_SLO_* knobs route through
    core/config.py with a SAFE parse — a malformed or out-of-range env
    value must retune to the default, never raise at scrape/eval time
    (an out-of-range objective previously survived the float() guard and
    blew up in SloSpec.__post_init__)."""

    def test_defaults(self):
        from rag_llm_k8s_tpu.core.config import SloConfig

        cfg = SloConfig.from_env({})
        assert cfg.availability_objective == 0.999
        assert cfg.request_p95_s == 2.0
        assert cfg.ttft_p95_s == 1.0

    def test_valid_overrides_apply(self):
        from rag_llm_k8s_tpu.core.config import SloConfig

        cfg = SloConfig.from_env({
            "TPU_RAG_SLO_REQUEST_P95_S": "3.5",
            "TPU_RAG_SLO_TTFT_P95_OBJECTIVE": "0.9",
        })
        assert cfg.request_p95_s == 3.5
        assert cfg.ttft_p95_objective == 0.9

    def test_malformed_values_fall_back(self):
        from rag_llm_k8s_tpu.core.config import SloConfig

        cfg = SloConfig.from_env({
            "TPU_RAG_SLO_REQUEST_P95_S": "two seconds",
            "TPU_RAG_SLO_AVAILABILITY_OBJECTIVE": "",
        })
        assert cfg.request_p95_s == 2.0
        assert cfg.availability_objective == 0.999

    def test_out_of_range_values_fall_back(self):
        # 1.5 parses as float but violates SloSpec's (0,1) objective
        # contract; 0/-1 thresholds violate "latency SLO needs threshold"
        from rag_llm_k8s_tpu.core.config import SloConfig

        cfg = SloConfig.from_env({
            "TPU_RAG_SLO_REQUEST_P95_OBJECTIVE": "1.5",
            "TPU_RAG_SLO_TTFT_P95_S": "0",
            "TPU_RAG_SLO_REQUEST_P95_S": "-1",
        })
        assert cfg.request_p95_objective == 0.95
        assert cfg.ttft_p95_s == 1.0
        assert cfg.request_p95_s == 2.0

    def test_default_specs_construct_from_hostile_env(self, monkeypatch):
        # end-to-end: a hostile environment still yields valid SloSpecs
        monkeypatch.setenv("TPU_RAG_SLO_REQUEST_P95_S", "bogus")
        monkeypatch.setenv("TPU_RAG_SLO_AVAILABILITY_OBJECTIVE", "7")
        specs = obs_slo.default_specs()
        by_name = {s.name: s for s in specs}
        assert by_name["request_p95"].threshold_s == 2.0
        assert by_name["availability"].objective == 0.999

    def test_app_config_threads_slo(self):
        cfg = AppConfig.from_env({"TPU_RAG_SLO_TTFT_P95_S": "0.75"})
        assert cfg.slo.ttft_p95_s == 0.75
        specs = obs_slo.default_specs(cfg.slo)
        assert {s.name: s for s in specs}["ttft_p95"].threshold_s == 0.75


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _availability_engine(objective=0.999):
    reg = obs_metrics.MetricsRegistry()
    fam = reg.labeled_counter("rag_http_requests_total", "test")
    clock = FakeClock()
    spec = obs_slo.SloSpec(
        "availability", "availability", "rag_http_requests_total",
        objective=objective,
    )
    eng = obs_slo.SloEngine(
        reg, specs=[spec], clock=clock, min_eval_interval_s=0.0,
        register_gauges=False,
    )
    return reg, fam, clock, eng


class TestBurnRateMath:
    def test_no_traffic_is_calm_and_compliant(self):
        _, _, _, eng = _availability_engine()
        (s,) = eng.evaluate(force=True)["slos"]
        assert s["burn_rate"] == {"5m": 0.0, "30m": 0.0, "1h": 0.0, "6h": 0.0}
        assert s["compliant"] and s["error_budget_remaining"] == 1.0
        assert not s["fast_burn"] and not s["slow_burn"]

    def test_hand_computed_windows(self):
        """6h of clean traffic, then 50 bad of 100 in the last minute.

        Sample ring (sample at t=i*1800 holds the i-th epoch's 1000 good;
        the burst lands at now = 11*1800 + 1801). budget = 0.001.
        Hand-computed window diffs (baseline = newest sample <= now - W):
          5m:  base t=19800 -> bad 50 / 100    -> burn 500.0
          30m: base t=19800 -> bad 50 / 100    -> burn 500.0
          1h:  base t=18000 -> bad 50 / 1100   -> burn ~45.45
          6h:  base t=0     -> bad 50 / 11100  -> burn ~4.50
        """
        _, fam, clock, eng = _availability_engine(objective=0.999)
        good = fam.labels(route="/generate", code="200")
        bad = fam.labels(route="/generate", code="500")
        for _ in range(12):  # every 30 min over 6h: 1000 good requests
            good.inc(1000)
            eng.sample()
            clock.advance(1800)
        good.inc(50)
        bad.inc(50)
        clock.advance(1)  # the burst lands "now", inside every window
        (s,) = eng.evaluate(force=True)["slos"]
        br = s["burn_rate"]
        assert br["5m"] == pytest.approx(500.0, rel=1e-3)
        assert br["30m"] == pytest.approx(500.0, rel=1e-3)
        assert br["1h"] == pytest.approx(50 / 1100 / 0.001, rel=1e-2)
        assert br["6h"] == pytest.approx(50 / 11100 / 0.001, rel=1e-2)
        # the acceptance shape: the FAST pair (5m and 1h both >= 14.4)
        # fires while the SLOW pair stays calm (6h ~4.5 < 6)
        assert s["fast_burn"] is True
        assert s["slow_burn"] is False
        # the 6h burst overspent the whole window budget (burn 4.5 > 1):
        # remaining floors at 0 and compliance over the long window is gone
        assert s["error_budget_remaining"] == 0.0
        assert s["compliant"] is False  # 6h bad-rate 0.45% > 0.1% objective

    def test_burn_clears_after_calm_period(self):
        _, fam, clock, eng = _availability_engine()
        good = fam.labels(route="/generate", code="200")
        bad = fam.labels(route="/generate", code="500")
        good.inc(50)
        bad.inc(50)
        eng.sample()
        clock.advance(1)
        (s,) = eng.evaluate(force=True)["slos"]
        assert s["fast_burn"]
        # 7h of clean traffic pushes the burst out of every window
        for _ in range(14):
            clock.advance(1800)
            good.inc(1000)
            eng.sample()
        clock.advance(1)
        (s,) = eng.evaluate(force=True)["slos"]
        assert not s["fast_burn"] and not s["slow_burn"]
        assert s["compliant"]

    def test_latency_sli_counts_threshold_buckets(self):
        """Latency good-event counting reads the SAME histogram /metrics
        exposes: observations <= threshold are good, others spend budget."""
        reg = obs_metrics.MetricsRegistry()
        h = reg.histogram(
            "rag_request_duration_seconds", buckets=(0.5, 2.0, 8.0)
        )
        clock = FakeClock()
        spec = obs_slo.SloSpec(
            "request_p95", "latency", "rag_request_duration_seconds",
            objective=0.95, threshold_s=2.0,
        )
        eng = obs_slo.SloEngine(
            reg, specs=[spec], clock=clock, min_eval_interval_s=0.0,
            register_gauges=False,
        )
        for _ in range(90):
            h.observe(0.3)  # good
        for _ in range(10):
            h.observe(5.0)  # bad: over the 2 s threshold
        clock.advance(1)
        (s,) = eng.evaluate(force=True)["slos"]
        # bad_frac = 10/100 = 0.1; budget = 0.05 -> burn 2.0 on every window
        assert s["burn_rate"]["5m"] == pytest.approx(2.0, rel=1e-6)
        assert s["threshold_bucket_s"] == 2.0
        assert not s["compliant"]

    def test_threshold_above_ladder_is_not_vacuous(self):
        """A threshold over the histogram's top bound clamps to the top
        bound — the +Inf overflow bucket must never count as 'good', or
        the SLO goes vacuously compliant at any latency."""
        reg = obs_metrics.MetricsRegistry()
        h = reg.histogram("rag_request_duration_seconds", buckets=(0.5, 2.0))
        clock = FakeClock()
        spec = obs_slo.SloSpec(
            "request_p95", "latency", "rag_request_duration_seconds",
            objective=0.95, threshold_s=100.0,  # above the 2.0 top bound
        )
        eng = obs_slo.SloEngine(
            reg, specs=[spec], clock=clock, min_eval_interval_s=0.0,
            register_gauges=False,
        )
        for _ in range(10):
            h.observe(50.0)  # lands in +Inf: slow no matter the threshold
        clock.advance(1)
        (s,) = eng.evaluate(force=True)["slos"]
        assert s["burn_rate"]["5m"] == pytest.approx(20.0)  # all bad
        assert not s["compliant"]
        assert s["threshold_bucket_s"] == 2.0  # the bound actually judged

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            obs_slo.SloSpec("x", "latency", "m", objective=0.95)  # no threshold
        with pytest.raises(ValueError):
            obs_slo.SloSpec("x", "availability", "m", objective=1.5)
        with pytest.raises(ValueError):
            obs_slo.SloSpec("x", "nope", "m", objective=0.9)

    def test_burn_gauges_exported(self):
        reg = obs_metrics.MetricsRegistry()
        fam = reg.labeled_counter("rag_http_requests_total", "test")
        clock = FakeClock()
        spec = obs_slo.SloSpec(
            "availability", "availability", "rag_http_requests_total",
            objective=0.9,
        )
        obs_slo.SloEngine(reg, specs=[spec], clock=clock, min_eval_interval_s=0.0)
        fam.labels(route="/q", code="500").inc(10)
        clock.advance(1)
        text = reg.render_prometheus()
        m = re.search(
            r'rag_slo_burn_rate\{slo="availability",window="5m"\} ([0-9.]+)', text
        )
        assert m, text[:2000]
        assert float(m.group(1)) == pytest.approx(10.0, rel=1e-6)  # all-bad / 0.1
        assert 'rag_slo_error_budget_remaining{slo="availability"} 0.0' in text
        assert 'rag_slo_fast_burn_active{slo="availability"}' in text


# ---------------------------------------------------------------------------
# HTTP: trace propagation + /slo + log correlation (one tiny shared service)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served():
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(model=llama_cfg, encoder=enc_cfg)
    engine = InferenceEngine(
        llama_cfg,
        init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32),
        sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
        engine_config=EngineConfig(prompt_buckets=(128, 512), max_batch_size=2,
                                   max_seq_len=640),
        dtypes=FP32,
    )
    encoder = EncoderRunner(
        enc_cfg,
        init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32,), max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size)
    svc = RagService(cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store)
    svc.ready = True
    vec = encoder.encode([ByteTokenizer().encode("tiny doc text")])[0]
    store.add([vec], [{"filename": "f", "chunk_id": 0, "text": "kernels tile queries"}])
    client = create_app(svc).test_client()
    r = client.post("/query", json={"prompt": "warm"})
    assert r.status_code == 200, r.get_json()
    return svc, client


class _JsonCapture(logging.Handler):
    """Captures records rendered through the production JsonLogFormatter."""

    def __init__(self):
        super().__init__()
        self.setFormatter(obs_logging.JsonLogFormatter())
        self.lines = []

    def emit(self, record):
        self.lines.append(self.format(record))


class TestTracePropagationHttp:
    def test_inbound_traceparent_round_trip(self, served):
        """The acceptance contract: one trace_id in x-trace-id, in the
        inline tree, and on every structured log line the request emitted."""
        _, client = served
        capture = _JsonCapture()
        root = logging.getLogger("rag_llm_k8s_tpu")
        old_level = root.level
        root.addHandler(capture)
        root.setLevel(logging.INFO)
        try:
            r = client.post(
                "/generate",
                json={"prompt": "what do kernels do?", "trace": True},
                headers={"traceparent": VALID_TP},
            )
        finally:
            root.removeHandler(capture)
            root.setLevel(old_level)
        assert r.status_code == 200, r.get_data()
        want = "4bf92f3577b34da6a3ce929d0e0e4736"
        assert r.headers["x-trace-id"] == want
        # the response traceparent names OUR span under the caller's trace
        ctx = obs_logging.parse_traceparent(r.headers["traceparent"])
        assert ctx is not None and ctx.trace_id == want
        assert ctx.span_id != "00f067aa0ba902b7"
        body = r.get_json()
        assert body["trace"]["trace_id"] == want
        assert body["trace"]["parent_span_id"] == "00f067aa0ba902b7"
        # every structured line emitted inside the request carries the id
        assert capture.lines, "no structured log lines captured"
        for line in capture.lines:
            rec = json.loads(line)
            assert rec["trace_id"] == want, rec
            assert rec["span_id"] == ctx.span_id
        served_lines = [
            json.loads(l) for l in capture.lines
            if json.loads(l)["logger"] == "rag_llm_k8s_tpu.access"
        ]
        assert served_lines and served_lines[-1]["status"] == 200
        assert served_lines[-1]["duration_ms"] > 0

    def test_malformed_traceparent_never_500s(self, served):
        _, client = served
        for bad in ("garbage", "00-zzz-yyy-01", "00-" + "0" * 32 + "-" + "1" * 16):
            r = client.post(
                "/generate", json={"prompt": "hi"}, headers={"traceparent": bad}
            )
            assert r.status_code == 200, (bad, r.get_data())
            tid = r.headers["x-trace-id"]
            assert re.fullmatch(r"[0-9a-f]{32}", tid), tid  # fresh trace

    def test_absent_header_generates_fresh_trace(self, served):
        _, client = served
        r1 = client.post("/query", json={"prompt": "a"})
        r2 = client.post("/query", json={"prompt": "b"})
        t1, t2 = r1.headers["x-trace-id"], r2.headers["x-trace-id"]
        assert re.fullmatch(r"[0-9a-f]{32}", t1)
        assert t1 != t2

    def test_query_alias_contract_identical(self, served):
        """BASELINE.json calls the endpoint /query; the README maps it to
        /generate. Same handler -> identical response contract, including
        the trace headers."""
        _, client = served
        rq = client.post("/query", json={"prompt": "alias?"})
        rg = client.post("/generate", json={"prompt": "alias?"})
        assert rq.status_code == rg.status_code == 200
        bq, bg = rq.get_json(), rg.get_json()
        assert set(bq) == set(bg)
        assert {"generated_text", "context", "timings"} <= set(bq)
        for r in (rq, rg):
            assert "x-trace-id" in r.headers and "traceparent" in r.headers

    def test_http_request_counter_by_route_and_code(self, served):
        svc, client = served
        client.post("/query", json={"prompt": "count me"})
        text = client.get("/metrics").get_data(as_text=True)
        m = re.search(
            r'tpu_rag_rag_http_requests_total\{code="200",route="/query"\} '
            r"([0-9.]+)",
            text,
        )
        # rag_-prefixed names render verbatim (no tpu_rag_ prefix)
        m = m or re.search(
            r'rag_http_requests_total\{code="200",route="/query"\} ([0-9.]+)', text
        )
        assert m, text[:1500]
        assert float(m.group(1)) >= 1


class TestSloEndpoint:
    def test_slo_report_reads_served_histograms(self, served):
        svc, client = served
        client.post("/query", json={"prompt": "traffic"})
        r = client.get("/slo?force=1")
        assert r.status_code == 200
        body = r.get_json()
        names = {s["name"] for s in body["slos"]}
        assert {"availability", "request_p95", "ttft_p95"} <= names
        req = next(s for s in body["slos"] if s["name"] == "request_p95")
        # the same histogram /metrics exposes fed the window: events counted
        assert req["window_events"]["6h"] >= 1
        assert req["threshold_s"] == 2.0
        assert set(req["burn_rate"]) == {"5m", "30m", "1h", "6h"}
        assert all(v >= 0 for v in req["burn_rate"].values())
        assert 0.0 <= req["error_budget_remaining"] <= 1.0
        avail = next(s for s in body["slos"] if s["name"] == "availability")
        assert avail["compliant"] is True  # every test request returned 200
        assert avail["burn_rate"]["6h"] == 0.0
        assert isinstance(body["page"], bool) and isinstance(body["ticket"], bool)

    def test_slo_gauges_share_the_scrape(self, served):
        _, client = served
        text = client.get("/metrics").get_data(as_text=True)
        assert "rag_slo_burn_rate{" in text
        assert "rag_slo_error_budget_remaining{" in text
        assert "rag_device_hbm_bytes_in_use{" in text  # per-device telemetry

    def test_synthetic_latency_flips_fast_burn_on_served_registry(self, served):
        """Acceptance: inject slow observations into the SAME histogram the
        server scrapes; the fast window burns while the slow one stays
        calm. A fresh SloEngine with a fake clock reads the service's own
        registry — proving /slo math and /metrics data share one source."""
        svc, _ = served
        clock = FakeClock()
        spec = obs_slo.SloSpec(
            "request_p95", "latency", "rag_request_duration_seconds",
            objective=0.95, threshold_s=2.0,
        )
        eng = obs_slo.SloEngine(
            svc.metrics, specs=[spec], clock=clock, min_eval_interval_s=0.0,
            register_gauges=False,
        )
        h = svc.metrics.histogram("rag_request_duration_seconds")
        # 6h of history: plenty of fast traffic (the served fixture's real
        # requests plus a synthetic steady stream)
        for _ in range(12):
            for _ in range(200):
                h.observe(0.05)
            eng.sample()
            clock.advance(1800)
        # the injection: 30 slow requests land in the last 5 minutes
        for _ in range(30):
            h.observe(30.0)
        for _ in range(5):
            h.observe(0.05)
        clock.advance(1)
        (s,) = eng.evaluate(force=True)["slos"]
        # 5m: 30/35 bad -> burn ~17 >= 14.4; 1h: 30/435 -> ~1.4 (calm)
        assert s["burn_rate"]["5m"] >= 14.4
        assert s["burn_rate"]["1h"] < 14.4
        assert s["burn_rate"]["6h"] < 6.0
        assert s["fast_burn"] is False  # both-windows rule: 1h is calm
        assert s["slow_burn"] is False
        # keep burning for an hour -> the 1h window joins and the PAGE fires
        for _ in range(2):
            for _ in range(300):
                h.observe(30.0)
            eng.sample()
            clock.advance(1800)
        for _ in range(50):
            h.observe(30.0)
        clock.advance(1)
        (s,) = eng.evaluate(force=True)["slos"]
        assert s["burn_rate"]["5m"] >= 14.4 and s["burn_rate"]["1h"] >= 14.4
        assert s["fast_burn"] is True
        assert s["burn_rate"]["6h"] < 6.0  # slow window still calm
        assert s["slow_burn"] is False


# ---------------------------------------------------------------------------
# bench regression gate
# ---------------------------------------------------------------------------


BASE_BENCH = {
    "metric": "llama_1b_decode_throughput",
    "value": 4000.0,
    "unit": "tokens/sec/chip",
    "vs_baseline": 1500.0,
    "query_p50_ms": 800.0,
    "query_p50_8b_ms": 1830.0,
    "query_qps_load": 4.5,
    "coalesce_tok_per_s": 1700.0,
    "query_stage_ms": {"generate": 770.0, "embed_retrieve": 6.0},
    "tunnel_fetch_ms": 100.0,
    "query_n": 20,
    "spec_8b_identical": True,
}


class TestRegressionGate:
    def test_self_comparison_is_clean(self):
        out = regression.compare(BASE_BENCH, BASE_BENCH)
        assert out["regression"] == [] and out["missing"] == []

    def test_latency_up_flags(self):
        cur = dict(BASE_BENCH, query_p50_ms=1200.0)  # +50% > 25% band
        out = regression.compare(cur, BASE_BENCH)
        assert [f.key for f in out["regression"]] == ["query_p50_ms"]

    def test_latency_down_is_improvement_not_regression(self):
        cur = dict(BASE_BENCH, query_p50_ms=400.0)
        out = regression.compare(cur, BASE_BENCH)
        assert out["regression"] == []
        assert any(f.key == "query_p50_ms" for f in out["improvement"])

    def test_throughput_down_flags_direction_aware(self):
        cur = dict(BASE_BENCH, coalesce_tok_per_s=1000.0, query_qps_load=2.0)
        keys = {f.key for f in regression.compare(cur, BASE_BENCH)["regression"]}
        assert keys == {"coalesce_tok_per_s", "query_qps_load"}

    def test_nested_stage_regression(self):
        cur = json.loads(json.dumps(BASE_BENCH))
        cur["query_stage_ms"]["generate"] = 2000.0
        keys = {f.key for f in regression.compare(cur, BASE_BENCH)["regression"]}
        assert keys == {"query_stage_ms.generate"}

    def test_within_tolerance_passes(self):
        cur = dict(BASE_BENCH, query_p50_ms=900.0)  # +12.5% < 25%
        assert regression.compare(cur, BASE_BENCH)["regression"] == []
        # but a tightened band catches it
        out = regression.compare(cur, BASE_BENCH, tolerance=0.10)
        assert [f.key for f in out["regression"]] == ["query_p50_ms"]

    def test_ignored_keys_never_flag(self):
        cur = dict(
            BASE_BENCH, tunnel_fetch_ms=900.0, query_n=3, spec_8b_identical=False
        )
        out = regression.compare(cur, BASE_BENCH)
        assert out["regression"] == []

    def test_missing_keys_reported_not_failed(self):
        cur = {k: v for k, v in BASE_BENCH.items() if k != "query_p50_ms"}
        out = regression.compare(cur, BASE_BENCH)
        assert out["regression"] == []
        assert [f.key for f in out["missing"]] == ["query_p50_ms"]

    def test_schema_check(self):
        assert regression.schema_check(BASE_BENCH) == []
        assert regression.schema_check({"note": "strings only"})
        assert regression.schema_check([1, 2])  # type: ignore[arg-type]

    def test_headline_value_is_gated(self):
        """'value' is the headline decode tok/s — a change that halves it
        must fail the gate (it is NOT a config echo)."""
        assert regression.classify("value") == "higher"
        cur = dict(BASE_BENCH, value=2000.0)
        keys = {f.key for f in regression.compare(cur, BASE_BENCH)["regression"]}
        assert "value" in keys

    def test_zero_overlap_is_detectable(self):
        """Disjoint schemas share nothing comparable — the CLI treats that
        as an error (rc 2), never a vacuous pass."""
        assert regression.comparable_overlap(
            {"alpha_ms": 1.0}, {"beta_ms": 2.0}
        ) == []
        assert "query_p50_ms" in regression.comparable_overlap(
            BASE_BENCH, BASE_BENCH
        )

    def test_load_json_unwraps_driver_envelope(self, tmp_path):
        """BENCH_r*.json artifacts wrap the bench line in {"parsed": ...};
        load_json unwraps it so any committed round can be the baseline."""
        p = tmp_path / "round.json"
        p.write_text(json.dumps({"n": 3, "rc": 0, "parsed": BASE_BENCH}))
        assert regression.load_json(str(p)) == BASE_BENCH
        # a null parsed (the rc-124 artifacts) stays a wrapper — the CLI's
        # zero-overlap guard then fails it loudly
        p.write_text(json.dumps({"n": 5, "rc": 124, "parsed": None}))
        assert regression.load_json(str(p))["rc"] == 124

    def test_classify_real_bench_keys(self):
        assert regression.classify("query_p50_load_adj_ms") == "lower"
        assert regression.classify("knn_ms_100k") == "lower"
        assert regression.classify("snapshot_save_s") == "lower"
        assert regression.classify("decode_int8_tok_per_s.64") == "higher"
        assert regression.classify("continuous_steps_per_s_sync16") == "higher"
        assert regression.classify("prefill_mfu_b8") == "higher"
        assert regression.classify("prefix_prefill_reduction") == "higher"
        assert regression.classify("query_p50_target_ms") == "ignore"
        assert regression.classify("query_8b_spec_verify_steps") == "ignore"
        assert regression.classify("query_load_quant") == "ignore"

    def test_fidelity_band_is_absolute(self):
        """ISSUE 17 (docs/REPLAY.md): the replay simulator's fidelity
        ratios are judged against the absolute 1.0 ± tolerance band —
        drifting HIGH is exactly as wrong as drifting low, so the _per_s
        higher-is-better rule must not swallow steps_per_s_ratio."""
        assert regression.classify("replay_fidelity.steps_per_s_ratio") == "band"
        assert regression.classify("replay_fidelity.cost_ratio") == "band"
        base = dict(BASE_BENCH, replay_fidelity={"steps_per_s_ratio": 1.0})
        for r in (0.8, 1.0, 1.2):  # inside the band: clean
            cur = dict(BASE_BENCH, replay_fidelity={"steps_per_s_ratio": r})
            assert regression.compare(cur, base)["regression"] == []
        for r in (0.7, 1.4):  # outside: flagged in BOTH directions
            cur = dict(BASE_BENCH, replay_fidelity={"steps_per_s_ratio": r})
            keys = {f.key for f in regression.compare(cur, base)["regression"]}
            assert keys == {"replay_fidelity.steps_per_s_ratio"}, r
        # the band is absolute: an out-of-band baseline does not grant an
        # out-of-band current a self-comparison pass
        drifted = dict(BASE_BENCH, replay_fidelity={"steps_per_s_ratio": 1.4})
        assert regression.compare(drifted, drifted)["regression"]


class TestBenchGateCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "bench_gate.py"), *args],
            capture_output=True, text=True, timeout=120,
        )

    def test_baseline_vs_itself_exits_zero(self):
        p = self._run()
        assert p.returncode == 0, p.stderr

    def test_injected_regression_exits_nonzero(self):
        p = self._run(
            "--current",
            os.path.join(REPO, "tests", "fixtures", "bench_regression.json"),
        )
        assert p.returncode == 1, (p.stdout, p.stderr)
        assert "REGRESSION" in p.stderr

    def test_dry_run_schema_check(self):
        p = self._run("--dry-run")
        assert p.returncode == 0, p.stderr
        assert "dry-run OK" in p.stdout

    def test_unreadable_input_exits_two(self):
        p = self._run("--current", "/nonexistent/bench.json")
        assert p.returncode == 2

    def test_disjoint_schemas_exit_two_not_ok(self, tmp_path):
        """A current document sharing NO comparable keys with the baseline
        must error (the gate would otherwise judge nothing and 'pass')."""
        p = tmp_path / "other.json"
        p.write_text(json.dumps({"totally_different_ms": 1.0}))
        r = self._run("--current", str(p))
        assert r.returncode == 2, (r.stdout, r.stderr)
        assert "no comparable metrics" in r.stderr


# ---------------------------------------------------------------------------
# bench budget truncation (satellite: BENCH_r05 rc-124 data loss)
# ---------------------------------------------------------------------------


class TestBenchBudget:
    def test_truncated_run_emits_valid_partial_json(self, monkeypatch, capsys):
        import bench

        def fake_legs(line):
            def ok():
                line["query_p50_ms"] = 123.0

            def boom():
                raise bench.BenchBudgetExceeded("SIGTERM")

            return [("fast", ok), ("slow", boom), ("never", lambda: None)]

        monkeypatch.setattr(bench, "bench_legs", fake_legs)
        old_term = signal.getsignal(signal.SIGTERM)
        old_alrm = signal.getsignal(signal.SIGALRM)
        try:
            bench.main()
        finally:
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGALRM, old_alrm)
            signal.alarm(0)
        out = capsys.readouterr().out.strip().splitlines()[-1]
        doc = json.loads(out)  # ALWAYS valid JSON — the contract
        assert doc["truncated"] is True
        assert doc["query_p50_ms"] == 123.0  # completed legs' data survives
        assert doc["legs_completed"] == ["fast"]
        assert doc["legs_skipped"] == ["slow", "never"]

    def test_untruncated_run_has_no_marker(self, monkeypatch, capsys):
        import bench

        monkeypatch.setattr(
            bench, "bench_legs",
            lambda line: [("only", lambda: line.update({"x_ms": 1.0}))],
        )
        old_term = signal.getsignal(signal.SIGTERM)
        old_alrm = signal.getsignal(signal.SIGALRM)
        try:
            bench.main()
        finally:
            # main() leaves TERM/ALRM ignored (emit protection) — restore
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGALRM, old_alrm)
            signal.alarm(0)
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert "truncated" not in doc and doc["x_ms"] == 1.0

    def test_budget_alarm_delivers_between_bytecodes(self):
        """TPU_RAG_BENCH_BUDGET_S arms SIGALRM -> BenchBudgetExceeded in the
        main thread; a compute loop is interrupted and the partial-emit
        path runs. Subprocess: the alarm must not leak into pytest."""
        code = (
            "import os, time, json\n"
            "os.environ['TPU_RAG_BENCH_BUDGET_S'] = '1'\n"
            "import bench\n"
            "assert bench.install_budget_guard() == '1'\n"
            "try:\n"
            "    t0 = time.monotonic()\n"
            "    while time.monotonic() - t0 < 30:\n"
            "        sum(range(1000))\n"
            "    print(json.dumps({'interrupted': False}))\n"
            "except bench.BenchBudgetExceeded as e:\n"
            "    print(json.dumps({'interrupted': True, 'sig': str(e)}))\n"
        )
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=60, cwd=REPO,
        )
        assert p.returncode == 0, p.stderr
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert doc == {"interrupted": True, "sig": "SIGALRM"}

    def test_guard_is_noop_off_main_thread(self):
        import bench

        result = {}

        def run():
            result["guard"] = bench.install_budget_guard()

        t = threading.Thread(target=run)
        t.start()
        t.join()
        assert result["guard"] is None


# ---------------------------------------------------------------------------
# per-device telemetry units
# ---------------------------------------------------------------------------


class TestDeviceTelemetry:
    def test_cpu_devices_report_zero_gracefully(self):
        from rag_llm_k8s_tpu.obs import devices as obs_devices

        reg = obs_metrics.MetricsRegistry()
        n = obs_devices.register_device_gauges(reg, lambda: {0: 4096})
        assert n >= 1  # the CPU test platform still enumerates devices
        text = reg.render_prometheus()
        assert re.search(r'rag_device_hbm_bytes_in_use\{device="0"\} 0\.0', text)
        assert re.search(r'rag_device_hbm_bytes_limit\{device="0"\} 0\.0', text)
        # the prefix-cache attribution flows through per device
        assert re.search(
            r'rag_prefix_cache_device_bytes\{device="0"\} 4096\.0', text
        )

    def test_prefix_cache_bytes_by_device_empty(self):
        from rag_llm_k8s_tpu.core.config import PrefixCacheConfig
        from rag_llm_k8s_tpu.engine.prefix_cache import PrefixCache

        cache = PrefixCache(PrefixCacheConfig(enabled=True), engine=None)
        assert cache.bytes_by_device() == {}
