"""Prompt-lookup speculative decoding (EngineConfig.speculative): the greedy
batch-1 fast path must be token-IDENTICAL to the vanilla loop on every input
— acceptance only ever keeps tokens equal to the model's own greedy argmax —
while the all-accept regime provably emits k+1 tokens per verify forward."""

import dataclasses

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=12)
ENG = EngineConfig(prompt_buckets=(32, 64), max_batch_size=2, max_seq_len=128)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    vanilla = InferenceEngine(cfg, params, sampling=GREEDY, engine_config=ENG, dtypes=FP32)
    spec = InferenceEngine(
        cfg, params, sampling=GREEDY,
        engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
        dtypes=FP32,
    )
    return cfg, params, vanilla, spec


PROMPTS = [
    [3, 17, 42, 7, 99],  # no obvious repeats
    [5, 9, 2, 5, 9, 2, 5, 9, 2],  # trailing n-gram repeats in-prompt
    [11] * 20,  # degenerate repeat
    [3, 17, 42, 7, 99, 3, 17, 42],  # repeat ending mid-span
    [8],  # shorter than the n-gram itself
    list(range(3, 30)),  # long distinct prompt
]


class TestExactness:
    def test_matches_vanilla_greedy(self, setup):
        _, _, vanilla, spec = setup
        for p in PROMPTS:
            want = vanilla.generate([p])[0]
            got = spec.generate([p])[0]
            assert got == want, p

    def test_budget_edges(self, setup):
        _, _, vanilla, spec = setup
        p = [5, 9, 2, 5, 9, 2, 5, 9, 2]
        for mn in (1, 2, 7, 8, 9, 20):  # around k+1 = 8 emission chunks
            assert spec.generate([p], max_new_tokens=mn)[0] == \
                vanilla.generate([p], max_new_tokens=mn)[0], mn

    def test_zero_slack_cache_shape_stays_exact(self, setup):
        """S + max_new an exact 128-multiple (the round-4 bench's own
        shapes): without k slack slots, the last verify forwards' KV writes
        would clamp-shift onto valid accepted KV and diverge near the
        budget. Repeat-heavy prompt drives acceptance right to the edge."""
        _, _, vanilla, spec = setup
        p = [5, 9, 2] * 6  # repeats: long accepted spans reach the budget
        for mn in (96, 95):  # 32 + 96 = 128 exactly
            want = vanilla.generate([p], max_new_tokens=mn)[0]
            got = spec.generate([p], max_new_tokens=mn)[0]
            assert got == want, mn

    def test_eos_mid_span(self, setup):
        """EOS inside an accepted span must truncate exactly where vanilla
        does. The EOS id is taken from the vanilla stream so it fires."""
        cfg, params, vanilla, _ = setup
        p = [5, 9, 2, 5, 9, 2, 5, 9, 2]
        stream = vanilla.generate([p])[0]
        assert len(stream) >= 4
        cfg_eos = dataclasses.replace(cfg, eos_token_ids=(stream[3],))
        v2 = InferenceEngine(cfg_eos, params, sampling=GREEDY, engine_config=ENG, dtypes=FP32)
        s2 = InferenceEngine(
            cfg_eos, params, sampling=GREEDY,
            engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        want = v2.generate([p])[0]
        got = s2.generate([p])[0]
        assert got == want
        assert len(want) == 3  # truncated at the injected EOS

    def test_fallbacks_to_vanilla(self, setup):
        cfg, params, vanilla, spec = setup
        # batch > 1: vanilla path (still correct)
        two = spec.generate([[3, 17, 42], [5, 9, 2]])
        assert two == vanilla.generate([[3, 17, 42], [5, 9, 2]])
        assert (2, 32, GREEDY.max_new_tokens, None) in spec._compiled
        # sampling at batch 1 now TAKES the spec path (rejection-sampling
        # verification preserves the distribution — TestSampledDistribution)
        sam = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=True, max_new_tokens=6, seed=3),
            engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        out = sam.generate([[3, 17, 42]], seed=7)[0]
        assert any(k[3] == "spec" for k in sam._compiled)
        assert len(out) <= 6 and all(isinstance(t, int) for t in out)
        assert sam.stats.spec_verify_steps >= 1


class TestAcceptance:
    def test_all_accept_regime_emits_k_plus_1_per_step(self, setup):
        """Zero params make the model a constant emitter (uniform logits →
        argmax 0 forever); a prompt seeded with 0-runs makes every proposal
        correct, so max_new tokens must arrive in ceil((max_new-1)/(k+1))
        verify steps — the machinery's best case, measured not assumed."""
        cfg, _, _, _ = setup
        params0 = jax.tree.map(
            lambda x: np.zeros_like(x), init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        )
        ec = dataclasses.replace(ENG, speculative="prompt_lookup")
        spec = InferenceEngine(cfg, params0, sampling=GREEDY, engine_config=ec, dtypes=FP32)
        p = [1] + [0] * 8
        out = spec.generate([p], max_new_tokens=12)[0]
        assert out == [0] * 12
        k1 = ec.spec_tokens + 1
        want_steps = -(-(12 - 1) // k1)
        assert spec.stats.spec_verify_steps == want_steps

    def test_verify_steps_never_exceed_tokens(self, setup):
        _, _, _, spec = setup
        before = spec.stats.spec_verify_steps
        out = spec.generate([[3, 17, 42, 7, 99]], max_new_tokens=9)[0]
        steps = spec.stats.spec_verify_steps - before
        assert 1 <= steps <= len(out)


class TestSpecWithQuantization:
    """Speculation composes with int8 weights and the int8 KV cache: the
    verify forward is the q8 chunked-prefill path, acceptance compares the
    QUANTIZED model's own greedy choices — exactness is vs the quantized
    vanilla loop (the same numerics)."""

    def test_exact_vs_vanilla_int8_w_and_kv(self):
        cfg = LlamaConfig.tiny()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        ec = dataclasses.replace(ENG, weight_quant="int8", kv_quant="int8")
        vanilla = InferenceEngine(cfg, params, sampling=GREEDY, engine_config=ec, dtypes=FP32)
        spec = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(ec, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        for p in ([3, 17, 42, 7, 99], [5, 9, 2] * 5, [11] * 16):
            want = vanilla.generate([p])[0]
            got = spec.generate([p])[0]
            assert got == want, p
        assert spec.stats.spec_verify_steps > 0


class TestSampledDistribution:
    """Rejection-sampling verification must preserve the SAMPLED output
    distribution exactly: accept proposal x w.p. p(x) under the filtered
    target, else draw from the residual (p with x masked, renormalized) —
    so each emitted token is marginally one vanilla sampling step given its
    prefix. Verified empirically: the marginal of the token at position 1
    (the first token a VERIFY forward emits; position 0 is sampled
    identically in both paths) over many seeded runs must match vanilla
    within TV-distance noise. Tiny vocab keeps the support small enough for
    a sharp bound at a few thousand samples."""

    N = 3000
    TV_BOUND = 0.08  # empirical-vs-empirical noise at N=3000, support ~30

    @pytest.fixture(scope="class")
    def engines(self):
        cfg = LlamaConfig.tiny(vocab_size=32)
        params = init_llama_params(jax.random.PRNGKey(1), cfg, FP32)
        sampling = SamplingConfig(do_sample=True, temperature=0.7, top_p=0.9,
                                  max_new_tokens=3)
        vanilla = InferenceEngine(
            cfg, params, sampling=sampling, engine_config=ENG, dtypes=FP32
        )
        spec = InferenceEngine(
            cfg, params, sampling=sampling,
            engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        return cfg, vanilla, spec

    def _marginal(self, engine, cfg, prompt):
        counts = np.zeros(cfg.vocab_size, np.int64)
        for seed in range(self.N):
            out = engine.generate([prompt], seed=seed)[0]
            # row excludes EOS; len==1 with budget 3 means EOS at position 1
            sym = out[1] if len(out) > 1 else cfg.eos_token_ids[0]
            counts[sym] += 1
        return counts / counts.sum()

    def test_position1_marginal_matches_vanilla(self, engines):
        cfg, vanilla, spec = engines
        # repeats in the prompt so proposals actually fire (and get
        # accepted/rejected — the code path under test)
        prompt = [5, 9, 7, 5, 9, 7, 5, 9]
        pv = self._marginal(vanilla, cfg, prompt)
        ps = self._marginal(spec, cfg, prompt)
        tv = 0.5 * float(np.abs(pv - ps).sum())
        assert spec.stats.spec_verify_steps >= self.N  # spec path really ran
        assert tv < self.TV_BOUND, f"TV distance {tv:.4f}"

    def test_pinned_seed_is_reproducible(self, engines):
        cfg, _, spec = engines
        a = spec.generate([[5, 9, 7, 5, 9, 7]], seed=11)
        b = spec.generate([[5, 9, 7, 5, 9, 7]], seed=11)
        assert a == b

    def test_greedy_temperature_zero_equivalence(self, engines):
        """temperature <= 0 with do_sample=True compiles the GREEDY
        acceptance rule (matches sample_token's own greedy degeneration)."""
        cfg, _, _ = engines
        params = init_llama_params(jax.random.PRNGKey(1), cfg, FP32)
        g0 = SamplingConfig(do_sample=True, temperature=0.0, max_new_tokens=8)
        van = InferenceEngine(
            cfg, params,
            sampling=dataclasses.replace(g0, do_sample=False),
            engine_config=ENG, dtypes=FP32,
        )
        spc = InferenceEngine(
            cfg, params, sampling=g0,
            engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        p = [5, 9, 2, 5, 9, 2, 5, 9]
        assert spc.generate([p])[0] == van.generate([p])[0]


class TestAutoMode:
    """speculative="auto" (the default) must self-disable on measured low
    acceptance — a flat-logits model under sampling accepts ~nothing, so
    paying a verify forward per token would be pure overhead — and keep
    speculating where acceptance is high (greedy all-accept regime)."""

    def test_auto_disables_on_low_acceptance(self):
        cfg = LlamaConfig.tiny(vocab_size=64)
        params0 = jax.tree.map(
            lambda x: np.zeros_like(x),
            init_llama_params(jax.random.PRNGKey(0), cfg, FP32),
        )
        eng = InferenceEngine(
            cfg, params0,
            sampling=SamplingConfig(do_sample=True, max_new_tokens=8),
            engine_config=dataclasses.replace(ENG, speculative="auto"),
            dtypes=FP32,
        )
        p = [3, 17, 42, 3, 17, 42]
        for s in range(6):
            eng.generate([p], seed=s)
        assert eng._spec_ema is not None and eng._spec_ema < 1.1
        steps_before = eng.stats.spec_verify_steps
        for s in range(6, 10):
            eng.generate([p], seed=s)
        # vanilla path now serves: no further verify steps, and the vanilla
        # batch-1 executable exists
        assert eng.stats.spec_verify_steps == steps_before
        assert (1, 32, 8, None) in eng._compiled

    def test_auto_keeps_speculating_when_accepting(self):
        cfg = LlamaConfig.tiny()
        params0 = jax.tree.map(
            lambda x: np.zeros_like(x),
            init_llama_params(jax.random.PRNGKey(0), cfg, FP32),
        )
        eng = InferenceEngine(
            cfg, params0,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=12),
            engine_config=dataclasses.replace(ENG, speculative="auto"),
            dtypes=FP32,
        )
        p = [1] + [0] * 8  # constant emitter: every proposal accepted
        for _ in range(5):
            eng.generate([p])
        assert eng._spec_ema is not None and eng._spec_ema > 4.0
        before = eng.stats.spec_verify_steps
        eng.generate([p])
        assert eng.stats.spec_verify_steps > before
