"""Prompt-lookup speculative decoding (EngineConfig.speculative): the greedy
batch-1 fast path must be token-IDENTICAL to the vanilla loop on every input
— acceptance only ever keeps tokens equal to the model's own greedy argmax —
while the all-accept regime provably emits k+1 tokens per verify forward."""

import dataclasses

import jax
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=12)
ENG = EngineConfig(prompt_buckets=(32, 64), max_batch_size=2, max_seq_len=128)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    vanilla = InferenceEngine(cfg, params, sampling=GREEDY, engine_config=ENG, dtypes=FP32)
    spec = InferenceEngine(
        cfg, params, sampling=GREEDY,
        engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
        dtypes=FP32,
    )
    return cfg, params, vanilla, spec


PROMPTS = [
    [3, 17, 42, 7, 99],  # no obvious repeats
    [5, 9, 2, 5, 9, 2, 5, 9, 2],  # trailing n-gram repeats in-prompt
    [11] * 20,  # degenerate repeat
    [3, 17, 42, 7, 99, 3, 17, 42],  # repeat ending mid-span
    [8],  # shorter than the n-gram itself
    list(range(3, 30)),  # long distinct prompt
]


class TestExactness:
    def test_matches_vanilla_greedy(self, setup):
        _, _, vanilla, spec = setup
        for p in PROMPTS:
            want = vanilla.generate([p])[0]
            got = spec.generate([p])[0]
            assert got == want, p

    def test_budget_edges(self, setup):
        _, _, vanilla, spec = setup
        p = [5, 9, 2, 5, 9, 2, 5, 9, 2]
        for mn in (1, 2, 7, 8, 9, 20):  # around k+1 = 8 emission chunks
            assert spec.generate([p], max_new_tokens=mn)[0] == \
                vanilla.generate([p], max_new_tokens=mn)[0], mn

    def test_zero_slack_cache_shape_stays_exact(self, setup):
        """S + max_new an exact 128-multiple (the round-4 bench's own
        shapes): without k slack slots, the last verify forwards' KV writes
        would clamp-shift onto valid accepted KV and diverge near the
        budget. Repeat-heavy prompt drives acceptance right to the edge."""
        _, _, vanilla, spec = setup
        p = [5, 9, 2] * 6  # repeats: long accepted spans reach the budget
        for mn in (96, 95):  # 32 + 96 = 128 exactly
            want = vanilla.generate([p], max_new_tokens=mn)[0]
            got = spec.generate([p], max_new_tokens=mn)[0]
            assert got == want, mn

    def test_eos_mid_span(self, setup):
        """EOS inside an accepted span must truncate exactly where vanilla
        does. The EOS id is taken from the vanilla stream so it fires."""
        cfg, params, vanilla, _ = setup
        p = [5, 9, 2, 5, 9, 2, 5, 9, 2]
        stream = vanilla.generate([p])[0]
        assert len(stream) >= 4
        cfg_eos = dataclasses.replace(cfg, eos_token_ids=(stream[3],))
        v2 = InferenceEngine(cfg_eos, params, sampling=GREEDY, engine_config=ENG, dtypes=FP32)
        s2 = InferenceEngine(
            cfg_eos, params, sampling=GREEDY,
            engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        want = v2.generate([p])[0]
        got = s2.generate([p])[0]
        assert got == want
        assert len(want) == 3  # truncated at the injected EOS

    def test_fallbacks_to_vanilla(self, setup):
        cfg, params, vanilla, spec = setup
        # batch > 1: vanilla path (still correct)
        two = spec.generate([[3, 17, 42], [5, 9, 2]])
        assert two == vanilla.generate([[3, 17, 42], [5, 9, 2]])
        assert (2, 32, GREEDY.max_new_tokens, None) in spec._compiled
        # sampling: vanilla path
        sam = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=True, max_new_tokens=6, seed=3),
            engine_config=dataclasses.replace(ENG, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        sam.generate([[3, 17, 42]], seed=7)
        assert not any(k[3] == "spec" for k in sam._compiled)


class TestAcceptance:
    def test_all_accept_regime_emits_k_plus_1_per_step(self, setup):
        """Zero params make the model a constant emitter (uniform logits →
        argmax 0 forever); a prompt seeded with 0-runs makes every proposal
        correct, so max_new tokens must arrive in ceil((max_new-1)/(k+1))
        verify steps — the machinery's best case, measured not assumed."""
        cfg, _, _, _ = setup
        params0 = jax.tree.map(
            lambda x: np.zeros_like(x), init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        )
        ec = dataclasses.replace(ENG, speculative="prompt_lookup")
        spec = InferenceEngine(cfg, params0, sampling=GREEDY, engine_config=ec, dtypes=FP32)
        p = [1] + [0] * 8
        out = spec.generate([p], max_new_tokens=12)[0]
        assert out == [0] * 12
        k1 = ec.spec_tokens + 1
        want_steps = -(-(12 - 1) // k1)
        assert spec.stats.spec_verify_steps == want_steps

    def test_verify_steps_never_exceed_tokens(self, setup):
        _, _, _, spec = setup
        before = spec.stats.spec_verify_steps
        out = spec.generate([[3, 17, 42, 7, 99]], max_new_tokens=9)[0]
        steps = spec.stats.spec_verify_steps - before
        assert 1 <= steps <= len(out)


class TestSpecWithQuantization:
    """Speculation composes with int8 weights and the int8 KV cache: the
    verify forward is the q8 chunked-prefill path, acceptance compares the
    QUANTIZED model's own greedy choices — exactness is vs the quantized
    vanilla loop (the same numerics)."""

    def test_exact_vs_vanilla_int8_w_and_kv(self):
        cfg = LlamaConfig.tiny()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        ec = dataclasses.replace(ENG, weight_quant="int8", kv_quant="int8")
        vanilla = InferenceEngine(cfg, params, sampling=GREEDY, engine_config=ec, dtypes=FP32)
        spec = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=dataclasses.replace(ec, speculative="prompt_lookup"),
            dtypes=FP32,
        )
        for p in ([3, 17, 42, 7, 99], [5, 9, 2] * 5, [11] * 16):
            want = vanilla.generate([p])[0]
            got = spec.generate([p])[0]
            assert got == want, p
        assert spec.stats.spec_verify_steps > 0
