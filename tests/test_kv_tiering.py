"""Hotness-aware KV tiering (ISSUE 8): tier transitions, swap-in, parity.

The contracts under test (engine/tiering.py, engine/prefix_cache.py,
docs/KV_POOL.md "hotness-aware tiering"):

- **Hotness**: decayed hit-frequency per chunk key — exact decay math on an
  injectable clock; scores drive every tier decision.
- **Transitions**: hot → warm quantizes IN PLACE to int8 (device bytes
  drop, no re-prefill), any → cold spills to host RAM (zero device bytes),
  swap-in restores residency. The `_Entry` object survives every
  transition (PR 7's creation-stamp staging discipline holds across
  tiers), pinned entries never demote, and `clear()` leaves zero
  host-spill bookkeeping behind.
- **Parity** (`make tiering-smoke`): with tiering ENABLED and every chain
  hot, greedy streams are BYTE-IDENTICAL to tiering-off on both substrates
  (splice buffers and paged pool blocks); a hot→cold→swap-in round trip is
  also byte-exact (the spill stores the exact planes); forced WARM
  demotion keeps last-token logits within the pinned int8 tolerance.
- **Chaos**: a failed host→HBM swap-in (fault site ``kv_swap_in``) falls
  back to recompute-from-tokens, releases the host buffer, and leaks zero
  pool blocks.
- **Pool side**: registrations carry tiers; non-hot registrations are
  reclaimed by admission pressure first; reset() zeroes the tier ledgers.
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    KVTieringConfig,
    LlamaConfig,
    PrefixCacheConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.prefix_cache import PrefixCache
from rag_llm_k8s_tpu.engine.tiering import (
    HostSpillStore,
    HotnessTracker,
    dequantize_planes,
    quantize_planes,
)
from rag_llm_k8s_tpu.resilience import faults

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=6)

PC = PrefixCacheConfig(
    enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
    suffix_buckets=(16,), hbm_budget_mb=64,
)
# thresholds chosen so a single touch (score 1.0) is HOT and demotions
# only ever happen through force_demote / an explicit retier with decayed
# scores — the all-hot parity tests must see zero spontaneous transitions
TIERING = KVTieringConfig(
    enabled=True, warm_below=0.25, cold_below=0.0625,
    half_life_s=3600.0, retier_interval_s=0.0,
)
# warm_below above any reachable touch score: demoted-warm entries STAY
# warm across hits (serve through the dequant-at-splice path) instead of
# promoting on the first touch — the sticky config the warm-quality tests
# use to observe steady-state warm serving
STICKY_WARM = dataclasses.replace(
    TIERING, warm_below=1e9, cold_below=0.01, retier_interval_s=3600.0
)


def _engine(cfg, params, tiering=None, kv_quant="bf16"):
    ec = EngineConfig(
        prompt_buckets=(64,), max_batch_size=2, speculative="off",
        max_seq_len=128, prefix_cache=PC, kv_quant=kv_quant,
        kv_tiering=tiering if tiering is not None else KVTieringConfig(),
    )
    return InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=ec, dtypes=FP32
    )


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(vocab_size=128)
    params = init_params = jax.random.PRNGKey(0)
    from rag_llm_k8s_tpu.models.llama import init_llama_params

    params = init_llama_params(init_params, cfg, FP32)
    return cfg, params


def _segments(cfg, rng, tag):
    head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 7)))
    chunk = list(map(int, rng.integers(3, 120, 11)))
    return [(f"head:{tag}", head), (f"chunk:{tag}", chunk)]


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


class TestHotness:
    def test_decay_math_is_exact(self):
        t = {"now": 0.0}
        h = HotnessTracker(half_life_s=10.0, clock=lambda: t["now"])
        assert h.touch("a") == 1.0
        t["now"] = 10.0  # one half-life
        assert h.score("a") == pytest.approx(0.5)
        assert h.touch("a") == pytest.approx(1.5)
        t["now"] = 30.0  # two more half-lives
        assert h.score("a") == pytest.approx(1.5 / 4)
        assert h.score("never-seen") == 0.0

    def test_prune_drops_decayed_keys(self):
        t = {"now": 0.0}
        h = HotnessTracker(half_life_s=1.0, clock=lambda: t["now"])
        h.touch("a")
        h.touch("b")
        t["now"] = 60.0  # 60 half-lives: ~1e-18
        assert h.prune() == 2
        assert len(h) == 0


class TestHostSpillStore:
    def test_budget_evicts_oldest_first(self):
        s = HostSpillStore(budget_mb=1)
        big = (np.zeros(600 * 1024, np.uint8),)
        s.put("a", big)
        s.put("b", big)  # over 1 MiB: "a" evicts
        assert "a" not in s and "b" in s
        assert s.evictions == 1
        assert s.bytes == big[0].nbytes

    def test_drop_and_clear_release_bytes(self):
        s = HostSpillStore(budget_mb=4)
        s.put("a", (np.zeros(64, np.uint8),), meta={"quantized": False})
        host, meta = s.get("a")
        assert meta == {"quantized": False} and host[0].nbytes == 64
        assert s.drop("a") and not s.drop("a")
        s.put("b", (np.zeros(64, np.uint8),))
        s.clear()
        assert s.bytes == 0 and len(s) == 0

    def test_quantize_dequantize_round_trip_bounded(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 1, 2, 16, 8)).astype(np.float32)
        planes = (jnp.asarray(x), jnp.asarray(x * 0.5))
        q = quantize_planes(planes)
        assert q is not None and len(q) == 4 and q[0].dtype == jnp.int8
        back = dequantize_planes(q, jnp.float32)
        # symmetric per-vector scales bound the error at max|x|/254
        for orig, rec in zip(planes, back):
            bound = np.abs(np.asarray(orig)).max(axis=-1, keepdims=True) / 254.0
            assert np.all(np.abs(np.asarray(orig) - np.asarray(rec)) <= bound + 1e-7)
        # already-int8 tuples decline (int8-KV engines: label-only warm)
        assert quantize_planes(q[:2]) is None


# ---------------------------------------------------------------------------
# tier transitions on the stub substrate (no compiles)
# ---------------------------------------------------------------------------


class _StubEngine:
    """Host-only engine stand-in with REAL fp32 plane tuples, so warm
    quantization and cold spill exercise the actual byte paths."""

    def __init__(self, tokens=16):
        self.tokens = tokens
        rng = np.random.default_rng(7)
        self._proto = rng.standard_normal((2, 1, 2, tokens, 8)).astype(np.float32)

    def prefix_buffer_zero(self):
        return (jnp.zeros((2, 1, 2, 64, 8), jnp.float32),) * 2

    def build_segment_kv(self, ids, ctx, off):
        base = jnp.asarray(self._proto) * (1 + len(ids))
        return (base, base * 0.5)

    def splice_prefix(self, buf, block, off):
        return buf


def _cache(**tier_kw):
    cfg = PrefixCacheConfig(
        enabled=True, max_prefix_tokens=4096, segment_buckets=(64,),
        suffix_buckets=(128,), hbm_budget_mb=64,
    )
    t = dataclasses.replace(TIERING, **tier_kw)
    return PrefixCache(cfg, _StubEngine(), tiering=t)


SEGS = [("head", list(range(8))), ("chunk:a", list(range(16)))]


class TestTierTransitions:
    def test_demote_warm_shrinks_device_bytes_in_place(self):
        c = _cache()
        c.prefix_for(SEGS)
        hot = c.tier_stats()
        assert hot["tier_hot_entries"] == 2
        stamps = {k: e.stamp for k, e in c._entries.items()}
        assert c.force_demote("warm") == 2
        warm = c.tier_stats()
        assert warm["tier_warm_entries"] == 2 and warm["tier_hot_entries"] == 0
        assert warm["tier_warm_bytes"] < hot["tier_hot_bytes"]
        assert warm["demotes_warm"] == 2
        # in-place: same entry objects, same stamps (staging discipline)
        assert {k: e.stamp for k, e in c._entries.items()} == stamps
        assert c.entry_bytes == warm["tier_warm_bytes"]

    def test_demote_cold_spills_and_swap_in_restores_exactly(self):
        c = _cache()
        c.prefix_for(SEGS)
        orig = {
            k: tuple(np.asarray(p) for p in e.planes)
            for k, e in c._entries.items()
        }
        assert c.force_demote("cold") == 2
        st = c.tier_stats()
        assert st["tier_cold_entries"] == 2 and c.entry_bytes == 0
        assert st["tier_cold_host_bytes"] > 0
        c._assembled.clear(); c.assembled_bytes = 0  # force past the memo
        cp = c.prefix_for(SEGS)
        assert cp.computed_tokens == 0  # swap-in, never re-prefill
        st = c.tier_stats()
        assert st["swap_ins_demand"] == 2 and st["tier_cold_host_bytes"] == 0
        # a hot→cold→swap-in round trip is BYTE-exact
        for k, planes in orig.items():
            e = c._entries[k]
            assert e.tier == "hot" and not e.quantized
            for a, b in zip(planes, e.planes):
                np.testing.assert_array_equal(a, np.asarray(b))

    def test_warm_then_cold_swap_in_restores_warm(self):
        c = _cache(warm_below=STICKY_WARM.warm_below,
                   cold_below=STICKY_WARM.cold_below)
        c.prefix_for(SEGS)
        c.force_demote("warm")
        warm_bytes = c.entry_bytes
        c.force_demote("cold")
        c._assembled.clear(); c.assembled_bytes = 0
        cp = c.prefix_for(SEGS)
        assert cp is not None and cp.computed_tokens == 0
        st = c.tier_stats()
        # quantized planes spill and restore as warm (dequant on splice)
        assert st["tier_warm_entries"] == 2
        assert c.entry_bytes == warm_bytes

    def test_rehit_promotes_swapped_in_warm_entry(self):
        """Under the DEFAULT thresholds a hit is hotness: the same resolve
        that swaps a quantized entry back in promotes it to hot (the
        dequantized copy is materialized; the int8 drift is retained until
        the entry is rebuilt)."""
        c = _cache()
        c.prefix_for(SEGS)
        c.force_demote("warm")
        c.force_demote("cold")
        c._assembled.clear(); c.assembled_bytes = 0
        cp = c.prefix_for(SEGS)
        assert cp is not None and cp.computed_tokens == 0
        st = c.tier_stats()
        assert st["tier_hot_entries"] == 2 and st["promotes"] == 2
        assert all(not e.quantized for e in c._entries.values())

    def test_retier_uses_decayed_scores_and_pins_survive(self):
        t = {"now": 0.0}
        c = _cache(half_life_s=10.0)
        c.hotness = HotnessTracker(10.0, clock=lambda: t["now"])
        c.pin("head")
        c.prefix_for(SEGS)
        t["now"] = 25.0  # 2.5 half-lives: score ~0.177 → warm band
        assert c.retier(force=True) == 1  # chunk only — head is pinned
        assert c._entries[("head", 0, ())].tier == "hot"
        t["now"] = 60.0  # score ~0.0156 → cold band
        c.retier(force=True)
        st = c.tier_stats()
        assert st["tier_cold_entries"] == 1
        assert c._entries[("head", 0, ())].tier == "hot"

    def test_promotion_on_rehit(self):
        t = {"now": 0.0}
        c = _cache(half_life_s=10.0)
        c.hotness = HotnessTracker(10.0, clock=lambda: t["now"])
        c.prefix_for(SEGS)
        t["now"] = 25.0
        c.retier(force=True)
        assert c.tier_stats()["tier_warm_entries"] == 2
        c._assembled.clear(); c.assembled_bytes = 0
        c.prefix_for(SEGS)  # touch → scores back over warm_below → promote
        st = c.tier_stats()
        assert st["tier_hot_entries"] == 2 and st["promotes"] == 2

    def test_demote_while_prestaged_release_discipline(self):
        """PR 7's creation-stamp staging must hold across tiers: a staged
        entry demoted COLD before the speculation dies still releases —
        including its host buffer; one another request consumed does not."""
        c = _cache()
        cp, record = c.stage(SEGS)
        assert cp is not None and record
        c.force_demote("cold")
        assert len(c.spill) == 2
        released = c.release_staged(record)
        assert released >= 2
        assert len(c.spill) == 0  # host buffers went with the entries
        assert len(c._entries) == 0

        # consumed-since-staging: the entry (and its spill) survive
        cp, record = c.stage(SEGS)
        c._assembled.clear(); c.assembled_bytes = 0
        c.prefix_for(SEGS)  # a live request consumed the staged entries
        c.force_demote("cold")
        c.release_staged(record)
        assert len(c._entries) == 2 and len(c.spill) == 2

    def test_clear_clears_host_spill_bookkeeping(self):
        c = _cache()
        c.prefix_for(SEGS)
        c.force_demote("cold")
        assert c.spill.bytes > 0
        c.clear()
        assert c.spill.bytes == 0 and len(c.spill) == 0
        assert c.tier_stats()["tier_cold_host_bytes"] == 0

    def test_swap_in_fault_falls_back_to_recompute(self):
        c = _cache()
        c.prefix_for(SEGS)
        c.force_demote("cold")
        c._assembled.clear(); c.assembled_bytes = 0
        faults.arm("kv_swap_in", times=1)
        try:
            cp = c.prefix_for(SEGS)
        finally:
            faults.clear()
        assert cp is not None
        st = c.tier_stats()
        assert st["swap_in_fallbacks"] == 1
        # ONE segment recomputed (8 head tokens), the other swapped in
        assert cp.computed_tokens == 8
        assert st["swap_ins_demand"] == 1
        # the failed entry's host buffer was released with it
        assert len(c.spill) == 0

    def test_host_store_eviction_is_an_ordinary_miss(self):
        c = _cache(host_spill_mb=1)
        c.spill = HostSpillStore(budget_mb=1)
        c.prefix_for(SEGS)
        c.force_demote("cold")
        c.spill.clear()  # model the budget having evicted everything
        c._assembled.clear(); c.assembled_bytes = 0
        cp = c.prefix_for(SEGS)
        assert cp is not None and cp.computed_tokens == 24  # full rebuild
        assert c.tier_stats()["swap_in_fallbacks"] == 0  # not a failure

    def test_bytes_by_device_survives_cold_entries(self):
        """A /metrics scrape must not die on a cold entry's planes=None —
        the per-device gauge attributes only RESIDENT bytes (regression:
        this raised TypeError and zeroed the gauge for every device)."""
        c = _cache()
        c.prefix_for(SEGS)
        resident = sum(c.bytes_by_device().values())
        assert resident > 0
        c.force_demote("cold")
        by_dev = c.bytes_by_device()  # must not raise
        # only the assembled memo's bytes remain on device
        assert sum(by_dev.values()) == c.assembled_bytes

    def test_retier_prunes_cold_entries_without_host_backing(self):
        """A cold entry whose spill buffer fell off the host budget can
        never swap in again — the sweep drops the stub instead of letting
        cold entries accrete one dict node per chunk ever cached."""
        c = _cache()
        c.prefix_for(SEGS)
        c.force_demote("cold")
        assert len(c._entries) == 2
        c.spill.clear()  # model host-budget eviction of the backing
        c.retier(force=True)
        assert len(c._entries) == 0

    def test_lookahead_trigger_attribution_and_hide_rate(self):
        c = _cache()
        c.prefix_for(SEGS)
        c.force_demote("cold")
        c._assembled.clear(); c.assembled_bytes = 0
        cp, record = c.stage(SEGS)  # the prestage path: trigger=lookahead
        assert cp.computed_tokens == 0
        st = c.tier_stats()
        assert st["swap_ins_lookahead"] == 2 and st["swap_ins_demand"] == 0
        # the executor folds these into the hide rate
        from rag_llm_k8s_tpu.core.config import LookaheadConfig
        from rag_llm_k8s_tpu.rag.lookahead import LookaheadExecutor

        ex = LookaheadExecutor(
            LookaheadConfig(enabled=True, max_workers=1),
            retrieve_fn=lambda text: [],
            tier_stats_fn=c.tier_stats,
        )
        try:
            stats = ex.stats()
            assert stats["swap_in_hide_rate"] == 1.0
            assert stats["swap_ins_hidden"] == 2
        finally:
            ex.shutdown()


class TestAssembledMemoEviction:
    """Satellite (the open item carried since the tiering PR): the
    assembled-memo cache evicts TIER-AWARE — a memo whose chain's coldest
    segment demoted gives its buffer back first, even when a hot chain's
    memo is older in LRU order."""

    def _tiered_cache(self, entries=2):
        cfg = PrefixCacheConfig(
            enabled=True, max_prefix_tokens=4096, segment_buckets=(64,),
            suffix_buckets=(128,), hbm_budget_mb=64,
            assembled_cache_entries=entries,
        )
        t = {"now": 0.0}
        c = PrefixCache(
            cfg, _StubEngine(),
            tiering=dataclasses.replace(TIERING, half_life_s=10.0),
        )
        c.hotness = HotnessTracker(10.0, clock=lambda: t["now"])
        return c, t

    HOT = [("head", list(range(8))), ("chunk:hot", list(range(16)))]
    COLD = [("head", list(range(8))), ("chunk:cold", list(range(16)))]
    NEW = [("head", list(range(8))), ("chunk:new", list(range(16)))]

    @staticmethod
    def _chains(c):
        return {ak[0] for ak in c._assembled}

    def test_cold_chain_memo_evicts_before_older_hot_chain(self):
        c, t = self._tiered_cache(entries=2)
        c.prefix_for(self.HOT)   # the OLDER memo (pure LRU's victim)
        c.prefix_for(self.COLD)
        assert len(c._assembled) == 2
        t["now"] = 60.0  # 6 half-lives: every score deep in the cold band
        for k, _ in self.HOT:
            c.hotness.touch(k)  # re-heat ONLY the hot chain's members
        # the third resolve trips the count cap: tier-aware eviction must
        # take the cold chain's memo, not the older hot one
        c.prefix_for(self.NEW)
        chains = self._chains(c)
        assert ("head", "chunk:hot") in chains
        assert ("head", "chunk:cold") not in chains
        assert ("head", "chunk:new") in chains

    def test_untiered_cache_keeps_pure_lru(self):
        cfg = PrefixCacheConfig(
            enabled=True, max_prefix_tokens=4096, segment_buckets=(64,),
            suffix_buckets=(128,), hbm_budget_mb=64,
            assembled_cache_entries=2,
        )
        c = PrefixCache(cfg, _StubEngine(), tiering=None)
        c.prefix_for(self.HOT)
        c.prefix_for(self.COLD)
        c.prefix_for(self.NEW)
        chains = self._chains(c)
        # no tiers: the oldest memo goes, exactly as before
        assert ("head", "chunk:hot") not in chains
        assert ("head", "chunk:cold") in chains

    def test_budget_sweep_consumes_the_same_tier_order(self):
        """``_enforce_budget_locked`` consumes ``_assembled_evict_order``
        — pin that the order puts the cold chain's memo first and the
        re-heated hot chain's last, LRU notwithstanding."""
        c, t = self._tiered_cache(entries=8)
        c.prefix_for(self.HOT)   # older in LRU order
        c.prefix_for(self.COLD)
        t["now"] = 60.0
        for k, _ in self.HOT:
            c.hotness.touch(k)
        order = [ak[0] for ak in c._assembled_evict_order()]
        assert order[0] == ("head", "chunk:cold")
        assert order[-1] == ("head", "chunk:hot")


class TestConcurrency:
    def test_promote_while_serving_stays_consistent(self):
        """Resolves racing retier demotions/promotions: every resolve must
        return a full-length prefix and the byte ledgers must balance."""
        c = _cache(half_life_s=0.001, retier_interval_s=0.0)
        errors = []
        stop = threading.Event()

        def serve():
            try:
                while not stop.is_set():
                    cp = c.prefix_for(SEGS)
                    assert cp is not None and cp.length == 24
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def churn():
            try:
                while not stop.is_set():
                    c.force_demote("warm")
                    c.force_demote("cold")
                    c._assembled.clear()
                    c.assembled_bytes = 0
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=serve) for _ in range(2)] + [
            threading.Thread(target=churn)
        ]
        for t in threads:
            t.start()
        import time as _time

        _time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors
        st = c.tier_stats()
        resident = st["tier_hot_bytes"] + st["tier_warm_bytes"]
        assert c.entry_bytes == resident >= 0


# ---------------------------------------------------------------------------
# real-engine parity (make tiering-smoke runs this class)
# ---------------------------------------------------------------------------


class TestSmoke:
    def test_all_hot_streams_byte_identical_both_substrates(self, tiny):
        """Tiering ON with every chain hot is BYTE-IDENTICAL to tiering
        off — splice-buffer substrate (generate_prefixed) and paged pool
        substrate (admit_prefixed) alike."""
        cfg, params = tiny
        rng = np.random.default_rng(3)
        segments = _segments(cfg, rng, "smoke")
        suffix = list(map(int, rng.integers(3, 120, 5)))

        off = _engine(cfg, params)
        on = _engine(cfg, params, tiering=TIERING)
        cp_off = off.prefix_cache.prefix_for(segments)
        cp_on = on.prefix_cache.prefix_for(segments)
        assert on.prefix_cache.tier_stats()["tier_hot_entries"] == 2
        got_off = off.generate_prefixed(suffix, cp_off)
        got_on = on.generate_prefixed(suffix, cp_on)
        assert got_on == got_off

        # paged pool substrate
        paged_cfg = dataclasses.replace(
            on.engine_config, kv_paged=True, kv_block_size=16
        )
        cont = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=paged_cfg, dtypes=FP32
        )
        _, fin = cont.admit_prefixed(1, suffix, cp_on, max_new=6)
        outs = {}
        while cont.has_active():
            for rid, toks in cont.step():
                outs[rid] = toks
        got_paged = fin if fin is not None else outs[1]
        full = [t for _, seg in segments for t in seg] + suffix
        want = off.generate([full])[0]
        assert got_off == want and got_paged == want

    def test_cold_swap_in_stream_byte_identical(self, tiny):
        """hot → cold → swap-in round-trips the EXACT planes: the greedy
        stream after a swap-in matches the never-demoted stream byte for
        byte (only warm's int8 round trip costs drift)."""
        cfg, params = tiny
        rng = np.random.default_rng(5)
        segments = _segments(cfg, rng, "cold")
        suffix = list(map(int, rng.integers(3, 120, 6)))
        eng = _engine(cfg, params, tiering=TIERING)
        cache = eng.prefix_cache
        cp = cache.prefix_for(segments)
        want = eng.generate_prefixed(suffix, cp)
        assert cache.force_demote("cold") == 2
        cache._assembled.clear(); cache.assembled_bytes = 0
        cp2 = cache.prefix_for(segments)
        assert cp2.computed_tokens == 0
        assert cache.tier_stats()["swap_ins_demand"] == 2
        assert eng.generate_prefixed(suffix, cp2) == want

    def test_forced_warm_demotion_logits_within_tolerance(self, tiny):
        """Warm (int8) chunks serve within the pinned logit tolerance: the
        spliced-prefix last-token logits move by less than INT8_LOGIT_ATOL
        vs the all-hot resolve, and stay far from zero-information."""
        cfg, params = tiny
        rng = np.random.default_rng(7)
        segments = _segments(cfg, rng, "warm")
        suffix = list(map(int, rng.integers(3, 120, 6)))
        eng = _engine(cfg, params, tiering=STICKY_WARM)
        cache = eng.prefix_cache
        cp_hot = cache.prefix_for(segments)
        assert cache.force_demote("warm") == 2
        cache._assembled.clear(); cache.assembled_bytes = 0
        cp_warm = cache.prefix_for(segments)
        assert cp_warm.computed_tokens == 0  # dequant, never re-prefill
        assert cache.tier_stats()["tier_warm_entries"] == 2

        def last_logits(cp):
            from rag_llm_k8s_tpu.models.llama import KVCache, make_kv_cache

            T, S_suf = 64, 16
            n = cp.length + len(suffix)
            cache_d = make_kv_cache(cfg, 1, T, jnp.float32)
            planes = tuple(
                jax.lax.dynamic_update_slice(c, b, (0,) * c.ndim)
                for c, b in zip((cache_d.k, cache_d.v), cp.planes)
            )
            toks = np.zeros((1, S_suf), np.int32)
            toks[0, : len(suffix)] = suffix
            pos = (cp.length + jnp.arange(S_suf, dtype=jnp.int32))[None, :]
            logits, _ = eng.model_chunked.apply(
                {"params": eng.params}, jnp.asarray(toks), pos,
                KVCache(*planes), jnp.zeros((1,), jnp.int32),
                jnp.full((1,), n, jnp.int32), jnp.int32(cp.length),
                logit_index=jnp.int32(len(suffix) - 1),
            )
            return np.asarray(logits[0, -1])

        hot_l, warm_l = last_logits(cp_hot), last_logits(cp_warm)
        INT8_LOGIT_ATOL = 0.15  # the pinned warm-tier quality contract
        np.testing.assert_allclose(warm_l, hot_l, atol=INT8_LOGIT_ATOL)
        assert np.abs(warm_l - hot_l).max() > 0  # it DID go through int8

    def test_mixed_tier_rows_share_one_paged_admission_group(self, tiny):
        """One admission group with a hot-prefix row and a warm-prefix row
        (mixed bf16/int8-history rows): both serve; the hot row's stream
        stays byte-identical to a plain full-prompt admission."""
        cfg, params = tiny
        rng = np.random.default_rng(11)
        seg_hot = _segments(cfg, rng, "mixhot")
        seg_warm = _segments(cfg, rng, "mixwarm")
        suffix = list(map(int, rng.integers(3, 120, 6)))
        eng = _engine(cfg, params, tiering=STICKY_WARM)
        cache = eng.prefix_cache
        cache.prefix_for(seg_warm)
        cache.force_demote("warm")
        cache._assembled.clear(); cache.assembled_bytes = 0
        cp_hot = cache.prefix_for(seg_hot)  # fresh build: hot, bf16-exact
        cp_warm = cache.prefix_for(seg_warm)  # dequantized int8 history
        assert cache.tier_stats()["tier_warm_entries"] == 2
        paged_cfg = dataclasses.replace(
            eng.engine_config, kv_paged=True, kv_block_size=16
        )
        cont = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=paged_cfg, dtypes=FP32
        )

        def drain(rid, fin):
            outs = {}
            while cont.has_active():
                for r, toks in cont.step():
                    outs[r] = toks
            return fin if fin is not None else outs[rid]

        # both tiers decode TOGETHER in one group of slots
        _, fin1 = cont.admit_prefixed(1, suffix, cp_hot, max_new=6)
        _, fin2 = cont.admit_prefixed(2, suffix, cp_warm, max_new=6)
        outs = {}
        while cont.has_active():
            for r, toks in cont.step():
                outs[r] = toks
        got_hot = fin1 if fin1 is not None else outs[1]
        got_warm = fin2 if fin2 is not None else outs[2]
        assert got_warm is not None  # the warm row served
        # the hot row's stream is byte-identical to a plain full admission
        full_hot = [t for _, seg in seg_hot for t in seg] + suffix
        _, fin3 = cont.admit(3, full_hot, max_new=6)
        want_hot = drain(3, fin3)
        assert got_hot == want_hot
        # every row retired its blocks — only the two chains' registered
        # full prefix blocks (cache refs) remain (no group-mixing leak)
        registered = (cp_hot.length // 16) + (cp_warm.length // 16)
        assert cont.kv_pool.blocks_in_use() == registered


# ---------------------------------------------------------------------------
# pool-side tier accounting
# ---------------------------------------------------------------------------


class TestPoolTiering:
    @pytest.fixture()
    def paged(self, tiny):
        cfg, params = tiny
        ec = EngineConfig(
            prompt_buckets=(64,), max_batch_size=2, speculative="off",
            max_seq_len=128, prefix_cache=PC, kv_paged=True,
            kv_block_size=16, kv_pool_blocks=24,
        )
        return cfg, params, ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ec, dtypes=FP32
        )

    def _prefix(self, cfg, params, tag="pool"):
        eng = _engine(cfg, params, tiering=TIERING)
        rng = np.random.default_rng(13)
        segs = _segments(cfg, rng, tag)
        return eng, eng.prefix_cache.prefix_for(segs)

    def test_registration_tier_accounting_and_reclaimable(self, paged, tiny):
        cfg, params, cont = paged
        _, cp = self._prefix(cfg, params)
        assert cont.prestage_prefix(cp, tier="warm") == "registered"
        occ = cont.tier_occupancy()
        assert occ["warm"] == cp.length // cont.block_size
        assert cont.reclaimable_blocks() == occ["warm"]
        # warm → hot: no longer reclaimable
        assert cont.set_prefix_tier(cp.chain_key, "hot")
        assert cont.reclaimable_blocks() == 0
        assert cont.tier_occupancy()["hot"] > 0
        # hot → cold DROPS the registration (pool-side spill)
        free_before = cont.kv_pool.available()
        assert cont.set_prefix_tier(cp.chain_key, "cold")
        assert cont.kv_pool.available() == free_before + occ["warm"]
        assert sum(
            v for k, v in cont.tier_occupancy().items() if k != "rows"
        ) == 0

    def test_admission_reclaims_warm_registration_while_active(self, paged, tiny):
        """A live row decoding + a warm registration crowding the pool:
        admission_state reclaims the WARM registration instead of
        reporting 'wait' — tier occupancy, not raw headroom."""
        cfg, params, cont = paged
        _, cp = self._prefix(cfg, params)
        cont.admit(1, [5] * 40, max_new=4)  # 3 blocks + growth, stays active
        assert cont.prestage_prefix(cp, tier="warm") == "registered"
        # eat the remaining headroom so the next admission can't fit
        # without the registration's block coming back
        filler = cont.kv_pool.alloc(cont.kv_pool.available() - 2)
        assert cont.has_active()
        state = cont.admission_state(30)  # needs 2 blocks + headroom
        assert state == "ok"  # the warm registration was reclaimed
        assert cont.reclaimable_blocks() == 0
        cont.kv_pool.free(filler)
        cont.evict_requests([1])

    def test_prestage_swap_in_fault_leaks_zero_blocks(self, paged, tiny):
        cfg, params, cont = paged
        _, cp = self._prefix(cfg, params)
        free = cont.kv_pool.available()
        faults.arm("kv_swap_in", times=1)
        try:
            assert cont.prestage_prefix(cp) is False
        finally:
            faults.clear()
        assert cont.kv_pool.available() == free  # zero leaked blocks
        # and the next prestage (fault cleared) succeeds
        assert cont.prestage_prefix(cp) == "registered"
        cont.release_prestaged(cp.chain_key)
        assert cont.kv_pool.available() == free

    def test_reset_clears_tier_ledgers(self, paged, tiny):
        cfg, params, cont = paged
        _, cp = self._prefix(cfg, params)
        assert cont.prestage_prefix(cp, tier="warm") == "registered"
        assert cont.reclaimable_blocks() > 0
        cont.reset()
        assert cont.reclaimable_blocks() == 0
        occ = cont.tier_occupancy()
        assert occ["hot"] == occ["warm"] == occ["rows"] == 0
        assert cont.kv_pool.available() == cont.kv_pool.usable_blocks()


class TestAdmissionTierHint:
    def test_saturated_pool_with_reclaimable_blocks_queues_not_sheds(self):
        from rag_llm_k8s_tpu.resilience.admission import (
            AdmissionController,
            AdmissionRejected,
        )

        gate = AdmissionController(max_concurrency=1, max_queue=4)
        gate.saturation_hint = lambda: True
        gate.reclaimable_hint = lambda: 0
        holder = gate.admit()
        holder.__enter__()
        with pytest.raises(AdmissionRejected) as ei:
            with gate.admit():
                pass
        assert ei.value.reason == "pool_exhausted"
        # with reclaimable warmth the request QUEUES instead
        gate.reclaimable_hint = lambda: 3
        got = []

        def second():
            with gate.admit():
                got.append(True)

        t = threading.Thread(target=second)
        t.start()
        import time as _time

        _time.sleep(0.1)
        assert not got  # queued, not rejected
        holder.__exit__(None, None, None)
        t.join(timeout=5)
        assert got == [True]
