"""Ring attention vs dense attention oracle on the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import MeshConfig
from rag_llm_k8s_tpu.core.mesh import make_mesh
from rag_llm_k8s_tpu.parallel.ring_attention import ring_attention_sharded


def dense_attention(q, k, v, causal=True, kv_valid=None):
    """Reference: full-materialization GQA attention, fp32."""
    B, S, H, hd = q.shape
    K = k.shape[2]
    G = H // K
    qg = q.reshape(B, S, K, G, hd)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32)
    s = s * (hd**-0.5)
    ok = jnp.ones((B, S, S), bool)
    if kv_valid is not None:
        ok = ok & kv_valid[:, None, :]
    if causal:
        pos = jnp.arange(S)
        ok = ok & (pos[None, None, :] <= pos[None, :, None])
    s = jnp.where(ok[:, None, None, :, :].transpose(0, 1, 2, 3, 4), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgqs,bskd->bqkgd", p, v.astype(jnp.float32))
    return o.reshape(B, S, H, hd)


@pytest.fixture(scope="module")
def sp_mesh(devices8):
    return make_mesh(MeshConfig(dp=1, sp=8, tp=1), devices=devices8)


def _problem(seed, B=2, S=64, H=4, K=2, hd=8):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, K, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, K, hd), jnp.float32)
    return q, k, v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, sp_mesh, causal):
        q, k, v = _problem(0)
        got = ring_attention_sharded(sp_mesh, q, k, v, causal=causal)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_respects_kv_validity(self, sp_mesh):
        """Masked (padded) key positions must not contribute."""
        q, k, v = _problem(1)
        B, S = q.shape[:2]
        kv_valid = jnp.arange(S)[None, :] < 40  # last 24 positions padded
        kv_valid = jnp.broadcast_to(kv_valid, (B, S))
        got = ring_attention_sharded(sp_mesh, q, k, v, causal=False, kv_valid=kv_valid)
        want = dense_attention(q, k, v, causal=False, kv_valid=kv_valid)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_gqa_grouping(self, sp_mesh):
        q, k, v = _problem(2, H=8, K=2)
        got = ring_attention_sharded(sp_mesh, q, k, v, causal=True)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_gradients_flow(self, sp_mesh):
        """Ring attention must be differentiable (training over long seqs)."""
        q, k, v = _problem(3, B=1, S=32)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention_sharded(sp_mesh, q, k, v) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v) ** 2)

        g_ring = jax.grad(loss_ring)(q, k, v)
        g_dense = jax.grad(loss_dense)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), rtol=1e-3, atol=1e-4)
