"""Ring attention vs dense attention oracle on the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import set_mesh

from rag_llm_k8s_tpu.core.config import MeshConfig
from rag_llm_k8s_tpu.core.mesh import make_mesh
from rag_llm_k8s_tpu.parallel.ring_attention import ring_attention_sharded


def dense_attention(q, k, v, causal=True, kv_valid=None):
    """Reference: full-materialization GQA attention, fp32."""
    B, S, H, hd = q.shape
    K = k.shape[2]
    G = H // K
    qg = q.reshape(B, S, K, G, hd)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32)
    s = s * (hd**-0.5)
    ok = jnp.ones((B, S, S), bool)
    if kv_valid is not None:
        ok = ok & kv_valid[:, None, :]
    if causal:
        pos = jnp.arange(S)
        ok = ok & (pos[None, None, :] <= pos[None, :, None])
    s = jnp.where(ok[:, None, None, :, :].transpose(0, 1, 2, 3, 4), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgqs,bskd->bqkgd", p, v.astype(jnp.float32))
    return o.reshape(B, S, H, hd)


@pytest.fixture(scope="module")
def sp_mesh(devices8):
    return make_mesh(MeshConfig(dp=1, sp=8, tp=1), devices=devices8)


def _problem(seed, B=2, S=64, H=4, K=2, hd=8):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, K, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, K, hd), jnp.float32)
    return q, k, v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, sp_mesh, causal):
        q, k, v = _problem(0)
        got = ring_attention_sharded(sp_mesh, q, k, v, causal=causal)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_respects_kv_validity(self, sp_mesh):
        """Masked (padded) key positions must not contribute."""
        q, k, v = _problem(1)
        B, S = q.shape[:2]
        kv_valid = jnp.arange(S)[None, :] < 40  # last 24 positions padded
        kv_valid = jnp.broadcast_to(kv_valid, (B, S))
        got = ring_attention_sharded(sp_mesh, q, k, v, causal=False, kv_valid=kv_valid)
        want = dense_attention(q, k, v, causal=False, kv_valid=kv_valid)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_gqa_grouping(self, sp_mesh):
        q, k, v = _problem(2, H=8, K=2)
        got = ring_attention_sharded(sp_mesh, q, k, v, causal=True)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_gradients_flow(self, sp_mesh):
        """Ring attention must be differentiable (training over long seqs)."""
        q, k, v = _problem(3, B=1, S=32)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention_sharded(sp_mesh, q, k, v) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v) ** 2)

        g_ring = jax.grad(loss_ring)(q, k, v)
        g_dense = jax.grad(loss_dense)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), rtol=1e-3, atol=1e-4)


class TestModelSequenceParallel:
    """Ring attention is REACHABLE: a model built with an sp>1 mesh runs its
    prefill/training attention as the ring (previously dead code)."""

    @pytest.fixture(scope="class")
    def sp_mix_mesh(self, devices8):
        return make_mesh(MeshConfig(dp=2, sp=2, tp=2), devices=devices8)

    def test_prefill_logits_match_sp1(self, sp_mix_mesh):
        import dataclasses

        from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
        from rag_llm_k8s_tpu.models.llama import (
            LlamaModel,
            init_llama_params,
            make_kv_cache,
        )

        FP32 = DTypePolicy.fp32()
        cfg = dataclasses.replace(
            LlamaConfig.tiny(), num_heads=4, num_kv_heads=2, head_dim=8,
            hidden_size=32,
        )
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        B, S = 2, 32
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 2, cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        window = jnp.array([0, 5], jnp.int32), jnp.full((B,), S, jnp.int32)

        ref = LlamaModel(cfg, FP32, attn_impl="xla")
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        want, _ = ref.apply({"params": params}, tokens, pos, cache, *window, jnp.int32(0))

        ring_model = LlamaModel(cfg, FP32, attn_impl="xla", mesh=sp_mix_mesh.mesh)
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        with set_mesh(sp_mix_mesh.mesh):
            got, _ = jax.jit(
                lambda p, t: ring_model.apply(
                    {"params": p}, t, pos, cache, *window, jnp.int32(0)
                )
            )(params, tokens)
        # rows attend only their valid windows; compare valid query positions
        for b, start in enumerate([0, 5]):
            np.testing.assert_allclose(
                np.asarray(got)[b, start:], np.asarray(want)[b, start:],
                rtol=2e-4, atol=2e-5,
            )

    def test_train_step_grads_match_sp1(self, sp_mix_mesh):
        import dataclasses

        from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
        from rag_llm_k8s_tpu.engine.training import make_train_step
        from rag_llm_k8s_tpu.models.llama import init_llama_params

        FP32 = DTypePolicy.fp32()
        cfg = dataclasses.replace(
            LlamaConfig.tiny(), num_heads=4, num_kv_heads=2, head_dim=8,
            hidden_size=32,
        )
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        B, S = 4, 32
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 2, cfg.vocab_size)
        mask = jnp.ones((B, S), jnp.int32)

        init_opt, step_sp1 = make_train_step(cfg, FP32)
        _, _, loss1 = jax.jit(step_sp1)(params, init_opt(params), tokens, mask)

        init_opt2, step_ring = make_train_step(cfg, FP32, mesh=sp_mix_mesh.mesh)
        with set_mesh(sp_mix_mesh.mesh):
            p2, _, loss2 = jax.jit(step_ring)(params, init_opt2(params), tokens, mask)
        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
        # updated params must match too (gradients flowed through the ring)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            ),
            jax.device_get(jax.jit(step_sp1)(params, init_opt(params), tokens, mask)[0]),
            jax.device_get(p2),
        )
