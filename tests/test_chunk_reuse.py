"""Chunk-granular prefix reuse via attention invariance (ISSUE 12).

The contracts under test (engine/prefix_cache.py ``reuse="chunk"``,
ops/attention.py ``rope_rerotate``, docs/PREFIX_CACHE.md "chunk-granular
reuse"):

- **Re-rotation math**: K cached at position ``p`` re-rotated by ``delta``
  equals K computed at ``p + delta`` (closed form, no re-prefill); delta=0
  is the bit-exact identity; the int8 dequant→rotate→requant round trip
  stays within the per-vector quantization bound.
- **Shuffled-composition tolerance**: the same chunk set permuted across
  queries serves from re-rotated + boundary-corrected canonical KV with
  spliced-vs-cold last-token logits within the pinned tolerance (0.15, the
  warm tier's pin) — on the one-shot splice-buffer substrate AND the paged
  per-chunk pool assembly, hot and warm tiers, and tp=2 under the serving
  specs.
- **Exact-chain regression**: a canonical-position, canonical-chain hit is
  served bit-identically (no rotation, no fixup), and the chunk-mode
  buffer for a first-seen chain equals the ``reuse="exact"`` buffer
  byte-for-byte.
- **Chaos**: a mid-splice fault (site ``chunk_splice``) falls back to
  recompute with zero leaked entries/blocks on either substrate (the
  chaos-lane twin lives in tests/test_resilience.py).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EngineConfig,
    KVTieringConfig,
    LlamaConfig,
    PrefixCacheConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.prefix_cache import PrefixCache
from rag_llm_k8s_tpu.models.llama import (
    KVCache,
    apply_rope,
    init_llama_params,
    make_kv_cache,
    rope_cos_sin,
    rope_frequencies,
)
from rag_llm_k8s_tpu.ops.attention import (
    quantize_kv,
    rope_rerotate,
    rope_rerotate_q8,
)
from rag_llm_k8s_tpu.resilience import faults

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=6)
# Pinned logit tolerance for shifted splices on the RANDOM-INIT tiny model
# — deliberately looser than the warm tier's 0.15: SIFT's composition
# invariance is a property of trained attention (retrieved chunks attend
# mostly within themselves), and a random-init model is its worst case
# (measured 0.10–0.27 max-abs across seeds at boundary_tokens=4). The pin
# bounds REGRESSION drift; the bench leg's fixed stream pins 0.15.
LOGIT_TOL = 0.35

CHUNK_PC = PrefixCacheConfig(
    enabled=True, max_prefix_tokens=64, segment_buckets=(16,),
    suffix_buckets=(16,), hbm_budget_mb=64, reuse="chunk",
    boundary_tokens=4, chunk_hot_min=0.0,
)
EXACT_PC = dataclasses.replace(CHUNK_PC, reuse="exact")
EC = EngineConfig(
    prompt_buckets=(64, 128), max_batch_size=2, speculative="off",
    max_seq_len=256, prefix_cache=CHUNK_PC,
)
PAGED_EC = dataclasses.replace(EC, kv_paged=True, kv_block_size=16)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(vocab_size=128)
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    engine = InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=EC, dtypes=FP32
    )
    return cfg, params, engine


def _corpus(cfg, seed=3, chunk_len=16):
    """One block-aligned head + two block-aligned chunks + a suffix."""
    r = np.random.default_rng(seed)
    head = [int(cfg.bos_token_id)] + list(
        map(int, r.integers(3, 120, chunk_len - 1))
    )
    a = list(map(int, r.integers(3, 120, chunk_len)))
    b = list(map(int, r.integers(3, 120, chunk_len)))
    suffix = list(map(int, r.integers(3, 120, 6)))
    return head, a, b, suffix


def _last_logits_spliced(cfg, engine, cp, suffix, T=128, S_suf=16):
    """Last-token logits of suffix chunk-prefilled over the spliced cp."""
    n = cp.length + len(suffix)
    cache = make_kv_cache(cfg, 1, T, jnp.float32)
    planes = tuple(
        jax.lax.dynamic_update_slice(c, b, (0,) * c.ndim)
        for c, b in zip((cache.k, cache.v), cp.planes)
    )
    toks = np.zeros((1, S_suf), np.int32)
    toks[0, : len(suffix)] = suffix
    pos = (cp.length + jnp.arange(S_suf, dtype=jnp.int32))[None, :]
    lg, _ = engine.model_chunked.apply(
        {"params": engine.params}, jnp.asarray(toks), pos, KVCache(*planes),
        jnp.zeros((1,), jnp.int32), jnp.full((1,), n, jnp.int32),
        jnp.int32(cp.length), logit_index=jnp.int32(len(suffix) - 1),
    )
    return np.asarray(lg[0, -1])


def _last_logits_cold(cfg, engine, full, T=128):
    n = len(full)
    cache = make_kv_cache(cfg, 1, T, jnp.float32)
    lg, _ = engine.model.apply(
        {"params": engine.params},
        jnp.asarray(np.asarray(full, np.int32)[None, :]),
        jnp.arange(n, dtype=jnp.int32)[None, :], cache,
        jnp.zeros((1,), jnp.int32), jnp.full((1,), n, jnp.int32),
        jnp.int32(0), last_logit_only=True,
    )
    return np.asarray(lg[0, -1])


def _drain(eng, rid, fin):
    outs = {}
    while eng.has_active():
        for r, toks in eng.step():
            outs[r] = toks
    return fin if fin is not None else outs[rid]


def _planes_equal(p1, p2) -> bool:
    return all(
        np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(p1, p2)
    )


# ---------------------------------------------------------------------------
# the re-rotation op
# ---------------------------------------------------------------------------


class TestRerotateOp:
    def test_rerotate_matches_recompute_at_shifted_position(self):
        cfg = LlamaConfig.tiny()
        inv = rope_frequencies(cfg)
        r = np.random.default_rng(0)
        x = jnp.asarray(
            r.normal(size=(1, 5, 2, cfg.head_dim)).astype(np.float32)
        )
        pos = jnp.asarray(np.arange(5)[None, :])
        c0, s0 = rope_cos_sin(pos, inv)
        k_at = apply_rope(x, c0, s0)
        for delta in (1, 7, -3):
            c1, s1 = rope_cos_sin(pos + delta, inv)
            want = apply_rope(x, c1, s1)
            got = rope_rerotate(k_at, jnp.int32(delta), inv)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-5
            )

    def test_zero_delta_is_bit_exact_identity(self):
        cfg = LlamaConfig.tiny()
        inv = rope_frequencies(cfg)
        r = np.random.default_rng(1)
        k = jnp.asarray(
            r.normal(size=(2, 1, 2, 8, cfg.head_dim)).astype(np.float32)
        )
        out = rope_rerotate(k, jnp.int32(0), inv)
        assert np.array_equal(np.asarray(out), np.asarray(k))

    def test_q8_rerotate_round_trip_bounded(self):
        cfg = LlamaConfig.tiny()
        inv = rope_frequencies(cfg)
        r = np.random.default_rng(2)
        x = jnp.asarray(
            r.normal(size=(1, 5, 2, cfg.head_dim)).astype(np.float32)
        )
        pos = jnp.asarray(np.arange(5)[None, :])
        c0, s0 = rope_cos_sin(pos, inv)
        k_at = apply_rope(x, c0, s0)
        kq, ks = quantize_kv(k_at)
        rq, rs = rope_rerotate_q8(kq, ks, jnp.int32(7), inv)
        c1, s1 = rope_cos_sin(pos + 7, inv)
        want = np.asarray(apply_rope(x, c1, s1))
        deq = np.asarray(rq.astype(jnp.float32) * rs[..., None])
        # two quantization round trips: in + out, each max|x|/254 per elem
        bound = 2.0 * np.max(np.abs(want)) / 127.0 + 1e-6
        assert np.max(np.abs(deq - want)) <= bound


# ---------------------------------------------------------------------------
# one-shot substrate: the splice-buffer path
# ---------------------------------------------------------------------------


class TestChunkReuseCache:
    def test_shuffled_composition_within_logit_tolerance(self, setup):
        cfg, params, engine = setup
        cache = PrefixCache(CHUNK_PC, engine)
        head, a, b, suffix = _corpus(cfg)
        cache.prefix_for([("head", head), ("A", a), ("B", b)])
        cp = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        counts = cache.chunk_reuse_counters()
        assert counts["rerotated"] == 2 and counts["chain_exact"] == 1
        # the acceptance shape: most of the shuffled prefix's prefill
        # skipped (only the boundary windows recompute)
        assert cp.reused_tokens / (cp.reused_tokens + cp.computed_tokens) > 0.5
        ls = _last_logits_spliced(cfg, engine, cp, suffix)
        lc = _last_logits_cold(cfg, engine, head + b + a + suffix)
        assert np.max(np.abs(ls - lc)) <= LOGIT_TOL

    def test_first_resolve_is_bit_identical_to_exact_policy(self, setup):
        """A chain built fresh under reuse="chunk" must equal the
        reuse="exact" build byte-for-byte — chunk mode changes REUSE, not
        the miss path's computation."""
        cfg, params, engine = setup
        head, a, b, _ = _corpus(cfg, seed=11)
        segs = [("head", head), ("A", a), ("B", b)]
        cp_chunk = PrefixCache(CHUNK_PC, engine).prefix_for(segs)
        cp_exact = PrefixCache(EXACT_PC, engine).prefix_for(segs)
        assert _planes_equal(cp_chunk.planes, cp_exact.planes)

    def test_canonical_position_rehit_is_bit_identical(self, setup):
        """Same chain again (memo cleared): every segment serves
        chain_exact — no rotation, no fixup, identical buffer bytes."""
        cfg, params, engine = setup
        cache = PrefixCache(CHUNK_PC, engine)
        head, a, b, _ = _corpus(cfg, seed=12)
        segs = [("head", head), ("A", a), ("B", b)]
        cp1 = cache.prefix_for(segs)
        with cache._lock:
            cache._assembled.clear()
            cache._assembled_uses.clear()
            cache._assembled_stamp.clear()
            cache._assembled_spans.clear()
            cache.assembled_bytes = 0
        before = cache.chunk_reuse_counters()
        cp2 = cache.prefix_for(segs)
        after = cache.chunk_reuse_counters()
        assert after["chain_exact"] - before["chain_exact"] == 3
        assert after["rerotated"] == before["rerotated"]
        assert cp2.computed_tokens == 0
        assert _planes_equal(cp1.planes, cp2.planes)

    def test_cold_chunk_keeps_recompute_path(self, setup):
        """With the hotness gate above the stream's score, a shuffled
        composition recomputes instead of splicing — and is therefore
        bit-identical to the exact-policy cold build."""
        cfg, params, engine = setup
        gated = PrefixCache(
            dataclasses.replace(CHUNK_PC, chunk_hot_min=100.0), engine
        )
        head, a, b, _ = _corpus(cfg, seed=13)
        gated.prefix_for([("head", head), ("A", a), ("B", b)])
        cp = gated.prefix_for([("head", head), ("B", b), ("A", a)])
        counts = gated.chunk_reuse_counters()
        assert counts["rerotated"] == 0 and counts["spliced"] == 0
        cp_exact = PrefixCache(EXACT_PC, engine).prefix_for(
            [("head", head), ("B", b), ("A", a)]
        )
        assert _planes_equal(cp.planes, cp_exact.planes)

    def test_warm_tier_splice_within_tolerance(self, setup):
        """A warm (int8-quantized in place) chunk still splices at a
        shifted position: dequant → rotate → boundary-correct, within the
        same pinned tolerance."""
        cfg, params, engine = setup
        tiering = KVTieringConfig(
            enabled=True, warm_below=1e9, cold_below=0.0,
            half_life_s=60.0, retier_interval_s=3600.0, host_spill_mb=64,
        )
        cache = PrefixCache(
            dataclasses.replace(CHUNK_PC, chunk_hot_min=0.0),
            engine, tiering=tiering,
        )
        head, a, b, suffix = _corpus(cfg, seed=14)
        cache.prefix_for([("head", head), ("A", a), ("B", b)])
        assert cache.force_demote("warm") > 0
        cp = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        assert cache.chunk_reuse_counters()["rerotated"] == 2
        ls = _last_logits_spliced(cfg, engine, cp, suffix)
        lc = _last_logits_cold(cfg, engine, head + b + a + suffix)
        assert np.max(np.abs(ls - lc)) <= LOGIT_TOL

    def test_failed_swap_in_on_shifted_splice_counts_recompute(self, setup):
        """A cold entry whose swap-in FAILS while it was headed for a
        shifted splice is a recompute, not a splice: the rebuilt segment
        must not take the boundary-correction branch (reused/computed must
        still sum to the prefix total, outcomes all recompute)."""
        cfg, params, engine = setup
        tiering = KVTieringConfig(
            enabled=True, warm_below=0.0, cold_below=0.0,
            half_life_s=60.0, retier_interval_s=3600.0, host_spill_mb=64,
        )
        cache = PrefixCache(CHUNK_PC, engine, tiering=tiering)
        head, a, b, _ = _corpus(cfg, seed=16)
        cache.prefix_for([("head", head), ("A", a), ("B", b)])
        assert cache.force_demote("cold") == 3
        with cache._lock:
            cache._assembled.clear()
            cache._assembled_uses.clear()
            cache._assembled_stamp.clear()
            cache._assembled_spans.clear()
            cache.assembled_bytes = 0
        before = cache.chunk_reuse_counters()
        faults.clear()
        faults.arm("kv_swap_in", times=3)  # every segment's swap fails
        try:
            cp = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        finally:
            faults.clear()
        total = len(head) + len(a) + len(b)
        assert cp.reused_tokens == 0 and cp.computed_tokens == total
        after = cache.chunk_reuse_counters()
        assert after["recompute"] - before["recompute"] == 3
        assert after["rerotated"] == before["rerotated"]
        assert after["boundary_tokens"] == before["boundary_tokens"]

    def test_splice_fault_falls_back_to_recompute_zero_leak(self, setup):
        """Fault site chunk_splice: the shifted splice dies mid-flight —
        the chunk recomputes from tokens (bit-identical to a cold build),
        no entry is lost, and the cache's byte accounting stays exact."""
        cfg, params, engine = setup
        cache = PrefixCache(CHUNK_PC, engine)
        head, a, b, _ = _corpus(cfg, seed=15)
        cache.prefix_for([("head", head), ("A", a), ("B", b)])
        entries_before = len(cache._entries)
        faults.clear()
        faults.arm("chunk_splice", times=2)  # both shifted chunks
        try:
            cp = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        finally:
            faults.clear()
        counts = cache.chunk_reuse_counters()
        assert counts["splice_faults"] == 2
        assert counts["rerotated"] == 0
        assert len(cache._entries) == entries_before  # rebuilt in place
        assert cache.entry_bytes == sum(
            e.nbytes for e in cache._entries.values()
        )
        cp_exact = PrefixCache(EXACT_PC, engine).prefix_for(
            [("head", head), ("B", b), ("A", a)]
        )
        assert _planes_equal(cp.planes, cp_exact.planes)


# ---------------------------------------------------------------------------
# continuous paged substrate: per-chunk block-table assembly
# ---------------------------------------------------------------------------


class TestChunkReusePaged:
    @pytest.fixture()
    def paged(self, setup):
        cfg, params, engine = setup
        cont = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED_EC, dtypes=FP32
        )
        return cfg, engine, cont

    def test_assembly_matches_buffer_substrate_and_leaks_nothing(self, paged):
        """The pool-side gather + re-rotate + boundary re-prefill must
        reproduce the splice-buffer substrate's stream exactly (same math,
        same order on this platform), with every block accounted for."""
        cfg, engine, cont = paged
        cache = PrefixCache(CHUNK_PC, engine)
        head, a, b, suffix = _corpus(cfg, seed=21)
        cp1 = cache.prefix_for([("head", head), ("A", a), ("B", b)])
        _, fin = cont.admit_prefixed(1, suffix, cp1, max_new=6)
        _drain(cont, 1, fin)
        # the scatter admission registered per-chunk canonical pool copies
        assert set(cont._chunk_regs) == {"head", "A", "B"}

        cp2 = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        plan = cont._chunk_splice_plan(cp2)
        assert plan is not None and len(plan) == 3
        _, fin2 = cont.admit_prefixed(2, suffix, cp2, max_new=6)
        got = _drain(cont, 2, fin2)
        want = engine.generate_prefixed(suffix, cp2)
        assert got == want

        # zero leak: releasing every registration empties the pool
        for k in list(cont._chunk_regs):
            cont._drop_chunk_reg(k)
        for k in list(cont._prefix_blocks):
            cont._drop_registration(k)
        assert cont.kv_pool.blocks_in_use() == 0
        assert cont._chunk_reg_tokens == 0

    def test_stale_stamp_declines_the_plan(self, paged):
        """A chunk entry rebuilt in the cache (new creation stamp) must
        not serve from its stale pool registration — the plan declines and
        the admission scatters the fresh buffer."""
        cfg, engine, cont = paged
        cache = PrefixCache(CHUNK_PC, engine)
        head, a, b, suffix = _corpus(cfg, seed=22)
        cp1 = cache.prefix_for([("head", head), ("A", a), ("B", b)])
        _, fin = cont.admit_prefixed(3, suffix, cp1, max_new=6)
        _drain(cont, 3, fin)
        assert "A" in cont._chunk_regs
        # rebuild A's entry: the canonical content changes generation
        with cache._lock:
            cache._entries.pop(("A",))
            cache.entry_bytes = sum(
                e.nbytes for e in cache._entries.values()
            )
            cache._assembled.clear()
            cache._assembled_uses.clear()
            cache._assembled_stamp.clear()
            cache._assembled_spans.clear()
            cache.assembled_bytes = 0
        cp2 = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        assert cont._chunk_splice_plan(cp2) is None

    def test_paged_splice_fault_falls_back_to_scatter_zero_leak(self, paged):
        """Armed chunk_splice pool-side: the plan declines BEFORE any
        allocation, the admission takes the buffer-scatter path, and the
        stream/accounting are unchanged."""
        cfg, engine, cont = paged
        cache = PrefixCache(CHUNK_PC, engine)
        head, a, b, suffix = _corpus(cfg, seed=23)
        cp1 = cache.prefix_for([("head", head), ("A", a), ("B", b)])
        _, fin = cont.admit_prefixed(4, suffix, cp1, max_new=6)
        _drain(cont, 4, fin)
        in_use_before = cont.kv_pool.blocks_in_use()
        cp2 = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        faults.clear()
        faults.arm("chunk_splice", times=1)
        try:
            _, fin2 = cont.admit_prefixed(5, suffix, cp2, max_new=6)
            got = _drain(cont, 5, fin2)
        finally:
            faults.clear()
        want = engine.generate_prefixed(suffix, cp2)
        assert got == want  # the scatter path serves the same buffer
        assert cont.kv_pool.blocks_in_use() >= in_use_before  # regs only
        for k in list(cont._chunk_regs):
            cont._drop_chunk_reg(k)
        for k in list(cont._prefix_blocks):
            cont._drop_registration(k)
        assert cont.kv_pool.blocks_in_use() == 0


# ---------------------------------------------------------------------------
# tp=2 under the serving specs
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 (virtual) devices for tp=2"
)
class TestChunkReuseTP2:
    def test_tp2_assembly_matches_tp1(self, setup):
        """The chunk-splice executable over the head-sharded arena: a tp=2
        per-chunk assembled admission streams identically to tp=1."""
        from rag_llm_k8s_tpu.core.config import MeshConfig
        from rag_llm_k8s_tpu.core.mesh import make_mesh
        from rag_llm_k8s_tpu.parallel.sharding import shard_llama_params

        cfg, params, engine = setup
        head, a, b, suffix = _corpus(cfg, seed=31)

        def run(cont, cache):
            cp1 = cache.prefix_for([("head", head), ("A", a), ("B", b)])
            _, fin = cont.admit_prefixed(1, suffix, cp1, max_new=6)
            _drain(cont, 1, fin)
            cp2 = cache.prefix_for([("head", head), ("B", b), ("A", a)])
            assert cont._chunk_splice_plan(cp2) is not None
            _, fin2 = cont.admit_prefixed(2, suffix, cp2, max_new=6)
            out = _drain(cont, 2, fin2)
            for k in list(cont._chunk_regs):
                cont._drop_chunk_reg(k)
            for k in list(cont._prefix_blocks):
                cont._drop_registration(k)
            assert cont.kv_pool.blocks_in_use() == 0
            return out

        cont1 = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED_EC, dtypes=FP32
        )
        want = run(cont1, PrefixCache(CHUNK_PC, engine))

        ctx = make_mesh(MeshConfig(dp=4, sp=1, tp=2))
        placed = shard_llama_params(params, ctx)
        cont2 = ContinuousEngine(
            cfg, placed, sampling=GREEDY, engine_config=PAGED_EC,
            dtypes=FP32, mesh=ctx,
        )
        shard = cont2._cache[0].addressable_shards[0].data.shape
        assert shard[2] == cfg.num_kv_heads // ctx.tp, shard
        got = run(cont2, PrefixCache(CHUNK_PC, engine))
        assert got == want


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


class TestChunkReuseConfig:
    def test_env_round_trip(self):
        c = AppConfig.from_env({
            "TPU_RAG_PREFIX_REUSE": "chunk",
            "TPU_RAG_PREFIX_BOUNDARY_TOKENS": "8",
            "TPU_RAG_PREFIX_CHUNK_HOT_MIN": "1.5",
            "TPU_RAG_PREFIX_CHUNK_POOL_REGS": "8",
        })
        pc = c.engine.prefix_cache
        assert pc.reuse == "chunk"
        assert pc.boundary_tokens == 8
        assert pc.chunk_hot_min == 1.5
        assert pc.chunk_pool_regs == 8
        assert AppConfig.from_env({}).engine.prefix_cache.reuse == "exact"

    def test_env_validation(self):
        for bad in (
            {"TPU_RAG_PREFIX_REUSE": "fuzzy"},
            {"TPU_RAG_PREFIX_BOUNDARY_TOKENS": "-1"},
            {"TPU_RAG_PREFIX_CHUNK_HOT_MIN": "-0.5"},
            {"TPU_RAG_PREFIX_CHUNK_POOL_REGS": "0"},
        ):
            with pytest.raises(ValueError):
                AppConfig.from_env(bad)

    def test_bad_policy_rejected_at_construction(self, setup):
        cfg, params, engine = setup
        with pytest.raises(ValueError):
            PrefixCache(
                dataclasses.replace(CHUNK_PC, reuse="fuzzy"), engine
            )


# ---------------------------------------------------------------------------
# smoke (the `make splice-smoke` lane)
# ---------------------------------------------------------------------------


class TestSmoke:
    def test_shuffled_composition_both_substrates(self, setup):
        """The acceptance shape end to end on the tiny config: a permuted
        composition serves mostly from cache (>50% prefill skipped) within
        the pinned logit tolerance, on the splice-buffer substrate and the
        paged per-chunk assembly, with zero leaked blocks."""
        cfg, params, engine = setup
        cache = PrefixCache(CHUNK_PC, engine)
        head, a, b, suffix = _corpus(cfg, seed=41)
        cp1 = cache.prefix_for([("head", head), ("A", a), ("B", b)])
        cp2 = cache.prefix_for([("head", head), ("B", b), ("A", a)])
        assert (
            cp2.reused_tokens / (cp2.reused_tokens + cp2.computed_tokens)
            > 0.5
        )
        ls = _last_logits_spliced(cfg, engine, cp2, suffix)
        lc = _last_logits_cold(cfg, engine, head + b + a + suffix)
        assert np.max(np.abs(ls - lc)) <= LOGIT_TOL

        cont = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED_EC, dtypes=FP32
        )
        _, fin = cont.admit_prefixed(1, suffix, cp1, max_new=6)
        _drain(cont, 1, fin)
        _, fin2 = cont.admit_prefixed(2, suffix, cp2, max_new=6)
        got = _drain(cont, 2, fin2)
        assert got == engine.generate_prefixed(suffix, cp2)
        for k in list(cont._chunk_regs):
            cont._drop_chunk_reg(k)
        for k in list(cont._prefix_blocks):
            cont._drop_registration(k)
        assert cont.kv_pool.blocks_in_use() == 0
