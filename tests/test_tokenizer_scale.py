"""Tokenizer parity + throughput at TRUE scale (VERDICT r3 #6): a 128k-vocab
byte-level BPE (Llama-3's size, download_model.py:5) and a 250k-piece Unigram
(bge-m3/XLM-R's size, rag.py:33), generated from the environment's own
sources with the live Rust ``tokenizers`` engine (tests/fixtures/
gen_tokenizers.py --scale; cached, gitignored — ~40 s first run).

Scale-dependent behavior the toy fixtures cannot catch: deep trie walks over
quarter-million-piece vocabs, merge-rank tables at 128k, id ranges past
2^16, score spreads that expose a wrong unk penalty, and throughput."""

import os
import subprocess
import sys
import time

import pytest

tokenizers = pytest.importorskip("tokenizers")

from tokenizers import Tokenizer  # noqa: E402

from rag_llm_k8s_tpu.tokenizer import load_tokenizer  # noqa: E402

SCALE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "tokenizers_scale")


@pytest.fixture(scope="module")
def scale_dir():
    bpe = os.path.join(SCALE_DIR, "bpe_128k.json")
    uni = os.path.join(SCALE_DIR, "unigram_250k.json")
    if not (os.path.exists(bpe) and os.path.exists(uni)):
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "fixtures", "gen_tokenizers.py"),
             "--scale"],
            check=True, timeout=600,
        )
    return SCALE_DIR


SAMPLES = [
    "The Technology Radar is a snapshot of tools, techniques and platforms.",
    "def chunk_prefill_attention_q8(q, k_cache, v_cache, k_scale, v_scale):",
    "import jax.numpy as jnp  # bfloat16 matmuls ride the MXU",
    "punctuation!!! and... spaces   here\ttabs\nnewlines",
    "self._fused_retrieve[(S, emb.shape[0], k_eff, B_pad)] = fn",
    "기술 레이더는 도구, 기법, 플랫폼의 스냅샷입니다.",  # OOV-heavy for a code corpus
    "日本語のテキストも正しく分割されるべきです。",
    "café naïve über résumé — ça va? 🚀",
    "",
    "x",
]


class TestScaleBPE:
    @pytest.fixture(scope="class")
    def pair(self, scale_dir):
        path = os.path.join(scale_dir, "bpe_128k.json")
        return Tokenizer.from_file(path), load_tokenizer(path)

    def test_vocab_is_llama3_scale(self, pair):
        rust, ours = pair
        assert rust.get_vocab_size() == 128000
        assert ours.vocab_size == 128000

    @pytest.mark.parametrize("text", SAMPLES)
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text) == rust.encode(text).ids

    def test_long_document_matches_rust(self, pair):
        rust, ours = pair
        doc = open(__file__, encoding="utf-8").read() * 3
        got, want = ours.encode(doc), rust.encode(doc).ids
        assert got == want
        assert max(want) > 1 << 16, "128k vocab never exercised ids past 2^16"

    def test_roundtrip(self, pair):
        _, ours = pair
        text = "high-vocab round trip — ども 🚀 café"
        assert ours.decode(ours.encode(text)) == text


class TestScaleUnigram:
    @pytest.fixture(scope="class")
    def pair(self, scale_dir):
        path = os.path.join(scale_dir, "unigram_250k.json")
        return Tokenizer.from_file(path), load_tokenizer(path)

    def test_vocab_is_xlmr_scale(self, pair):
        rust, ours = pair
        assert rust.get_vocab_size() == 250000
        assert ours.vocab_size == 250000

    def test_unk_score_derived_from_spec(self, pair):
        _, ours = pair
        worst = min(s for _, s in ours.pieces)
        assert ours.unk_score == pytest.approx(worst - 10.0)
        assert ours.unk_score != -20.0  # the round-3 hardcode would be wrong here

    @pytest.mark.parametrize("text", SAMPLES)
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text, add_special=False) == rust.encode(text).ids

    def test_oov_heavy_matches_rust(self, pair):
        """Multilingual OOV runs: segmentation depends on the unk score
        relative to the spec's score spread — exactly what a hardcoded
        penalty gets wrong on a 250k-piece vocab."""
        rust, ours = pair
        text = "ψψφ мир 你好世界 ψ mixed_with known_words"
        assert ours.encode(text, add_special=False) == rust.encode(text).ids


class TestScaleThroughput:
    """Throughput on a ~1 MB document, ours vs the Rust engine. The figures
    print into the test log (the perf record); the floors only guard against
    pathological regressions (e.g. accidental O(n^2))."""

    DOC_MB = 1.0

    def _doc(self):
        import glob

        parts, total = [], 0
        for p in sorted(glob.glob(os.path.join(
                os.path.dirname(__file__), "..", "rag_llm_k8s_tpu", "**", "*.py"),
                recursive=True)):
            with open(p, encoding="utf-8") as f:
                t = f.read()
            parts.append(t)
            total += len(t)
        doc = "\n".join(parts)
        while len(doc) < self.DOC_MB * 1e6:
            doc += doc
        return doc[: int(self.DOC_MB * 1e6)]

    def _rate(self, fn, doc):
        t0 = time.monotonic()
        fn(doc)
        return len(doc) / 1e6 / (time.monotonic() - t0)

    def test_bpe_throughput(self, scale_dir):
        path = os.path.join(scale_dir, "bpe_128k.json")
        rust, ours = Tokenizer.from_file(path), load_tokenizer(path)
        doc = self._doc()
        r_rust = self._rate(lambda d: rust.encode(d).ids, doc)
        r_ours = self._rate(ours.encode, doc)
        print(f"\nbpe-128k throughput MB/s: ours={r_ours:.2f} rust={r_rust:.2f} "
              f"ratio={r_ours / r_rust:.2f}")
        assert r_ours > 0.2, f"BPE encode collapsed to {r_ours:.3f} MB/s"

    def test_unigram_throughput(self, scale_dir):
        path = os.path.join(scale_dir, "unigram_250k.json")
        rust, ours = Tokenizer.from_file(path), load_tokenizer(path)
        doc = self._doc()[: int(0.25e6)]  # pure-Python Viterbi: keep CI sane
        r_rust = self._rate(lambda d: rust.encode(d).ids, doc)
        r_ours = self._rate(lambda d: ours.encode(d, add_special=False), doc)
        print(f"\nunigram-250k throughput MB/s: ours={r_ours:.2f} rust={r_rust:.2f} "
              f"ratio={r_ours / r_rust:.2f}")
        assert r_ours > 0.02, f"Unigram encode collapsed to {r_ours:.3f} MB/s"
