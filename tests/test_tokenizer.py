"""Tokenizer parity tests against the HF Rust ``tokenizers`` library (the
exact engine the reference uses via AutoTokenizer, rag.py:25): load the
committed ``tokenizer.json`` fixtures (tests/fixtures/tokenizers/, generated
once by tests/fixtures/gen_tokenizers.py — training fresh each run is
nondeterministic and float-tie flaky), reload them with the framework's
pure-Python implementations, and compare token ids exactly."""

import os

import pytest

tokenizers = pytest.importorskip("tokenizers")

from tokenizers import Tokenizer  # noqa: E402

from rag_llm_k8s_tpu.tokenizer import load_tokenizer  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "tokenizers")


def fixture_pair(name):
    path = os.path.join(FIXTURES, name)
    return Tokenizer.from_file(path), load_tokenizer(path)


SAMPLES = [
    "The Technology Radar improves tools and platforms.",
    "hello hello world 123",
    "def f(x): return x+1",
    "punctuation!!! and... spaces   here",
    "",
    "a",
]

NON_ASCII_SAMPLES = [
    "기술 레이더는 도구의 스냅샷입니다.",  # Korean (the reference corpus language)
    "日本語のテキストも分割されるべきです。",
    "café naïve über résumé — ça va?",
    "cafe\u0301 composed",  # NFD input: e+combining-acute must compose first
    "ＦＵＬＬｗｉｄｔｈ１２３",  # fullwidth forms fold to ASCII under NFKC
    "nbsp\xa0and em-space",  # unicode spaces normalize to plain space
    "emoji 🚀 test",
    "ψψφ consecutive unknowns ψ",  # runs of OOV chars must fuse to one <unk>
]

# literal special-token strings inside ordinary text: HF extracts them
# before normalization (AddedVocabulary), and unk-fusing must not swallow a
# real '<unk>' match into an adjacent OOV run
SPECIAL_IN_TEXT_SAMPLES = ["ψ<unk>ψ", "a <s> b", "<s>hello</s>", "<unk><unk>"]


class TestBPEParity:
    @pytest.fixture(scope="class")
    def pair(self):
        return fixture_pair("bpe_ascii.json")

    @pytest.mark.parametrize("text", SAMPLES)
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text) == rust.encode(text).ids

    @pytest.mark.parametrize("text", SAMPLES)
    def test_decode_roundtrip(self, pair, text):
        rust, ours = pair
        ids = ours.encode(text)
        assert ours.decode(ids) == rust.decode(ids)

    def test_special_tokens_split(self, pair):
        rust, ours = pair
        text = "<|begin_of_text|>hello world<|end_of_text|>"
        got = ours.encode(text)
        assert got[0] == ours.special_tokens["<|begin_of_text|>"]
        assert got[-1] == ours.special_tokens["<|end_of_text|>"]
        # interior matches rust's encoding of the plain text
        assert got[1:-1] == rust.encode("hello world").ids


class TestBPENonAscii:
    """Byte-level BPE must byte-fall-back through any unicode input with ids
    identical to the Rust engine (exact \\p{L}/\\p{N} splitting via `regex`)."""

    @pytest.fixture(scope="class")
    def pair(self):
        return fixture_pair("bpe_multi.json")

    @pytest.mark.parametrize("text", NON_ASCII_SAMPLES)
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text) == rust.encode(text).ids

    @pytest.mark.parametrize("text", NON_ASCII_SAMPLES)
    def test_decode_roundtrip(self, pair, text):
        rust, ours = pair
        ids = ours.encode(text)
        assert ours.decode(ids) == rust.decode(ids) == text


class TestUnigramParity:
    @pytest.fixture(scope="class")
    def pair(self):
        return fixture_pair("unigram_plain.json")

    @pytest.mark.parametrize("text", [s for s in SAMPLES if s])
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text, add_special=False) == rust.encode(text).ids

    @pytest.mark.parametrize(
        "text",
        [
            "The Technology Radar improves tools and platforms.",
            "hello world 123",
            "naive tokenization tests, with punctuation...",
        ],
    )
    def test_decode_roundtrip_covered_text(self, pair, text):
        """For in-vocabulary text, decode(encode(x)) == x (modulo whitespace
        normalization). OOV chars map to <unk> and are lossy by design."""
        _, ours = pair
        ids = ours.encode(text, add_special=False)
        assert ours.decode(ids).split() == text.split()

    def test_oov_degrades_to_unk(self, pair):
        _, ours = pair
        ids = ours.encode("x+1", add_special=False)
        assert ours.unk_id in ids  # '+' is not in the trained vocab


class TestUnigramNormalizedParity:
    """Parity with a normalizer in the pipeline (bge-m3's tokenizer.json
    carries a Precompiled charsmap ≈ NFKC + whitespace folding; the HF
    trainer can't emit Precompiled, so the equivalent declarative chain
    stands in for it)."""

    @pytest.fixture(scope="class")
    def pair(self):
        return fixture_pair("unigram_norm.json")

    @pytest.mark.parametrize("text", NON_ASCII_SAMPLES)
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text, add_special=False) == rust.encode(text).ids

    @pytest.mark.parametrize("text", [s for s in SAMPLES if s])
    def test_ascii_still_matches(self, pair, text):
        rust, ours = pair
        assert ours.encode(text, add_special=False) == rust.encode(text).ids

    @pytest.mark.parametrize("text", SPECIAL_IN_TEXT_SAMPLES)
    def test_literal_special_tokens_in_text(self, pair, text):
        rust, ours = pair
        assert ours.encode(text, add_special=False) == rust.encode(text).ids


class TestNmtNfkc:
    """Unit behavior of the reimplemented SentencePiece nmt_nfkc rules (what
    bge-m3's Precompiled charsmap encodes)."""

    def test_unicode_spaces_fold(self):
        from rag_llm_k8s_tpu.tokenizer.normalize import nmt_nfkc

        assert nmt_nfkc("a\xa0b　c d") == "a b c d"

    def test_controls_and_zero_width_dropped(self):
        from rag_llm_k8s_tpu.tokenizer.normalize import nmt_nfkc

        assert nmt_nfkc("a\x07b​c﻿d") == "abcd"

    def test_nfkc_folds_fullwidth_and_composes(self):
        from rag_llm_k8s_tpu.tokenizer.normalize import nmt_nfkc

        assert nmt_nfkc("ＡＢＣ１２３") == "ABC123"
        assert nmt_nfkc("café") == "café"

    def test_whitespace_runs_collapse_and_strip(self):
        from rag_llm_k8s_tpu.tokenizer.normalize import nmt_nfkc

        assert nmt_nfkc("  a \t b\n\nc  ") == "a b c"

    def test_precompiled_spec_applies_charsmap_rules(self):
        """Precompiled is a PER-CHARACTER map: separators fold and NFKC
        applies, but runs are NOT collapsed and ends are NOT stripped (the
        real bge-m3 spec adds a separate Replace node for collapsing)."""
        from rag_llm_k8s_tpu.tokenizer.normalize import normalizer_from_spec

        fn = normalizer_from_spec({"type": "Precompiled", "precompiled_charsmap": "x"})
        assert fn("hello\n") == "hello "  # trailing separator kept (as space)
        assert fn(" a\xa0 b") == " a  b"  # no strip, no run collapse
        assert fn("ＡＢＣ") == "ABC"

    def test_replace_content_is_literal(self):
        """HF substitutes Replace `content` literally — backslashes are not
        template escapes or group references."""
        from rag_llm_k8s_tpu.tokenizer.normalize import normalizer_from_spec

        fn = normalizer_from_spec(
            {"type": "Replace", "pattern": {"Regex": "(x)"}, "content": "a\\b"}
        )
        assert fn("x") == "a\\b"

    def test_korean_text_survives(self):
        from rag_llm_k8s_tpu.tokenizer.normalize import nmt_nfkc

        assert nmt_nfkc("기술 레이더") == "기술 레이더"


class TestNativeBPE:
    def test_native_matches_python(self):
        """The C++ merge loop must produce identical ids to the Python path."""
        rust, ours = fixture_pair("bpe_multi.json")
        if ours._native is None:
            pytest.skip("no C++ toolchain in this environment")
        for text in SAMPLES + NON_ASCII_SAMPLES + ["x" * 500]:
            native_ids = ours.encode(text)
            nat = ours._native
            ours._native = None
            ours._cache.clear()
            try:
                python_ids = ours.encode(text)
            finally:
                ours._native = nat
            assert native_ids == python_ids, text
            assert native_ids == rust.encode(text).ids, text


class TestMetaspacePrependFirst:
    """HF's prepend_scheme="first" (newer SPM exports): only the input's
    FIRST segment gets the ▁ marker — segments after a special token do
    not. Parity is checked against the live Rust engine on a tokenizer
    built in-test (deterministic vocab, no training)."""

    @pytest.fixture(scope="class")
    def pair(self, tmp_path_factory):
        import json as _json

        from tokenizers import Tokenizer as RustTokenizer
        from tokenizers.models import Unigram as RustUnigram
        from tokenizers.pre_tokenizers import Metaspace as RustMetaspace

        vocab = [("<unk>", 0.0)] + [
            (p, -float(i + 1))
            for i, p in enumerate(
                ["▁", "▁hello", "▁world", "hello", "world",
                 "▁h", "e", "l", "o", "w", "r", "d", "h"]
            )
        ]
        rust = RustTokenizer(RustUnigram(vocab, unk_id=0, byte_fallback=False))
        rust.pre_tokenizer = RustMetaspace(prepend_scheme="first")
        rust.add_special_tokens(["<sep>"])
        path = str(tmp_path_factory.mktemp("tok") / "tokenizer.json")
        rust.save(path)
        # sanity: the saved spec really carries the "first" scheme
        with open(path) as f:
            spec = _json.load(f)
        assert spec["pre_tokenizer"]["prepend_scheme"] == "first"
        ours = load_tokenizer(path)
        ours.normalize = lambda s: s  # rust side has no normalizer here
        return rust, ours

    @pytest.mark.parametrize(
        "text",
        [
            "hello world",
            "hello <sep> world",          # post-special segment: NO marker
            "hello <sep> world <sep> hello",
            "<sep> hello",                # first segment empty
        ],
    )
    def test_parity_with_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text, add_special=False) == rust.encode(text).ids
