"""Tokenizer parity tests against the HF Rust ``tokenizers`` library (the
exact engine the reference uses via AutoTokenizer, rag.py:25): train a small
byte-level BPE / Unigram model, save tokenizer.json, reload with the
framework's pure-Python implementations, and compare token ids exactly."""

import pytest

tokenizers = pytest.importorskip("tokenizers")

from tokenizers import Tokenizer  # noqa: E402
from tokenizers.models import BPE, Unigram  # noqa: E402
from tokenizers.pre_tokenizers import ByteLevel, Metaspace  # noqa: E402
from tokenizers.decoders import ByteLevel as ByteLevelDecoder  # noqa: E402
from tokenizers.trainers import BpeTrainer, UnigramTrainer  # noqa: E402

from rag_llm_k8s_tpu.tokenizer import load_tokenizer  # noqa: E402

CORPUS = [
    "The Technology Radar is a snapshot of tools, techniques, platforms and languages.",
    "Retrieval-augmented generation improves factuality of large language models.",
    "TPU v5e slices communicate over ICI links; XLA emits the collectives.",
    "def split_text(text, chunk_size=1000, overlap=200):",
    "Hello world! 12345 -- naive tokenization tests, with punctuation...",
    "Multilingual text: cafe, uber, naive.",
] * 8

SAMPLES = [
    "The Technology Radar improves tools and platforms.",
    "hello hello world 123",
    "def f(x): return x+1",
    "punctuation!!! and... spaces   here",
    "",
    "a",
]


class TestBPEParity:
    @pytest.fixture(scope="class")
    def pair(self, tmp_path_factory):
        tok = Tokenizer(BPE(unk_token=None))
        tok.pre_tokenizer = ByteLevel(add_prefix_space=False, use_regex=True)
        tok.decoder = ByteLevelDecoder()
        trainer = BpeTrainer(
            vocab_size=400,
            special_tokens=["<|begin_of_text|>", "<|end_of_text|>"],
            initial_alphabet=ByteLevel.alphabet(),
            show_progress=False,
        )
        tok.train_from_iterator(CORPUS, trainer)
        p = tmp_path_factory.mktemp("bpe") / "tokenizer.json"
        tok.save(str(p))
        return tok, load_tokenizer(str(p))

    @pytest.mark.parametrize("text", SAMPLES)
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text) == rust.encode(text).ids

    @pytest.mark.parametrize("text", SAMPLES)
    def test_decode_roundtrip(self, pair, text):
        rust, ours = pair
        ids = ours.encode(text)
        assert ours.decode(ids) == rust.decode(ids)

    def test_special_tokens_split(self, pair):
        rust, ours = pair
        text = "<|begin_of_text|>hello world<|end_of_text|>"
        got = ours.encode(text)
        assert got[0] == ours.special_tokens["<|begin_of_text|>"]
        assert got[-1] == ours.special_tokens["<|end_of_text|>"]
        # interior matches rust's encoding of the plain text
        assert got[1:-1] == rust.encode("hello world").ids


class TestUnigramParity:
    @pytest.fixture(scope="class")
    def pair(self, tmp_path_factory):
        tok = Tokenizer(Unigram())
        tok.pre_tokenizer = Metaspace()
        trainer = UnigramTrainer(
            vocab_size=300,
            special_tokens=["<s>", "</s>", "<unk>"],
            unk_token="<unk>",
            show_progress=False,
        )
        tok.train_from_iterator(CORPUS, trainer)
        p = tmp_path_factory.mktemp("uni") / "tokenizer.json"
        tok.save(str(p))
        return tok, load_tokenizer(str(p))

    @pytest.mark.parametrize("text", [s for s in SAMPLES if s])
    def test_encode_matches_rust(self, pair, text):
        rust, ours = pair
        assert ours.encode(text, add_special=False) == rust.encode(text).ids

    @pytest.mark.parametrize(
        "text",
        [
            "The Technology Radar improves tools and platforms.",
            "hello world 123",
            "naive tokenization tests, with punctuation...",
        ],
    )
    def test_decode_roundtrip_covered_text(self, pair, text):
        """For in-vocabulary text, decode(encode(x)) == x (modulo whitespace
        normalization). OOV chars map to <unk> and are lossy by design."""
        _, ours = pair
        ids = ours.encode(text, add_special=False)
        assert ours.decode(ids).split() == text.split()

    def test_oov_degrades_to_unk(self, pair):
        _, ours = pair
        ids = ours.encode("x+1", add_special=False)
        assert ours.unk_id in ids  # '+' is not in the trained vocab


class TestNativeBPE:
    def test_native_matches_python(self, tmp_path):
        """The C++ merge loop must produce identical ids to the Python path."""
        from tokenizers import Tokenizer
        from tokenizers.models import BPE
        from tokenizers.pre_tokenizers import ByteLevel
        from tokenizers.trainers import BpeTrainer

        tok = Tokenizer(BPE(unk_token=None))
        tok.pre_tokenizer = ByteLevel(add_prefix_space=False, use_regex=True)
        tok.train_from_iterator(
            CORPUS,
            BpeTrainer(
                vocab_size=300,
                initial_alphabet=ByteLevel.alphabet(),
                show_progress=False,
            ),
        )
        p = tmp_path / "tokenizer.json"
        tok.save(str(p))
        ours = load_tokenizer(str(p))
        if ours._native is None:
            pytest.skip("no C++ toolchain in this environment")
        for text in SAMPLES + ["unicode: café — naïve", "x" * 500]:
            native_ids = ours.encode(text)
            nat = ours._native
            ours._native = None
            ours._cache.clear()
            try:
                python_ids = ours.encode(text)
            finally:
                ours._native = nat
            assert native_ids == python_ids, text
            assert native_ids == tok.encode(text).ids, text
