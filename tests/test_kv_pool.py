"""Paged KV cache (ISSUE 5): allocator semantics, paged↔dense greedy
parity, kernel↔oracle parity, block accounting under eviction / reset /
preemption, and block-granular prefix reuse.

The load-bearing contract is BYTE-IDENTICAL greedy token streams between
the paged and dense continuous engines on mixed-length batches — the paged
layout changes WHERE KV lives (pool blocks via per-row tables, right-padded
logical positions) but not a single attended value. Every other test here
is bookkeeping: blocks must flow back to the free list on every exit path
(retire, first-token EOS, eviction, preemption, EngineStateLost reset), or
the pool leaks toward permanent backpressure.
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    PrefixCacheConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.engine.kv_pool import KVBlockPool, NULL_BLOCK, PoolExhausted
from rag_llm_k8s_tpu.models.llama import init_llama_params

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
ENG = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64)
PAGED = dataclasses.replace(ENG, kv_paged=True, kv_block_size=16)
PROMPTS = [[3, 17, 42, 7, 99], [5, 5, 8], [11] * 12, [2, 9]]


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    oracle = InferenceEngine(
        cfg, params, sampling=GREEDY, engine_config=ENG, dtypes=FP32
    )
    return cfg, params, oracle


def paged_engine(cfg, params, eng_cfg=PAGED, sampling=GREEDY):
    return ContinuousEngine(
        cfg, params, sampling=sampling, engine_config=eng_cfg, dtypes=FP32
    )


def drain(eng, reqs):
    """admit_many + step-to-completion → {rid: tokens}."""
    results = {}
    outs = eng.admit_many([(rid, p, mn, None) for rid, p, mn in reqs])
    for (rid, _, _), res in zip(reqs, outs):
        if isinstance(res, BaseException):
            raise res
        _, fin = res
        if fin is not None:
            results[rid] = fin
    for _ in range(300):
        for rid, toks in eng.step():
            results[rid] = toks
        if not eng.has_active():
            break
    return results


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------


class TestKVBlockPool:
    def test_alloc_free_refcount(self):
        pool = KVBlockPool(9, 16)  # 8 usable
        assert pool.usable_blocks() == 8
        ids = pool.alloc(3)
        assert len(ids) == 3 and NULL_BLOCK not in ids
        assert pool.blocks_in_use() == 3
        pool.ref(ids[:1])
        assert pool.free(ids) == 2  # the ref'd block survives
        assert pool.blocks_in_use() == 1
        assert pool.free(ids[:1]) == 1
        assert pool.blocks_in_use() == 0

    def test_alloc_is_all_or_nothing(self):
        pool = KVBlockPool(5, 16)  # 4 usable
        pool.alloc(3)
        with pytest.raises(PoolExhausted):
            pool.alloc(2)
        assert pool.available() == 1  # the failed alloc took nothing

    def test_double_free_and_foreign_ref_are_loud(self):
        pool = KVBlockPool(5, 16)
        (b,) = pool.alloc(1)
        pool.free([b])
        with pytest.raises(ValueError):
            pool.free([b])
        with pytest.raises(ValueError):
            pool.ref([b])

    def test_blocks_for_and_fragmentation(self):
        pool = KVBlockPool(17, 16)
        assert pool.blocks_for(1) == 1
        assert pool.blocks_for(16) == 1
        assert pool.blocks_for(17) == 2
        pool.alloc(2)  # 32 slots
        assert pool.fragmentation(24) == pytest.approx(0.25)
        assert pool.fragmentation(0) == 1.0

    def test_reset_reclaims_everything(self):
        pool = KVBlockPool(9, 16)
        ids = pool.alloc(5)
        pool.ref(ids)  # even multiply-referenced blocks
        pool.reset()
        assert pool.blocks_in_use() == 0
        assert len(pool.alloc(8)) == 8


# ---------------------------------------------------------------------------
# engine parity + accounting
# ---------------------------------------------------------------------------


class TestPagedDenseParity:
    def test_mixed_length_greedy_parity(self, setup):
        """THE acceptance contract: byte-identical greedy streams across
        paged/dense on a mixed-length batch."""
        cfg, params, oracle = setup
        want = {i: oracle.generate([p])[0] for i, p in enumerate(PROMPTS)}
        dense = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=ENG, dtypes=FP32
        )
        paged = paged_engine(cfg, params)
        reqs = [(i, p, GREEDY.max_new_tokens) for i, p in enumerate(PROMPTS)]
        assert drain(dense, reqs) == want
        assert drain(paged, reqs) == want
        assert paged.kv_pool.blocks_in_use() == 0  # all returned at retire

    def test_mid_generation_admission_parity(self, setup):
        cfg, params, oracle = setup
        p1, p2 = PROMPTS[0], PROMPTS[1]
        want1, want2 = oracle.generate([p1])[0], oracle.generate([p2])[0]
        eng = paged_engine(cfg, params)
        eng.admit(1, p1, GREEDY.max_new_tokens)
        results = {}
        for _ in range(3):
            for rid, toks in eng.step():
                results[rid] = toks
        eng.admit(2, p2, GREEDY.max_new_tokens)  # joins mid-flight
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results == {1: want1, 2: want2}
        assert eng.kv_pool.blocks_in_use() == 0

    def test_multi_step_sync_parity(self, setup):
        """k-step windows over the paged arena: same stream as dense k=1."""
        cfg, params, oracle = setup
        want = {i: oracle.generate([p])[0] for i, p in enumerate(PROMPTS)}
        eng = paged_engine(
            cfg, params, dataclasses.replace(PAGED, decode_sync_steps=4)
        )
        got = drain(eng, [(i, p, GREEDY.max_new_tokens) for i, p in enumerate(PROMPTS)])
        assert got == want
        assert eng.kv_pool.blocks_in_use() == 0

    def test_int8_kv_paged_matches_dense(self, setup):
        cfg, params, _ = setup
        eng8 = dataclasses.replace(
            ENG, prompt_buckets=(32,), kv_quant="int8"
        )
        paged8 = dataclasses.replace(
            eng8, kv_paged=True, kv_block_size=32
        )
        reqs = [(i, p, 8) for i, p in enumerate(PROMPTS[:2])]
        d = drain(ContinuousEngine(cfg, params, sampling=GREEDY,
                                   engine_config=eng8, dtypes=FP32), reqs)
        p = drain(paged_engine(cfg, params, paged8), reqs)
        assert d == p

    def test_seeded_sampling_layout_invariant(self, setup):
        """Draws are (seed, position)-keyed: the cache layout must not
        change what a seeded request samples."""
        cfg, params, _ = setup
        samp = SamplingConfig(do_sample=True, temperature=1.0, top_p=1.0,
                              max_new_tokens=6)

        def run(eng_cfg):
            eng = ContinuousEngine(cfg, params, sampling=samp,
                                   engine_config=eng_cfg, dtypes=FP32)
            _, fin = eng.admit(1, [3, 17, 42, 7], 6, seed=123)
            assert fin is None
            out = {}
            while eng.has_active():
                for rid, toks in eng.step():
                    out[rid] = toks
            return out[1]

        assert run(ENG) == run(PAGED)

    def test_scheduler_end_to_end(self, setup):
        cfg, params, oracle = setup
        want = [oracle.generate([p])[0] for p in PROMPTS]
        sched = ContinuousScheduler(paged_engine(cfg, params))
        try:
            outs = [None] * len(PROMPTS)

            def run(i):
                outs[i] = sched.submit(PROMPTS[i], timeout=120)

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(PROMPTS))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert outs == want
            assert sched.engine.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()


class TestPoolAccounting:
    def test_eviction_returns_blocks(self, setup):
        """Mid-decode eviction (the deadline path) frees the row's blocks
        within the same call."""
        cfg, params, _ = setup
        eng = paged_engine(cfg, params)
        eng.admit(1, PROMPTS[0], 8)
        eng.step()
        assert eng.kv_pool.blocks_in_use() > 0
        assert eng.evict_requests([1]) != []
        assert eng.kv_pool.blocks_in_use() == 0

    def test_first_token_eos_releases_blocks(self, setup):
        cfg, params, oracle = setup
        first = oracle.generate([PROMPTS[0]], max_new_tokens=1)[0][0]
        cfg_eos = dataclasses.replace(cfg, eos_token_ids=(first,))
        eng = ContinuousEngine(cfg_eos, params, sampling=GREEDY,
                               engine_config=PAGED, dtypes=FP32)
        outs = eng.admit_many([(1, PROMPTS[0], 8, None)])
        assert outs[0][1] == []  # finished at its very first token
        assert eng.kv_pool.blocks_in_use() == 0

    def test_reset_returns_every_block(self, setup):
        """EngineStateLost recovery: reset() must hand EVERY block back —
        a leak here compounds into permanent backpressure one fault at a
        time (the chaos-lane twin lives in test_resilience.py)."""
        cfg, params, oracle = setup
        eng = paged_engine(cfg, params)
        eng.admit(1, PROMPTS[2], 8)
        eng.step()
        assert eng.kv_pool.blocks_in_use() > 0
        eng.reset()
        assert eng.kv_pool.blocks_in_use() == 0
        # and the engine still serves, correctly
        want = oracle.generate([PROMPTS[1]])[0]
        _, fin = eng.admit(2, PROMPTS[1], 8)
        assert fin is None
        results = {}
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results[2] == want
        assert eng.kv_pool.blocks_in_use() == 0

    def test_preemption_resumes_with_parity(self, setup):
        """A pool sized for HALF the batch's decode growth forces mid-decode
        preemption; the scheduler resubmits (prompt + emitted) and every
        stream still matches the solo oracle, with zero leaked blocks."""
        cfg, params, oracle = setup
        want = [oracle.generate([p], max_new_tokens=40)[0] for p in PROMPTS]
        tight = dataclasses.replace(PAGED, kv_pool_blocks=8)
        eng = paged_engine(cfg, params, tight)
        sched = ContinuousScheduler(eng)
        try:
            outs = [None] * len(PROMPTS)
            errs = [None] * len(PROMPTS)

            def run(i):
                try:
                    outs[i] = sched.submit(
                        PROMPTS[i], max_new_tokens=40, timeout=300
                    )
                except BaseException as e:  # noqa: BLE001
                    errs[i] = e

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(PROMPTS))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert errs == [None] * len(PROMPTS), errs
            assert outs == want
            assert eng.kv_pool.blocks_in_use() == 0
        finally:
            sched.shutdown()

    def test_oversized_prompt_fails_never_hangs(self, setup):
        """A prompt whose blocks outsize the whole pool fails with
        PoolExhausted instead of queueing forever."""
        cfg, params, _ = setup
        eng = paged_engine(cfg, params, dataclasses.replace(PAGED, kv_pool_blocks=8))
        assert eng.admission_state(16 * 9) == "never"
        sched = ContinuousScheduler(eng)
        try:
            with pytest.raises(PoolExhausted):
                sched.submit([7] * 30 * 5, timeout=60)  # > 8 blocks of 16
        finally:
            sched.shutdown()

    def test_construction_validation(self, setup):
        cfg, params, _ = setup
        with pytest.raises(ValueError, match="at least one full row"):
            paged_engine(cfg, params, dataclasses.replace(PAGED, kv_pool_blocks=2))
        with pytest.raises(ValueError, match="Mosaic"):
            paged_engine(cfg, params, dataclasses.replace(PAGED, kv_block_size=12))
        with pytest.raises(ValueError, match="divide"):
            paged_engine(
                cfg, params,
                dataclasses.replace(PAGED, prompt_buckets=(24,), kv_block_size=16),
            )


# ---------------------------------------------------------------------------
# prefix-cache hits over the pool (block-granular reuse)
# ---------------------------------------------------------------------------


PC = PrefixCacheConfig(
    enabled=True, max_prefix_tokens=48, segment_buckets=(16,),
    suffix_buckets=(16,), hbm_budget_mb=64,
)


class TestPagedPrefixedAdmission:
    @pytest.fixture(scope="class")
    def px_setup(self):
        cfg = LlamaConfig.tiny(vocab_size=128)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
        ec = EngineConfig(
            prompt_buckets=(64,), max_batch_size=2, speculative="off",
            max_seq_len=128, prefix_cache=PC,
        )
        engine = InferenceEngine(
            cfg, params, sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=ec, dtypes=FP32,
        )
        cont = ContinuousEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
            engine_config=dataclasses.replace(ec, kv_paged=True, kv_block_size=16),
            dtypes=FP32,
        )
        return cfg, engine, cont

    def _drain(self, cont, rid, fin):
        outs = {}
        while cont.has_active():
            for r, toks in cont.step():
                outs[r] = toks
        return fin if fin is not None else outs[rid]

    def test_prefixed_admission_parity_and_block_sharing(self, px_setup):
        """A cached prefix admits into pool blocks with greedy parity vs a
        plain full-prompt admission; a SECOND admission of the same prefix
        maps the registered full blocks copy-free (only the tail + suffix
        blocks are freshly allocated)."""
        cfg, engine, cont = px_setup
        rng = np.random.default_rng(9)
        head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 7)))
        chunk = list(map(int, rng.integers(3, 120, 11)))
        segments = [("head:p", head), ("chunk:p", chunk)]
        suffix = list(map(int, rng.integers(3, 120, 6)))
        cp = engine.prefix_cache.prefix_for(segments)
        assert cp.chain_key is not None  # exact reuse → shareable identity

        _, fin = cont.admit_prefixed(1, suffix, cp, max_new=6)
        got = self._drain(cont, 1, fin)
        full = [t for _, seg in segments for t in seg] + suffix
        _, fin2 = cont.admit(2, full, max_new=6)
        want = self._drain(cont, 2, fin2)
        assert got == want
        # the registered full prefix blocks stay pinned (cache ref)
        registered = cont.kv_pool.blocks_in_use()
        assert registered == cp.length // cont.block_size

        allocs_before = cont.kv_pool.total_allocs
        cp2 = engine.prefix_cache.prefix_for(segments)  # memo hit
        _, fin3 = cont.admit_prefixed(3, suffix, cp2, max_new=6)
        assert self._drain(cont, 3, fin3) == want
        # hit: shared blocks were NOT reallocated — only tail + growth
        fresh = cont.kv_pool.total_allocs - allocs_before
        assert fresh < cont.kv_pool.blocks_for(cp.length + len(suffix))
        assert cont.kv_pool.blocks_in_use() == registered  # rows released

    def test_eos_mid_window_never_corrupts_shared_prefix_block(self, px_setup):
        """A row hitting EOS inside a k>1 sync window keeps its table mapped
        until the host drains the window — its junk parking-write (wi=0)
        must land in the NULL block, not logical block 0, which here is a
        REF-SHARED prefix block another request reads."""
        cfg, engine, _ = px_setup
        params = engine.params
        samp = SamplingConfig(do_sample=False, max_new_tokens=6)
        rng = np.random.default_rng(21)
        head = [cfg.bos_token_id] + list(map(int, rng.integers(3, 120, 15)))
        segments = [("head:eosw", head)]  # 16 tokens: exactly one full block
        suffix = list(map(int, rng.integers(3, 120, 5)))
        cp = engine.prefix_cache.prefix_for(segments)
        assert cp.length % 16 == 0  # the whole prefix is shareable blocks

        # oracle stream → pick an EOS that CANNOT fire before its index
        # (a value repeated earlier would end the stream at token 0 and the
        # window — and with it the hazard — would never run)
        ref = engine.generate([head + suffix])[0]
        idx = next(i for i in range(1, len(ref)) if ref[i] not in ref[:i])
        eos_tok = ref[idx]
        cfg_eos = dataclasses.replace(cfg, eos_token_ids=(eos_tok,))
        ec = dataclasses.replace(
            engine.engine_config, kv_paged=True, kv_block_size=16,
            decode_sync_steps=4,
        )
        cont = ContinuousEngine(
            cfg_eos, params, sampling=samp, engine_config=ec, dtypes=FP32
        )
        # request A registers + maps the shared block, then EOSes mid-window
        _, finA = cont.admit_prefixed(1, suffix, cp, max_new=6)
        outA = self._drain(cont, 1, finA)
        assert 0 < len(outA) < 6, "EOS never fired MID-stream — vacuous fixture"
        # request B shares the registered block: its stream must match a
        # FRESH engine (whose shared block was never exposed to A's window)
        cp2 = engine.prefix_cache.prefix_for(segments)
        _, finB = cont.admit_prefixed(2, suffix, cp2, max_new=6)
        outB = self._drain(cont, 2, finB)
        fresh = ContinuousEngine(
            cfg_eos, params, sampling=samp, engine_config=ec, dtypes=FP32
        )
        _, finF = fresh.admit_prefixed(3, suffix, cp2, max_new=6)
        assert outB == self._drain(fresh, 3, finF)

    def test_reset_drops_registrations_without_leak(self, px_setup):
        cfg, engine, cont = px_setup
        cont.reset()
        assert cont.kv_pool.blocks_in_use() == 0


# ---------------------------------------------------------------------------
# kernel ↔ oracle parity (interpret mode; the TPU lane re-runs compiled)
# ---------------------------------------------------------------------------


class TestPagedKernelParity:
    def _arena(self, rng, L=2, K=2, hd=16, bs=16, nblocks=9):
        k = rng.standard_normal((L, nblocks, K, bs, hd)).astype(np.float32)
        v = rng.standard_normal((L, nblocks, K, bs, hd)).astype(np.float32)
        return jnp.asarray(k), jnp.asarray(v)

    def test_paged_decode_kernel_matches_oracle(self):
        from rag_llm_k8s_tpu.ops.attention import (
            paged_decode_attention,
            paged_decode_attention_xla,
        )

        rng = np.random.default_rng(0)
        B, H, K, hd, bs, MB = 3, 4, 2, 16, 16, 4
        ka, va = self._arena(rng, K=K, hd=hd, bs=bs, nblocks=1 + B * MB)
        tables = np.zeros((B, MB), np.int32)
        kv_len = np.array([5, 33, 64], np.int32)
        phys = 1
        for b in range(B):
            for j in range(-(-int(kv_len[b]) // bs)):
                tables[b, j] = phys
                phys += 1
        q = jnp.asarray(rng.standard_normal((B, 1, H, hd)).astype(np.float32))
        for lay in range(2):
            want = paged_decode_attention_xla(
                q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len),
                jnp.int32(lay),
            )
            got = paged_decode_attention(
                q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len),
                jnp.int32(lay), interpret=True,
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-5
            )

    def test_paged_chunk_kernel_matches_oracle(self):
        from rag_llm_k8s_tpu.ops.attention import (
            paged_chunk_attention,
            paged_chunk_attention_xla,
        )

        rng = np.random.default_rng(1)
        B, S, H, K, hd, bs, MB = 2, 8, 4, 2, 16, 16, 4
        ka, va = self._arena(rng, K=K, hd=hd, bs=bs, nblocks=1 + B * MB)
        tables = np.zeros((B, MB), np.int32)
        kv_len = np.array([20, 41], np.int32)
        wi = kv_len - S
        phys = 1
        for b in range(B):
            for j in range(-(-int(kv_len[b]) // bs)):
                tables[b, j] = phys
                phys += 1
        q = jnp.asarray(rng.standard_normal((B, S, H, hd)).astype(np.float32))
        want = paged_chunk_attention_xla(
            q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len),
            jnp.int32(1), jnp.asarray(wi),
        )
        got = paged_chunk_attention(
            q, ka, va, jnp.asarray(tables), jnp.asarray(kv_len),
            jnp.int32(1), jnp.asarray(wi), bq=4, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_paged_q8_decode_kernel_matches_oracle(self):
        from rag_llm_k8s_tpu.ops.attention import (
            paged_decode_attention_q8,
            paged_decode_attention_xla_q8,
        )

        rng = np.random.default_rng(2)
        B, H, K, hd, bs, MB = 2, 4, 2, 16, 32, 2
        N = 1 + B * MB
        ka = rng.integers(-127, 128, (2, N, K, bs, hd)).astype(np.int8)
        va = rng.integers(-127, 128, (2, N, K, bs, hd)).astype(np.int8)
        ks = rng.uniform(0.001, 0.02, (2, N, K, bs)).astype(np.float32)
        vs = rng.uniform(0.001, 0.02, (2, N, K, bs)).astype(np.float32)
        tables = np.zeros((B, MB), np.int32)
        kv_len = np.array([10, 50], np.int32)
        phys = 1
        for b in range(B):
            for j in range(-(-int(kv_len[b]) // bs)):
                tables[b, j] = phys
                phys += 1
        q = jnp.asarray(rng.standard_normal((B, 1, H, hd)).astype(np.float32))
        args = (
            q, jnp.asarray(ka), jnp.asarray(va), jnp.asarray(ks),
            jnp.asarray(vs), jnp.asarray(tables), jnp.asarray(kv_len),
            jnp.int32(0),
        )
        want = paged_decode_attention_xla_q8(*args)
        got = paged_decode_attention_q8(*args, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)

    def test_paged_q8_chunk_kernel_matches_oracle(self):
        """The FUSED q8 paged chunk-prefill kernel (it replaced PR 5's
        gather-XLA oracle serving) against that oracle, interpret mode:
        warm-tier chunked prefill streams int8 blocks with epilogue
        dequant — per-row offset causality included."""
        from rag_llm_k8s_tpu.ops.attention import (
            paged_chunk_attention_q8,
            paged_chunk_attention_xla_q8,
        )

        rng = np.random.default_rng(3)
        B, S, H, K, hd, bs, MB = 2, 8, 4, 2, 16, 16, 4
        N = 1 + B * MB
        ka = rng.integers(-127, 128, (2, N, K, bs, hd)).astype(np.int8)
        va = rng.integers(-127, 128, (2, N, K, bs, hd)).astype(np.int8)
        ks = rng.uniform(0.001, 0.02, (2, N, K, bs)).astype(np.float32)
        vs = rng.uniform(0.001, 0.02, (2, N, K, bs)).astype(np.float32)
        tables = np.zeros((B, MB), np.int32)
        kv_len = np.array([20, 41], np.int32)
        wi = kv_len - S  # rows chunk at their own depths
        phys = 1
        for b in range(B):
            for j in range(-(-int(kv_len[b]) // bs)):
                tables[b, j] = phys
                phys += 1
        q = jnp.asarray(rng.standard_normal((B, S, H, hd)).astype(np.float32))
        for lay in range(2):
            args = (
                q, jnp.asarray(ka), jnp.asarray(va), jnp.asarray(ks),
                jnp.asarray(vs), jnp.asarray(tables), jnp.asarray(kv_len),
                jnp.int32(lay), jnp.asarray(wi),
            )
            want = paged_chunk_attention_xla_q8(*args)
            got = paged_chunk_attention_q8(*args, bq=4, interpret=True)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-4
            )
