"""ragcheck (scripts/ragcheck): the repo-native static-analysis suite.

Per-rule contract tests — each rule must flag its seeded fixture violation
and stay silent on the compliant twin — plus the framework contracts
(suppressions, baseline ratchet, CLI exit codes) and the whole-repo gate:
the analyzer over THIS tree yields zero non-baselined findings and zero
stale baseline entries. docs/STATIC_ANALYSIS.md is the rule catalog.

No jax required: ragcheck is stdlib-only AST analysis.
"""

import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from scripts.ragcheck import core  # noqa: E402
from scripts.ragcheck.rules.config_drift import ConfigDriftRule  # noqa: E402
from scripts.ragcheck.rules.debug_gate import DebugGateRule  # noqa: E402
from scripts.ragcheck.rules.event_registry import EventRegistryRule  # noqa: E402
from scripts.ragcheck.rules.fault_sites import FaultSiteRegistryRule  # noqa: E402
from scripts.ragcheck.rules.jit_hygiene import JitHygieneRule  # noqa: E402
from scripts.ragcheck.rules.lock_discipline import LockDisciplineRule  # noqa: E402
from scripts.ragcheck.rules.metric_drift import MetricDriftRule  # noqa: E402
from scripts.ragcheck.rules.sharding_contract import ShardingContractRule  # noqa: E402
from scripts.ragcheck.rules.durable_write import DurableWriteRule  # noqa: E402
from scripts.ragcheck.rules.sim_purity import SimPurityRule  # noqa: E402

BASELINE = REPO_ROOT / "scripts" / "ragcheck" / "baseline.json"


def run_rule(tmp_path, rule_cls, files):
    """Materialize a fixture repo and run one rule over it."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    _, findings = core.run_analysis(str(tmp_path), rules=[rule_cls()])
    return findings


def keys(findings):
    return {f.key for f in findings}


# ---------------------------------------------------------------------------
# LOCK-DISCIPLINE
# ---------------------------------------------------------------------------


class TestLockDiscipline:
    def test_flags_blocking_work_under_lock(self, tmp_path):
        fs = run_rule(tmp_path, LockDisciplineRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import time
                import jax

                class Cache:
                    def bad(self, x):
                        with self._lock:
                            y = jax.device_put(x, None)
                            x.block_until_ready()
                            time.sleep(0.1)
                            self._thread.join(timeout=5)
                            self.coalescer.submit(x)
                        return y
                """,
        })
        assert keys(fs) == {
            "Cache.bad:device_put",
            "Cache.bad:block_until_ready",
            "Cache.bad:time.sleep",
            "Cache.bad:thread-join",
            "Cache.bad:submit",
        }
        assert all(f.rule == "LOCK-DISCIPLINE" for f in fs)

    def test_flags_executable_work_under_lock(self, tmp_path):
        fs = run_rule(tmp_path, LockDisciplineRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                class Engine:
                    def bad(self, key, args):
                        with self._lock:
                            out = self._compiled[key](*args)
                            fn = self._build_step(2)
                            exe = jax.jit(fn).lower(args).compile()
                        return out, exe
                """,
        })
        assert "Engine.bad:compiled-executable-call" in keys(fs)
        assert "Engine.bad:executable-build:_build_step" in keys(fs)
        assert "Engine.bad:jit-lower-compile" in keys(fs)

    def test_compliant_twin_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, LockDisciplineRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import time
                import jax

                class Cache:
                    def good(self, x):
                        y = jax.device_put(x, None)  # transfer OFF-lock
                        time.sleep(0)
                        with self._lock:
                            self._entries[id(x)] = y  # bookkeeping only
                            parts = ",".join(["a", "b"])  # str.join is fine
                        return y, parts
                """,
        })
        assert fs == []

    def test_deferred_closures_are_not_lock_held(self, tmp_path):
        fs = run_rule(tmp_path, LockDisciplineRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                class Cache:
                    def register(self, x):
                        with self._lock:
                            def probe():  # runs later, not under the lock
                                return jax.device_put(x, None)
                            self._probe = probe
                """,
        })
        assert fs == []


# ---------------------------------------------------------------------------
# JIT-HYGIENE
# ---------------------------------------------------------------------------


class TestJitHygiene:
    def test_flags_host_calls_and_concretization(self, tmp_path):
        fs = run_rule(tmp_path, JitHygieneRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import time
                import random
                import jax

                def traced(x, n):
                    t = time.time()
                    r = random.random()
                    v = x.item()
                    m = float(n)
                    return x * t * r * v * m

                fn = jax.jit(traced)
                """,
        })
        assert keys(fs) == {
            "traced:time.time",
            "traced:random.random",
            "traced:item",
            "traced:float:n",
        }

    def test_nested_loop_bodies_are_traced_too(self, tmp_path):
        fs = run_rule(tmp_path, JitHygieneRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import time
                import jax

                def gen(x):
                    def body(c):
                        return c + time.perf_counter()
                    return jax.lax.while_loop(lambda c: c < 9, body, x)

                fn = jax.jit(gen)
                """,
        })
        assert keys(fs) == {"gen:time.perf_counter"}

    def test_compliant_twin_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, JitHygieneRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import time
                import jax
                import jax.numpy as jnp

                def traced(x, n):
                    return x * jnp.float32(n)  # jnp casts stay traced

                t0 = time.time()  # host code outside the traced fn: fine
                fn = jax.jit(traced)
                """,
        })
        assert fs == []

    def test_decorator_forms_are_traced(self, tmp_path):
        # the repo's dominant jit idiom: @jax.jit and
        # @functools.partial(jax.jit, ...) trace exactly like jit(f)
        fs = run_rule(tmp_path, JitHygieneRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import functools
                import time
                import jax

                @jax.jit
                def bare(x):
                    return x * time.time()

                @functools.partial(jax.jit, static_argnames=("n",))
                def partial_form(x, n):
                    return x * float(n) * time.perf_counter()
                """,
        })
        assert keys(fs) == {
            "bare:time.time",
            "partial_form:float:n",
            "partial_form:time.perf_counter",
        }

    def test_name_collision_with_host_method_does_not_leak(self, tmp_path):
        # regression: ContinuousEngine.step (host, times itself) shares its
        # name with the traced local `def step` — lexical scoping must bind
        # jit(step) to the sibling def, not the class method
        fs = run_rule(tmp_path, JitHygieneRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import time
                import jax

                class Engine:
                    def step(self):  # HOST method: timing is fine here
                        t0 = time.perf_counter()
                        return t0

                    def _build_step(self):
                        def step(cache):
                            return cache * 2
                        return jax.jit(step)
                """,
        })
        assert fs == []


# ---------------------------------------------------------------------------
# SHARDING-CONTRACT
# ---------------------------------------------------------------------------


class TestShardingContract:
    def test_flags_state_returning_jit_without_out_shardings(self, tmp_path):
        fs = run_rule(tmp_path, ShardingContractRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                def build(model):
                    def prefill(params, cache, toks):
                        new_cache = cache
                        return new_cache, toks
                    return jax.jit(prefill).lower().compile()
                """,
        })
        assert keys(fs) == {"jit:build.prefill"}

    def test_indirect_state_return_is_caught(self, tmp_path):
        # the _build_segment_kv shape: state tuple bound to a neutral name
        fs = run_rule(tmp_path, ShardingContractRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                def build(model):
                    def seg(params, cache):
                        out = (cache.k, cache.v)
                        return out
                    return jax.jit(seg).lower().compile()
                """,
        })
        assert keys(fs) == {"jit:build.seg"}

    def test_pinned_out_shardings_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, ShardingContractRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                def build(model, specs):
                    def prefill(params, cache, toks):
                        return cache, toks
                    return jax.jit(prefill, out_shardings=specs).lower().compile()
                """,
        })
        assert fs == []

    def test_token_returning_executables_are_exempt(self, tmp_path):
        # regression (bench.py fwd): a value DERIVED from cache through a
        # call is logits, not state — call results don't taint the return,
        # whether bound to a temp or returned inline
        fs = run_rule(tmp_path, ShardingContractRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                def build(model):
                    def fwd(params, toks, cache):
                        logits, _ = model.apply(params, toks, cache)
                        return logits
                    return jax.jit(fwd)

                def build_inline(model):
                    def fwd2(params, toks, cache):
                        return model.apply(params, toks, cache)[0]
                    return jax.jit(fwd2)
                """,
        })
        assert fs == []

    def test_decorator_form_is_checked(self, tmp_path):
        fs = run_rule(tmp_path, ShardingContractRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import functools
                import jax

                @jax.jit
                def bad(params, cache):
                    return cache  # bare decorator cannot pin out_shardings

                @functools.partial(jax.jit, out_shardings=None)
                def pinned(params, cache):
                    return cache
                """,
        })
        assert keys(fs) == {"jit:bad"}

    def test_same_named_functions_get_distinct_fingerprints(self, tmp_path):
        # two ClassX.step methods must not collapse into one fingerprint —
        # a shared key would dedupe one finding and let a single baseline
        # entry mask every same-named function in the file
        fs = run_rule(tmp_path, ShardingContractRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                class A:
                    @jax.jit
                    def step(self, cache):
                        return cache

                class B:
                    @jax.jit
                    def step(self, cache):
                        return cache
                """,
        })
        assert keys(fs) == {"jit:A.step", "jit:B.step"}


# ---------------------------------------------------------------------------
# CONFIG-DRIFT
# ---------------------------------------------------------------------------


_CONFIG_OK = """
    import os

    def from_env(env=None):
        env = dict(os.environ if env is None else env)
        return env.get("TPU_RAG_FOO", "0")
    """
_DEPLOY_OK = """
    env:
      - name: TPU_RAG_FOO
        value: "0"
    """
_RUNBOOK_OK = """
    # RUNBOOK

    1. [Configuration reference](#configuration-reference)

    ## 8. Configuration reference

    | env var | default | meaning |
    |---|---|---|
    | `TPU_RAG_FOO` | `0` | the knob |

    ## 9. Operations
    """


class TestConfigDrift:
    def test_flags_env_read_outside_config(self, tmp_path):
        fs = run_rule(tmp_path, ConfigDriftRule, {
            "rag_llm_k8s_tpu/obs/thing.py": """
                import os
                def probe():
                    return os.environ.get("TPU_RAG_THING", "1")
                """,
        })
        assert keys(fs) == {"env-read:probe"}

    def test_config_home_and_bootstrap_allowlist_are_exempt(self, tmp_path):
        fs = run_rule(tmp_path, ConfigDriftRule, {
            "rag_llm_k8s_tpu/core/config.py": _CONFIG_OK,
            "rag_llm_k8s_tpu/server/main.py": """
                import os
                LEVEL = os.environ.get("TPU_RAG_LOG_LEVEL", "INFO")
                """,
            "deploy/llm/deploy.yaml": _DEPLOY_OK,
            "docs/RUNBOOK.md": _RUNBOOK_OK,
        })
        assert fs == []

    def test_flags_unpinned_knob(self, tmp_path):
        fs = run_rule(tmp_path, ConfigDriftRule, {
            "rag_llm_k8s_tpu/core/config.py": _CONFIG_OK,
            "deploy/llm/deploy.yaml": "env: []\n",
            "docs/RUNBOOK.md": _RUNBOOK_OK.replace("TPU_RAG_FOO", "TPU_RAG_OTHER"),
        })
        assert keys(fs) == {"knob-deploy:TPU_RAG_FOO", "knob-runbook:TPU_RAG_FOO"}

    def test_prefix_knob_is_not_pinned_by_its_longer_sibling(self, tmp_path):
        # TPU_RAG_FOO must not read as deploy-pinned just because
        # TPU_RAG_FOO_EXTRA is (substring match would miss the drift)
        fs = run_rule(tmp_path, ConfigDriftRule, {
            "rag_llm_k8s_tpu/core/config.py": """
                import os

                def from_env(env=None):
                    env = dict(os.environ if env is None else env)
                    return env.get("TPU_RAG_FOO", "0")

                def more(env):
                    return env.get("TPU_RAG_FOO_EXTRA")
                """,
            "deploy/llm/deploy.yaml": """
                env:
                  - name: TPU_RAG_FOO_EXTRA
                    value: "1"
                """,
            "docs/RUNBOOK.md": _RUNBOOK_OK.replace(
                "| `TPU_RAG_FOO` | `0` | the knob |",
                "| `TPU_RAG_FOO` | `0` | the knob |\n"
                "    | `TPU_RAG_FOO_EXTRA` | `1` | the other knob |",
            ),
        })
        assert keys(fs) == {"knob-deploy:TPU_RAG_FOO"}

    def test_missing_manifest_or_section_is_loud(self, tmp_path):
        # renaming deploy.yaml (or dropping the RUNBOOK section) must not
        # silently retire the whole pinning gate — same scanner-rot class
        # METRIC-DRIFT guards against
        fs = run_rule(tmp_path, ConfigDriftRule, {
            "rag_llm_k8s_tpu/core/config.py": _CONFIG_OK,
            "docs/RUNBOOK.md": "# RUNBOOK\n\nno config section here\n",
        })
        assert keys(fs) == {
            "missing-deploy-manifest",
            "missing-runbook-config-section",
        }

    def test_knob_outside_config_section_does_not_count(self, tmp_path):
        # a troubleshooting aside naming the knob is not a table row
        runbook = _RUNBOOK_OK.replace("| `TPU_RAG_FOO` | `0` | the knob |", "") \
            + "\n    raise `TPU_RAG_FOO` when paged\n"
        fs = run_rule(tmp_path, ConfigDriftRule, {
            "rag_llm_k8s_tpu/core/config.py": _CONFIG_OK,
            "deploy/llm/deploy.yaml": _DEPLOY_OK,
            "docs/RUNBOOK.md": runbook,
        })
        assert keys(fs) == {"knob-runbook:TPU_RAG_FOO"}


# ---------------------------------------------------------------------------
# FAULT-SITE-REGISTRY
# ---------------------------------------------------------------------------


_FAULTS_FIXTURE = """
    SITES = ("alpha", "beta")

    def maybe_fail(site):
        pass

    def arm(site, times=1):
        pass
    """


class TestFaultSiteRegistry:
    def test_flags_unknown_site_and_untested_site(self, tmp_path):
        fs = run_rule(tmp_path, FaultSiteRegistryRule, {
            "rag_llm_k8s_tpu/resilience/faults.py": _FAULTS_FIXTURE,
            "rag_llm_k8s_tpu/engine/thing.py": """
                from rag_llm_k8s_tpu.resilience import faults
                def hot_path():
                    faults.maybe_fail("gamma")  # not in SITES
                """,
            "tests/test_thing.py": """
                def test_alpha():
                    assert "alpha"
                """,
        })
        assert keys(fs) == {"unknown-site:gamma", "untested-site:beta"}

    def test_docstring_mention_does_not_count_as_exercised(self, tmp_path):
        # exercised = EXACT string literal in a test; a docstring sentence
        # naming the site (with quotes, even) is not a test pulling it
        fs = run_rule(tmp_path, FaultSiteRegistryRule, {
            "rag_llm_k8s_tpu/resilience/faults.py": _FAULTS_FIXTURE,
            "tests/test_thing.py": '''
                """The "beta" site falls back to recompute."""

                def test_alpha():
                    assert "alpha"
                ''',
        })
        assert keys(fs) == {"untested-site:beta"}

    def test_compliant_twin_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, FaultSiteRegistryRule, {
            "rag_llm_k8s_tpu/resilience/faults.py": _FAULTS_FIXTURE,
            "rag_llm_k8s_tpu/engine/thing.py": """
                from rag_llm_k8s_tpu.resilience import faults
                def hot_path():
                    faults.maybe_fail("alpha")
                """,
            "tests/test_thing.py": """
                def test_both():
                    assert "alpha" and "beta"
                """,
        })
        assert fs == []


# ---------------------------------------------------------------------------
# EVENT-REGISTRY
# ---------------------------------------------------------------------------

_FLIGHT_FIXTURE = """
    EVENTS = {
        "admit": "request admitted",
        "reset": "engine reset",
    }

    def emit(etype, request_id=None, **attrs):
        pass
"""

_EVENTS_DOC = """
    # Observability

    | event | meaning |
    |---|---|
    | `admit` | request admitted |
    | `reset` | engine reset |
"""


class TestEventRegistry:
    def test_flags_unknown_and_unemitted_events(self, tmp_path):
        fs = run_rule(tmp_path, EventRegistryRule, {
            "rag_llm_k8s_tpu/obs/flight.py": _FLIGHT_FIXTURE,
            "rag_llm_k8s_tpu/engine/thing.py": """
                from rag_llm_k8s_tpu.obs import flight
                def hot_path():
                    flight.emit("admitt", slot=1)  # typo: not in EVENTS
                    flight.emit("admit", slot=1)
                """,
            "docs/OBSERVABILITY.md": _EVENTS_DOC,
        })
        # "reset" is declared + documented but nothing emits it
        assert keys(fs) == {"unknown-event:admitt", "unemitted-event:reset"}

    def test_test_file_emits_do_not_satisfy_coverage(self, tmp_path):
        # a test calling flight.emit("reset") validates the literal but
        # does NOT count as the package instrumenting the decision point
        fs = run_rule(tmp_path, EventRegistryRule, {
            "rag_llm_k8s_tpu/obs/flight.py": _FLIGHT_FIXTURE,
            "rag_llm_k8s_tpu/engine/thing.py": """
                from rag_llm_k8s_tpu.obs import flight
                def hot_path():
                    flight.emit("admit", slot=1)
                """,
            "tests/test_thing.py": """
                from rag_llm_k8s_tpu.obs import flight
                def test_reset():
                    flight.emit("reset")
                """,
            "docs/OBSERVABILITY.md": _EVENTS_DOC,
        })
        assert keys(fs) == {"unemitted-event:reset"}

    def test_flags_undocumented_event_and_missing_doc(self, tmp_path):
        files = {
            "rag_llm_k8s_tpu/obs/flight.py": _FLIGHT_FIXTURE,
            "rag_llm_k8s_tpu/engine/thing.py": """
                from rag_llm_k8s_tpu.obs import flight
                def hot_path():
                    flight.emit("admit")
                    flight.emit("reset")
                """,
            # the doc table documents only one of the two; "reset" appears
            # in PROSE (unbackticked) and must not count
            "docs/OBSERVABILITY.md": """
                | `admit` | request admitted |

                After a reset the engine rebuilds its state.
            """,
        }
        fs = run_rule(tmp_path, EventRegistryRule, files)
        assert keys(fs) == {"undocumented-event:reset"}
        del files["docs/OBSERVABILITY.md"]
        fs = run_rule(tmp_path / "nodoc", EventRegistryRule, files)
        assert keys(fs) == {"events-doc-missing"}

    def test_compliant_twin_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, EventRegistryRule, {
            "rag_llm_k8s_tpu/obs/flight.py": _FLIGHT_FIXTURE,
            "rag_llm_k8s_tpu/engine/thing.py": """
                from rag_llm_k8s_tpu.obs import flight
                def hot_path():
                    flight.emit("admit", slot=1)
                    flight.emit("reset")
                """,
            "docs/OBSERVABILITY.md": _EVENTS_DOC,
        })
        assert fs == []


# ---------------------------------------------------------------------------
# DEBUG-GATE
# ---------------------------------------------------------------------------


class TestDebugGate:
    def test_flags_ungated_debug_route(self, tmp_path):
        fs = run_rule(tmp_path, DebugGateRule, {
            "rag_llm_k8s_tpu/server/app.py": """
                class WsgiApp:
                    def __init__(self):
                        self.url_map = Map([
                            Rule("/debug/stuff", endpoint="debug_stuff",
                                 methods=["GET"]),
                            Rule("/healthz", endpoint="healthz"),
                        ])

                    def _debug_enabled(self):
                        return False

                    def ep_debug_stuff(self, request):
                        return {"secret": "journal"}  # no gate call

                    def ep_healthz(self, request):
                        return {"ok": True}  # non-debug: no gate needed
                """,
        })
        assert keys(fs) == {"ungated-debug-route:debug_stuff"}

    def test_flags_missing_handler(self, tmp_path):
        fs = run_rule(tmp_path, DebugGateRule, {
            "rag_llm_k8s_tpu/server/app.py": """
                class WsgiApp:
                    def __init__(self):
                        self.url_map = Map([
                            Rule("/debug/ghost", endpoint="debug_ghost"),
                        ])
                """,
        })
        assert keys(fs) == {"missing-handler:debug_ghost"}

    def test_compliant_twin_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, DebugGateRule, {
            "rag_llm_k8s_tpu/server/app.py": """
                class WsgiApp:
                    def __init__(self):
                        self.url_map = Map([
                            Rule("/debug/stuff", endpoint="debug_stuff"),
                            Rule("/debug/faults", endpoint="debug_faults"),
                        ])

                    def _debug_enabled(self):
                        return False

                    def ep_debug_stuff(self, request):
                        if not self._debug_enabled():
                            return 403
                        return {"ok": True}

                    def ep_debug_faults(self, request):
                        if not faults.endpoint_enabled():
                            return 403
                        return {"ok": True}
                """,
        })
        assert fs == []

    def test_no_server_module_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, DebugGateRule, {
            "rag_llm_k8s_tpu/mod.py": "x = 1\n",
        })
        assert fs == []


# ---------------------------------------------------------------------------
# SIM-PURITY
# ---------------------------------------------------------------------------


class TestSimPurity:
    def test_flags_every_violation_class(self, tmp_path):
        fs = run_rule(tmp_path, SimPurityRule, {
            "rag_llm_k8s_tpu/sim/bad.py": """
                import jax
                import numpy as np
                from rag_llm_k8s_tpu.obs import flight
                import rag_llm_k8s_tpu.core.config
                from . import policy
                import os, json
                """,
        })
        assert keys(fs) == {
            "nonstdlib-import:jax",
            "nonstdlib-import:numpy",
            "package-import:rag_llm_k8s_tpu.obs",
            "package-import:rag_llm_k8s_tpu.core.config",
            "relative-import:",
        }
        assert all(f.rule == "SIM-PURITY" for f in fs)

    def test_flags_path_loaded_obs_modules(self, tmp_path):
        fs = run_rule(tmp_path, SimPurityRule, {
            "rag_llm_k8s_tpu/obs/goodput.py": """
                import numpy as np
                import time
                """,
        })
        assert keys(fs) == {"nonstdlib-import:numpy"}

    def test_pure_module_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, SimPurityRule, {
            "rag_llm_k8s_tpu/sim/ok.py": """
                import importlib.util
                import os
                from collections import deque
                from typing import Dict
                """,
            # the rest of the package is NOT held to the pure contract
            "rag_llm_k8s_tpu/engine/dev.py": """
                import jax
                from rag_llm_k8s_tpu.obs import flight
                """,
        })
        assert fs == []

    def test_repo_sim_modules_are_pure(self):
        # the real tree's pure set stays clean — the contract the rule
        # exists to hold (a finding here means someone imported jax or
        # the package into a path-loaded module)
        _, findings = core.run_analysis(
            str(REPO_ROOT), rules=[SimPurityRule()]
        )
        assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# DURABLE-WRITE
# ---------------------------------------------------------------------------


class TestDurableWrite:
    def test_flags_raw_write_and_bare_replace(self, tmp_path):
        fs = run_rule(tmp_path, DurableWriteRule, {
            "rag_llm_k8s_tpu/obs/flight.py": """
                import json
                import os

                def save_manifest(path, doc):
                    with open(path, "w") as f:
                        json.dump(doc, f)

                def swap(tmp, path):
                    os.replace(tmp, path)
                """,
        })
        assert keys(fs) == {
            "raw-open:save_manifest:w",
            "raw-replace:swap",
        }
        assert all(f.rule == "DURABLE-WRITE" for f in fs)

    def test_compliant_twin_is_silent(self, tmp_path):
        # the helper itself owns the tmp-write + replace; append-mode
        # (the WAL's per-event fsync discipline) and reads are exempt,
        # and modules outside the writer set are not held to the rule
        fs = run_rule(tmp_path, DurableWriteRule, {
            "rag_llm_k8s_tpu/obs/flight.py": """
                import json
                import os

                def durable_write(path, obj):
                    tmp = path + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(obj, f)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, path)

                def append_event(path, line):
                    with open(path, "a") as f:
                        f.write(line)

                def load(path):
                    with open(path) as f:
                        return json.load(f)
                """,
            "rag_llm_k8s_tpu/engine/other.py": """
                def scratch(path):
                    with open(path, "w") as f:
                        f.write("not durable state")
                """,
        })
        assert fs == []

    def test_repo_writer_modules_are_compliant(self):
        # the real tree holds the discipline — a finding here means a raw
        # write-mode open or bare os.replace crept into a writer module
        _, findings = core.run_analysis(
            str(REPO_ROOT), rules=[DurableWriteRule()]
        )
        assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# METRIC-DRIFT
# ---------------------------------------------------------------------------


class TestMetricDrift:
    def test_flags_undocumented_metric(self, tmp_path):
        fs = run_rule(tmp_path, MetricDriftRule, {
            "rag_llm_k8s_tpu/mod.py": """
                def bind(reg):
                    reg.counter("rag_widgets_total", "widgets")
                """,
            "docs/OBSERVABILITY.md": "| `rag_other_total` | counter |\n",
        })
        assert keys(fs) == {"undocumented:rag_widgets_total"}

    def test_flags_inconsistent_label_sets(self, tmp_path):
        fs = run_rule(tmp_path, MetricDriftRule, {
            "rag_llm_k8s_tpu/mod.py": """
                def bind(reg):
                    fam = reg.labeled_counter("rag_widgets_total", "widgets")
                    fam.labels(stage="a").inc()
                    fam.labels(phase="b").inc()  # same family, new label name
                """,
            "docs/OBSERVABILITY.md": "| `rag_widgets_total` | counter |\n",
        })
        assert len(fs) == 1
        assert fs[0].key.startswith("labelset:rag_widgets_total:")

    def test_flags_dynamic_label_value(self, tmp_path):
        fs = run_rule(tmp_path, MetricDriftRule, {
            "rag_llm_k8s_tpu/mod.py": """
                def bind(reg, i):
                    fam = reg.labeled_counter("rag_widgets_total", "widgets")
                    fam.labels(stage=f"s{i}").inc()
                """,
            "docs/OBSERVABILITY.md": "| `rag_widgets_total` | counter |\n",
        })
        assert keys(fs) == {"dynamic-label:rag_widgets_total:stage"}

    def test_compliant_twin_is_silent(self, tmp_path):
        fs = run_rule(tmp_path, MetricDriftRule, {
            "rag_llm_k8s_tpu/mod.py": """
                def bind(reg, code):
                    fam = reg.labeled_counter("rag_widgets_total", "widgets")
                    fam.labels(stage="a").inc()
                    fam.labels(stage=str(code)).inc()  # bounded str() is fine
                """,
            "docs/OBSERVABILITY.md": "| `rag_widgets_total` | counter |\n",
        })
        assert fs == []

    def test_zero_registrations_with_doc_is_scanner_rot(self, tmp_path):
        # the old check_metrics_docs self-check: a tree shipping an
        # OBSERVABILITY.md in which the scanner finds NO registrations
        # means the matcher broke — fail loudly, never vacuously pass
        fs = run_rule(tmp_path, MetricDriftRule, {
            "rag_llm_k8s_tpu/mod.py": "def nothing():\n    pass\n",
            "docs/OBSERVABILITY.md": "| `rag_widgets_total` | counter |\n",
        })
        assert keys(fs) == {"no-registrations-found"}
        # fixture repos WITHOUT the doc stay silent (no metrics surface)
        fs = run_rule(tmp_path / "bare", MetricDriftRule, {
            "rag_llm_k8s_tpu/mod.py": "def nothing():\n    pass\n",
        })
        assert fs == []


# ---------------------------------------------------------------------------
# framework: suppressions, baseline, CLI
# ---------------------------------------------------------------------------


class TestFramework:
    def test_inline_suppression(self, tmp_path):
        fs = run_rule(tmp_path, LockDisciplineRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                class Cache:
                    def known(self, x):
                        with self._lock:
                            # one-time init, measured harmless  # ragcheck: disable=LOCK-DISCIPLINE
                            return jax.device_put(x, None)
                """,
        })
        assert fs == []

    def test_suppression_is_per_rule(self, tmp_path):
        fs = run_rule(tmp_path, LockDisciplineRule, {
            "rag_llm_k8s_tpu/mod.py": """
                import jax

                class Cache:
                    def known(self, x):
                        with self._lock:
                            # ragcheck: disable=JIT-HYGIENE
                            return jax.device_put(x, None)
                """,
        })
        assert keys(fs) == {"Cache.known:device_put"}

    def test_baseline_gate_and_ratchet(self):
        findings = [
            core.Finding("R", "a.py", 3, "m", "k1"),
            core.Finding("R", "b.py", 9, "m", "k2"),
        ]
        baseline = {"R::a.py::k1": "known"}
        new, stale = core.gate(findings, baseline)
        assert [f.key for f in new] == ["k2"] and stale == []
        # the ratchet: a GROWN baseline (an entry nothing fires for) fails
        grown = dict(baseline, **{"R::zombie.py::gone": "stale"})
        new, stale = core.gate(findings, grown)
        assert stale == ["R::zombie.py::gone"]

    def test_baseline_requires_justification(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text('{"entries": [{"fingerprint": "R::a.py::k"}]}')
        with pytest.raises(ValueError, match="justification"):
            core.load_baseline(str(p))

    def test_cli_exits_nonzero_on_seeded_violation(self, tmp_path):
        from scripts.ragcheck.__main__ import main

        (tmp_path / "rag_llm_k8s_tpu").mkdir()
        (tmp_path / "rag_llm_k8s_tpu" / "mod.py").write_text(
            "import jax\n\n"
            "class C:\n"
            "    def bad(self, x):\n"
            "        with self._lock:\n"
            "            return jax.device_put(x, None)\n"
        )
        empty = tmp_path / "no_baseline.json"  # absent file = empty baseline
        assert main(["--root", str(tmp_path), "--baseline", str(empty)]) == 1
        # --json still exits 1 and is parseable
        assert main(
            ["--root", str(tmp_path), "--baseline", str(empty), "--json"]
        ) == 1


# ---------------------------------------------------------------------------
# the whole-repo gate (what `make analyze` enforces)
# ---------------------------------------------------------------------------


class TestWholeRepo:
    def test_repo_tree_is_clean_against_baseline(self):
        _, findings = core.run_analysis(str(REPO_ROOT))
        baseline = core.load_baseline(str(BASELINE))
        new, stale = core.gate(findings, baseline)
        assert new == [], "unbaselined findings:\n" + "\n".join(
            f.render() for f in new
        )
        assert stale == [], f"stale baseline entries (delete them): {stale}"

    def test_grown_baseline_fails(self):
        _, findings = core.run_analysis(str(REPO_ROOT))
        baseline = core.load_baseline(str(BASELINE))
        baseline["CONFIG-DRIFT::rag_llm_k8s_tpu/gone.py::env-read:nope"] = "x"
        _, stale = core.gate(findings, baseline)
        assert stale  # the extra entry reads as stale -> make analyze fails

    def test_cli_green_on_repo(self):
        from scripts.ragcheck.__main__ import main

        assert main(["--root", str(REPO_ROOT), "--baseline", str(BASELINE)]) == 0

    def test_metric_docs_shim_still_works(self):
        import importlib

        shim = importlib.import_module("scripts.check_metrics_docs")
        assert shim.main() == 0
