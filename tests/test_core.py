"""Core config + mesh tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rag_llm_k8s_tpu.core import AppConfig, LlamaConfig, MeshConfig, RetrievalConfig, SamplingConfig
from rag_llm_k8s_tpu.core.config import SYSTEM_MESSAGE
from rag_llm_k8s_tpu.core.mesh import make_mesh, single_device_mesh


class TestReferenceParityDefaults:
    """Defaults must reproduce the reference's hardcoded constants (SURVEY §5 config)."""

    def test_retrieval_defaults(self):
        r = RetrievalConfig()
        assert r.chunk_size == 1000  # rag.py:39
        assert r.chunk_overlap == 200  # rag.py:39
        assert r.k == 5  # rag.py:114
        assert r.context_top_n == 3  # rag.py:164
        assert r.embed_dim == 1024  # bge-m3 dim, rag.py:60

    def test_sampling_defaults(self):
        s = SamplingConfig()
        assert s.max_new_tokens == 150  # rag.py:172
        assert s.temperature == 0.7
        assert s.top_p == 0.9

    def test_server_defaults(self):
        c = AppConfig()
        assert c.server.port == 5001  # rag.py:204
        assert c.server.model_path == "/models"  # rag.py:18
        assert c.server.pdf_dir == "/pdfs"  # rag.py:20

    def test_system_message_parity(self):
        assert "based ONLY on the given context" in SYSTEM_MESSAGE
        assert "I don't have enough information" in SYSTEM_MESSAGE

    def test_llama_8b_architecture(self):
        m = LlamaConfig.llama_3_1_8b()
        assert m.hidden_size == 4096
        assert m.num_layers == 32
        assert m.num_kv_heads == 8
        assert m.vocab_size == 128256
        assert m.rope_theta == 500000.0
        assert m.rope_scaling.factor == 8.0

    def test_llama_family_parameter_counts(self):
        """Config shapes reproduce each family member's published size —
        the invariant that guards against transcription slips in the
        classmethods (checked analytically; no tensors built)."""
        def n_params(m):
            attn = m.num_heads * m.head_dim + 2 * m.num_kv_heads * m.head_dim
            per_layer = (
                m.hidden_size * attn                      # wq wk wv
                + m.num_heads * m.head_dim * m.hidden_size  # wo
                + 3 * m.hidden_size * m.intermediate_size   # gate up down
                + 2 * m.hidden_size                          # norms
            )
            total = m.num_layers * per_layer + m.hidden_size
            total += m.vocab_size * m.hidden_size  # embedding
            if not m.tie_word_embeddings:
                total += m.vocab_size * m.hidden_size  # lm_head
            return total

        # published sizes (billions): 1.24, 3.21, 8.03, 70.6
        for cfg, want_b in [
            (LlamaConfig.llama_3_2_1b(), 1.24),
            (LlamaConfig.llama_3_2_3b(), 3.21),
            (LlamaConfig.llama_3_1_8b(), 8.03),
            (LlamaConfig.llama_3_1_70b(), 70.6),
        ]:
            got_b = n_params(cfg) / 1e9
            assert abs(got_b - want_b) / want_b < 0.01, (cfg, got_b, want_b)

    def test_70b_dims_divide_tp8(self):
        m = LlamaConfig.llama_3_1_70b()
        for dim in (m.hidden_size, m.intermediate_size, m.vocab_size,
                    m.num_heads, m.num_kv_heads):
            assert dim % 8 == 0

    def test_from_env_model_path(self):
        c = AppConfig.from_env({"MODEL_PATH": "/tmp/m", "TPU_RAG_PORT": "8080"})
        assert c.server.model_path == "/tmp/m"
        assert c.server.index_path == "/tmp/m/tpu_index"
        assert c.server.port == 8080

    def test_from_env_mesh(self):
        c = AppConfig.from_env({"TPU_RAG_MESH": "dp=2,tp=4"})
        assert c.mesh.dp == 2 and c.mesh.tp == 4

    def test_from_env_warm_full_ladder(self):
        c = AppConfig.from_env({"TPU_RAG_WARM_FULL_LADDER": "1"})
        assert c.engine.warm_full_ladder is True
        assert AppConfig.from_env({}).engine.warm_full_ladder is False
        with pytest.raises(ValueError):
            AppConfig.from_env({"TPU_RAG_WARM_FULL_LADDER": "true"})

    def test_from_env_speculative(self):
        c = AppConfig.from_env(
            {"TPU_RAG_SPECULATIVE": "prompt_lookup", "TPU_RAG_DO_SAMPLE": "0"}
        )
        assert c.engine.speculative == "prompt_lookup"
        assert c.sampling.do_sample is False
        with pytest.raises(ValueError):
            AppConfig.from_env({"TPU_RAG_SPECULATIVE": "ngram"})
        with pytest.raises(ValueError):
            AppConfig.from_env({"TPU_RAG_DO_SAMPLE": "yes"})

    def test_from_env_sync_steps(self):
        c = AppConfig.from_env({"TPU_RAG_SYNC_STEPS": "8"})
        assert c.engine.decode_sync_steps == 8
        with pytest.raises(ValueError):
            AppConfig.from_env({"TPU_RAG_SYNC_STEPS": "0"})

    def test_from_env_resilience(self):
        c = AppConfig.from_env({
            "TPU_RAG_ADMISSION_MAX_CONCURRENCY": "4",
            "TPU_RAG_ADMISSION_MAX_QUEUE": "0",
            "TPU_RAG_ADMISSION_RETRY_AFTER_S": "2.5",
            "TPU_RAG_DEADLINE_MS": "30000",
            "TPU_RAG_BREAKER_RESETS": "5",
            "TPU_RAG_BREAKER_WINDOW_S": "60",
            "TPU_RAG_INFLIGHT_RETRIES": "2",
            "TPU_RAG_RETRY_BACKOFF_MS": "10",
        })
        r = c.resilience
        assert r.admission_max_concurrency == 4
        assert r.admission_max_queue == 0
        assert r.admission_retry_after_s == 2.5
        assert r.deadline_ms == 30000
        assert r.breaker_reset_threshold == 5
        assert r.breaker_window_s == 60.0
        assert r.inflight_retries == 2
        assert r.retry_backoff_ms == 10.0
        # defaults survive an empty env
        d = AppConfig.from_env({}).resilience
        assert d.deadline_ms == 120_000 and d.inflight_retries == 1

    def test_from_env_kv_tiering(self):
        c = AppConfig.from_env({
            "TPU_RAG_KV_TIERING": "1",
            "TPU_RAG_KV_TIERING_WARM_BELOW": "0.5",
            "TPU_RAG_KV_TIERING_COLD_BELOW": "0.1",
            "TPU_RAG_KV_TIERING_HALF_LIFE_S": "120",
            "TPU_RAG_KV_TIERING_HOST_MB": "2048",
            "TPU_RAG_KV_TIERING_INTERVAL_S": "2.5",
        })
        t = c.engine.kv_tiering
        assert t.enabled and t.warm_below == 0.5 and t.cold_below == 0.1
        assert t.half_life_s == 120.0 and t.host_spill_mb == 2048
        assert t.retier_interval_s == 2.5
        # off by default; cross-field rules enforced with the env applied
        assert not AppConfig.from_env({}).engine.kv_tiering.enabled
        for bad in (
            {"TPU_RAG_KV_TIERING": "yes"},
            {"TPU_RAG_KV_TIERING_COLD_BELOW": "0.9"},  # > warm_below
            {"TPU_RAG_KV_TIERING_HALF_LIFE_S": "0"},
            {"TPU_RAG_KV_TIERING_HOST_MB": "0"},
        ):
            with pytest.raises(ValueError):
                AppConfig.from_env(bad)

    def test_from_env_resilience_validation(self):
        for bad in (
            {"TPU_RAG_ADMISSION_MAX_CONCURRENCY": "0"},
            {"TPU_RAG_ADMISSION_MAX_QUEUE": "-1"},
            {"TPU_RAG_DEADLINE_MS": "0"},
            {"TPU_RAG_BREAKER_RESETS": "0"},
            {"TPU_RAG_BREAKER_WINDOW_S": "0"},
            {"TPU_RAG_INFLIGHT_RETRIES": "-1"},
        ):
            with pytest.raises(ValueError):
                AppConfig.from_env(bad)


class TestMesh:
    def test_resolved_auto_tp(self):
        assert MeshConfig(dp=2, sp=1, tp=-1).resolved(8) == (2, 1, 4)
        assert MeshConfig().resolved(8) == (1, 1, 8)
        with pytest.raises(ValueError):
            MeshConfig(dp=3, sp=1, tp=-1).resolved(8)

    def test_make_mesh_shapes(self, devices8):
        ctx = make_mesh(MeshConfig(dp=2, sp=1, tp=4), devices=devices8)
        assert ctx.dp == 2 and ctx.sp == 1 and ctx.tp == 4
        assert ctx.n_devices == 8

    def test_sharded_matmul_over_tp(self, mesh_tp8):
        """A TP-sharded matmul must produce identical numerics to unsharded."""
        k = jax.random.PRNGKey(0)
        x = jax.random.normal(k, (16, 64), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 128), jnp.float32)
        ws = jax.device_put(w, mesh_tp8.sharding(None, "tp"))
        xs = jax.device_put(x, mesh_tp8.replicated)

        @jax.jit
        def f(x, w):
            return x @ w

        out = f(xs, ws)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), rtol=1e-4, atol=1e-4)
        # output stays sharded over tp on its last dim
        assert out.sharding.spec == P(None, "tp")

    def test_single_device_mesh(self):
        ctx = single_device_mesh()
        assert ctx.n_devices == 1
        assert ctx.tp == 1
