"""Goodput ledger (ISSUE 14): per-window chip-time attribution,
MFU/roofline accounting, and cost-per-query.

The contracts under test (obs/goodput.py, docs/GOODPUT.md):

- **Conservation**: every ``goodput_window`` event's category chip-ms sum
  to its duration, and N concurrent mixed-length requests' attributed
  chip-seconds sum to the scheduler's own independently-measured busy
  time within 5% — including under preemption and reset recovery, whose
  re-fed prefill lanes attribute to ``preempt_rework`` exactly once.
- **Same report, two sources**: ``GET /debug/goodput`` (live ledger) and
  ``scripts/flightview.py --goodput`` (offline journal reconstruction)
  render through ONE shared function and agree on every figure the
  journal covers.
- **Per-request surfacing**: ``/generate`` timings carry ``chip_ms`` /
  ``goodput_frac`` / ``cost_usd`` and the per-request speculation stats
  (``spec_accept_len_mean``, drafted/accepted counts) that previously
  existed only as EngineStats aggregates.
- **Gating**: ``/debug/goodput`` is 403-unless-armed like every
  ``/debug`` route; the ledger off (TPU_RAG_GOODPUT=0) attributes
  nothing and journals nothing.
"""

import dataclasses
import json
import sys
import threading
from pathlib import Path

import jax
import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    FlightConfig,
    GoodputConfig,
    LlamaConfig,
    SamplingConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.obs import goodput
from rag_llm_k8s_tpu.resilience import faults
from rag_llm_k8s_tpu.server.app import RagService, create_app

from scripts import flightview  # noqa: E402

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=24)
# sync=4: the conservation bound compares per-request sums against the
# scheduler's wall-clock busy timer, which also covers the ledger's own
# ~50µs of post-window bookkeeping per step call — real window shapes
# amortize that; degenerate sub-ms windows would spend the whole 5%
# tolerance on it
PAGED = EngineConfig(
    prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=128,
    kv_paged=True, kv_block_size=16, decode_sync_steps=4,
)
MIXED_PROMPTS = [
    [3, 17, 42, 7, 99], [5, 5, 8], [11] * 12, [2, 9],
    [4] * 20, [7, 8, 9, 10, 11, 12],
]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params


def _roofline():
    return goodput.roofline_for_llama(
        num_layers=2, hidden_size=64, num_heads=4, num_kv_heads=2,
        head_dim=16, intermediate_size=128, vocab_size=256,
    )


# ---------------------------------------------------------------------------
# roofline arithmetic
# ---------------------------------------------------------------------------
class TestRoofline:
    def test_figures_and_ridge(self):
        rf = _roofline()
        assert rf.flops_per_token > 0 and rf.weight_bytes > 0
        assert rf.kv_bytes_per_token > 0
        assert rf.ridge == pytest.approx(rf.peak_flops / rf.peak_bytes)
        # splice weight: a KV copy is cheaper than a forward, never free
        assert 0.0 < rf.splice_weight <= 1.0

    def test_classification_compute_vs_bandwidth(self):
        rf = _roofline()
        # prefill-shaped: many flops per streamed byte → compute-bound
        assert rf.classify(rf.peak_flops, rf.peak_bytes / 100) == "compute"
        # decode-shaped: whole weight stream for one token → bandwidth
        assert rf.classify(rf.flops_per_token, rf.weight_bytes) == "bandwidth"

    def test_int8_variants_change_bytes_not_flops(self):
        base = _roofline()
        w8 = goodput.roofline_for_llama(
            2, 64, 4, 2, 16, 128, 256, weight_bytes_per_param=1.0
        )
        kv8 = goodput.roofline_for_llama(2, 64, 4, 2, 16, 128, 256,
                                         kv_quant="int8")
        assert w8.flops_per_token == base.flops_per_token
        assert w8.weight_bytes == pytest.approx(base.weight_bytes / 2)
        # int8 KV: half payload + fp32 scales — less than bf16, not half
        assert kv8.kv_bytes_per_token < base.kv_bytes_per_token
        assert kv8.kv_bytes_per_token > base.kv_bytes_per_token / 2

    def test_peak_overrides(self):
        rf = goodput.roofline_for_llama(
            2, 64, 4, 2, 16, 128, 256, peak_tflops=100.0, hbm_gbs=500.0
        )
        assert rf.peak_flops == pytest.approx(100e12)
        assert rf.peak_bytes == pytest.approx(500e9)


# ---------------------------------------------------------------------------
# ledger unit semantics
# ---------------------------------------------------------------------------
class TestLedgerUnits:
    def test_decode_window_conserves_and_splits_equally(self):
        led = goodput.GoodputLedger(_roofline())
        w = led.record_decode(0.1, batch=4, steps=2, kept={1: 2, 2: 1})
        cats = sum(w[c] for c in goodput.WINDOW_CATEGORIES if c in w)
        assert cats == pytest.approx(w["dur_ms"], rel=1e-6)
        # 3 useful lanes of 8 → useful frac 3/8 of the window
        assert w["decode_useful"] == pytest.approx(100.0 * 3 / 8, rel=1e-6)
        r1, r2 = led.pop_request(1), led.pop_request(2)
        # equal chip share per active request (d / A)
        assert r1["chip_ms"] == pytest.approx(50.0, rel=1e-6)
        assert r2["chip_ms"] == pytest.approx(50.0, rel=1e-6)
        # request 1 kept 2 of the window's 3 useful lanes
        assert r1["goodput_frac"] > r2["goodput_frac"]
        assert led.pop_request(1) is None  # popped once

    def test_disabled_ledger_records_nothing(self):
        led = goodput.GoodputLedger(_roofline(), enabled=False)
        assert led.record_decode(0.1, 4, 2, {1: 2}) is None
        assert led.pop_request(1) is None
        assert led.state()["busy_s"] == 0.0

    def test_prefill_rework_attributed_not_useful(self):
        led = goodput.GoodputLedger(_roofline())
        w = led.record_prefill(0.1, bucket=16, rows={1: 8, 2: 8},
                               rework={2})
        assert w["prefill_compute"] == pytest.approx(25.0, rel=1e-6)
        assert w["preempt_rework"] == pytest.approx(25.0, rel=1e-6)
        r1, r2 = led.pop_request(1), led.pop_request(2)
        assert r1["chip_ms"] == pytest.approx(r2["chip_ms"])
        assert r1["goodput_frac"] > 0.0
        assert r2["goodput_frac"] == 0.0  # rework earns nothing

    def test_prefill_px_skipped_weighting(self):
        led = goodput.GoodputLedger(_roofline())
        w = led.record_prefill_px(0.1, bucket=8, rid=1, computed=8,
                                  skipped=64)
        assert w["prefill_skipped"] > 0.0
        # splice service is weighted DOWN: 64 skipped tokens must not
        # out-bill the 8 computed ones by their raw count
        assert w["prefill_skipped"] < w["prefill_compute"] * 64 / 8
        cats = sum(w[c] for c in goodput.WINDOW_CATEGORIES if c in w)
        assert cats == pytest.approx(w["dur_ms"], rel=1e-6)

    def test_verify_window_spec_stats_reach_the_request(self):
        led = goodput.GoodputLedger(_roofline())
        led.record_verify(0.1, batch=2, lanes_per_row=5,
                          rows={1: (4, 4, 3), 2: (1, 2, 0)})
        led.record_verify(0.1, batch=2, lanes_per_row=5,
                          rows={1: (2, 3, 1), 2: (1, 0, 0)})
        r1 = led.pop_request(1)
        assert r1["spec_drafted"] == 7 and r1["spec_accepted"] == 4
        assert r1["spec_accept_len_mean"] == pytest.approx(2.0)
        r2 = led.pop_request(2)
        assert r2["spec_drafted"] == 2 and r2["spec_accepted"] == 0
        # row 2 offered drafts in one window only
        assert r2["spec_accept_len_mean"] == pytest.approx(0.0)

    def test_cost_usd_appears_only_when_priced(self):
        led = goodput.GoodputLedger(_roofline(), chip_hour_usd=3.6)
        led.record_decode(1.0, batch=1, steps=1, kept={1: 1})
        r = led.pop_request(1)
        # 1 chip-second at $3.6/hr = $0.001
        assert r["cost_usd"] == pytest.approx(0.001, rel=1e-6)
        led2 = goodput.GoodputLedger(_roofline())
        led2.record_decode(1.0, batch=1, steps=1, kept={1: 1})
        assert "cost_usd" not in led2.pop_request(1)

    def test_merge_and_render(self):
        a, b = goodput.GoodputLedger(_roofline()), goodput.GoodputLedger(_roofline())
        a.record_decode(0.2, 2, 1, {1: 1})
        b.record_prefill(0.1, 16, {2: 8})
        merged = goodput.merge_states([a.state(), b.state()])
        assert merged["busy_s"] == pytest.approx(0.3, rel=1e-6)
        report = goodput.render_report(merged, chip_hour_usd=1.0)
        fracs = sum(
            v["frac"] for c, v in report["categories"].items() if c != "idle"
        )
        assert fracs == pytest.approx(1.0, rel=1e-6)
        assert report["conservation"]["ratio"] == pytest.approx(1.0, rel=1e-6)
        assert set(report["kinds"]) == {"decode", "prefill"}
        assert report["cost"]["chip_hour_usd"] == 1.0


# ---------------------------------------------------------------------------
# the smoke set (make goodput-smoke)
# ---------------------------------------------------------------------------
class TestSmoke:
    def test_conservation_concurrent_mixed_lengths(self, tiny):
        """THE acceptance invariant: N concurrent mixed-length requests
        through the paged scheduler — per-request attributed chip-seconds
        sum to the scheduler's independently measured busy time within
        5%, every goodput_window's categories sum to its duration, and
        the split is non-vacuous (compute, useful decode AND bubble all
        present)."""
        cfg, params = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED, dtypes=FP32
        )
        eng.warmup(batch_sizes=(4,))  # compiles out of the measured span
        seq0 = flight.recorder().events_emitted
        sched = ContinuousScheduler(eng)
        try:
            infos = [dict() for _ in MIXED_PROMPTS]
            outs = [None] * len(MIXED_PROMPTS)

            def run(i):
                outs[i] = sched.submit(
                    MIXED_PROMPTS[i], timeout=120, info=infos[i]
                )

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(MIXED_PROMPTS))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(o is not None for o in outs)
            # per-request figures surfaced through submit(info=)
            total_chip_s = 0.0
            for info in infos:
                gp = info["goodput"]
                assert gp["chip_ms"] > 0
                assert 0.0 < gp["goodput_frac"] <= 1.0
                total_chip_s += gp["chip_ms"] / 1e3
            busy = sched.busy_seconds()
            assert busy > 0
            assert abs(total_chip_s - busy) / busy < 0.05, (
                f"attributed {total_chip_s:.4f}s vs busy {busy:.4f}s"
            )
            # per-window conservation + non-vacuous split, from the journal
            events = [
                e for e in flight.recorder().snapshot(etype="goodput_window")
                if e["seq"] >= seq0
            ]
            assert events, "no goodput_window events journaled"
            seen = {c: 0.0 for c in goodput.WINDOW_CATEGORIES}
            for e in events:
                cats = sum(
                    e.get(c, 0.0) for c in goodput.WINDOW_CATEGORIES
                )
                assert cats == pytest.approx(e["dur_ms"], abs=0.01)
                for c in seen:
                    seen[c] += e.get(c, 0.0)
            assert seen["prefill_compute"] > 0
            assert seen["decode_useful"] > 0
            assert seen["padding_bubble"] > 0
        finally:
            sched.shutdown()

    def test_preemption_rework_attributed_once(self, tiny):
        """Chaos lane: a pool sized to force preemption — the resumed
        request's re-fed admission attributes to preempt_rework, the
        conservation invariant still holds, and rework is counted at
        most once per re-feeding admission (bounded by re-fed tokens)."""
        cfg, params = tiny
        # 8 blocks of 16: two 12-token prompts decoding 24 tokens each
        # must collide mid-decode and preempt (each row grows to 3 blocks)
        tight = dataclasses.replace(PAGED, kv_pool_blocks=8)
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=tight, dtypes=FP32
        )
        eng.warmup(batch_sizes=(4,))
        sched = ContinuousScheduler(eng)
        try:
            prompts = [[11] * 12, [7] * 12, [3] * 12, [9] * 12]
            infos = [dict() for _ in prompts]
            outs = [None] * len(prompts)

            def run(i):
                # a LONG decode (80 tokens → 6 blocks/row vs the 8-block
                # pool) guarantees mid-decode collisions AND builds enough
                # total busy time that host noise (GC pauses, container
                # scheduling) amortizes under the 5% conservation bound
                outs[i] = sched.submit(
                    prompts[i], max_new_tokens=80, timeout=120,
                    info=infos[i],
                )

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(o is not None for o in outs)
            st = eng.ledger.state()
            if eng.stats is not None and eng.kv_pool is not None:
                assert eng.kv_pool.blocks_in_use() == 0
            # the tight pool preempted at least one row → rework attributed
            preempts = flight.recorder().snapshot(etype="preempt")
            if preempts:  # deterministic on this shape, but stay honest
                assert st["categories"]["preempt_rework"] > 0
            total_chip_s = sum(
                i["goodput"]["chip_ms"] / 1e3 for i in infos
            )
            busy = sched.busy_seconds()
            assert abs(total_chip_s - busy) / busy < 0.05
            # never double-counted: rework cannot exceed the whole of
            # admission-window time
            kinds = st["kinds"]
            adm_busy = sum(
                kinds.get(k, {}).get("busy_s", 0.0)
                for k in ("prefill", "prefill_px")
            )
            assert st["categories"]["preempt_rework"] <= adm_busy + 1e-9
        finally:
            sched.shutdown()

    def test_reset_recovery_attributes_rework(self, tiny):
        """An injected decode fault resets the engine; the resubmitted
        request's re-prefill lands in preempt_rework and the request
        still carries a coherent attribution."""
        cfg, params = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED, dtypes=FP32
        )
        sched = ContinuousScheduler(eng, retry_backoff_s=0.0)
        try:
            faults.arm("decode_step", times=1)
            info = {}
            out = sched.submit([3, 17, 42], timeout=120, info=info)
            assert out
            gp = info["goodput"]
            assert gp["chip_ms"] > 0
            st = eng.ledger.state()
            assert st["categories"]["preempt_rework"] > 0
        finally:
            sched.shutdown()

    def test_ledger_off_attributes_nothing(self, tiny):
        cfg, params = tiny
        off = dataclasses.replace(PAGED, goodput=GoodputConfig(enabled=False))
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=off, dtypes=FP32
        )
        seq0 = flight.recorder().events_emitted
        sched = ContinuousScheduler(eng)
        try:
            info = {}
            out = sched.submit([3, 17, 42], timeout=120, info=info)
            assert out
            assert "goodput" not in info
            assert eng.ledger.state()["busy_s"] == 0.0
            assert not [
                e for e in flight.recorder().snapshot(etype="goodput_window")
                if e["seq"] >= seq0
            ]
        finally:
            sched.shutdown()

    def test_debug_goodput_contract(self, goodput_service, monkeypatch):
        """403 unless armed; armed, the report carries the category
        split, roofline kinds and cost block the router consumes."""
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        monkeypatch.delenv("TPU_RAG_DEBUG", raising=False)
        client = create_app(goodput_service).test_client()
        r = client.get("/debug/goodput")
        assert r.status_code == 403
        assert "error" in r.get_json()
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(goodput_service).test_client()
        # serve one query so the report is non-empty — and whichever
        # serving tail takes it (the DEFAULT fused single-fetch path
        # included), its timings must carry the attribution
        r = client.post("/generate", json={"prompt": "alpha"})
        assert r.status_code == 200
        t = r.get_json()["timings"]
        assert t["chip_ms"] > 0 and 0.0 < t["goodput_frac"] <= 1.0
        report = client.get("/debug/goodput").get_json()
        assert report["schema_version"] == 1
        assert set(report["categories"]) == set(goodput.CATEGORIES)
        assert report["busy_s"] > 0
        fracs = sum(
            v["frac"] for c, v in report["categories"].items() if c != "idle"
        )
        assert fracs == pytest.approx(1.0, rel=1e-4)
        assert report["kinds"]  # at least one executable attributed
        for v in report["kinds"].values():
            assert v["bound"] in ("compute", "bandwidth")
        assert "per_query_chip_ms" in report["cost"]
        assert report["conservation"]["ratio"] == pytest.approx(1.0, rel=1e-4)

    def test_flightview_goodput_renders_same_report(self, tiny, tmp_path):
        """The acceptance contract's second half: flightview --goodput
        over a journal dump reproduces the live report's figures for the
        windows the ring covers (one shared renderer)."""
        cfg, params = tiny
        flight.configure(capacity=8192)  # ring must cover the whole run
        try:
            eng = ContinuousEngine(
                cfg, params, sampling=GREEDY, engine_config=PAGED,
                dtypes=FP32,
            )
            sched = ContinuousScheduler(eng)
            try:
                for p in MIXED_PROMPTS[:3]:
                    sched.submit(p, timeout=120)
            finally:
                sched.shutdown()
            live = goodput.render_report(
                eng.ledger.state(), chip_hour_usd=2.0
            )
            bundle = {
                "schema_version": flight.SCHEMA_VERSION,
                "journal": flight.recorder().snapshot(),
            }
            path = tmp_path / "journal.json"
            path.write_text(json.dumps(bundle))
            offline = flightview.build_goodput_report(
                flightview.load_events(bundle), chip_hour_usd=2.0
            )
            # same schema, same figures (event chip-ms rounds at 0.1 µs)
            assert set(offline) == set(live)
            for c in goodput.WINDOW_CATEGORIES:
                assert offline["categories"][c]["chip_s"] == pytest.approx(
                    live["categories"][c]["chip_s"], abs=1e-4
                )
            for kind, lv in live["kinds"].items():
                ov = offline["kinds"][kind]
                assert ov["windows"] == lv["windows"]
                assert ov["tokens"] == lv["tokens"]
                assert ov["mfu"] == pytest.approx(lv["mfu"], rel=0.01)
                assert ov["bound"] == lv["bound"]
            assert offline["cost"]["per_query_chip_ms"]["n"] == 3
            assert offline["cost"]["per_query_chip_ms"]["p50"] > 0
            # the CLI renders both forms standalone
            rc = flightview.main([str(path), "--goodput", "--json",
                                  "--chip-hour-usd", "2.0"])
            assert rc == 0
            rc = flightview.main([str(path), "--goodput"])
            assert rc == 0
        finally:
            flight.configure(capacity=4096)


# ---------------------------------------------------------------------------
# dual-engine debug surfaces (ISSUE 15 satellite): the merged-ledger and
# spool paths were only ever exercised single-engine — pin them with BOTH
# serving engines live and attributed concurrently
# ---------------------------------------------------------------------------
class TestDualEngineDebug:
    def _drive_both_engines(self, svc):
        """Concurrent traffic on BOTH substrates: continuous submits race
        one-shot generates, so each engine's ledger accrues windows in
        the same wall-clock span the merged report covers."""
        errs = []

        def sched_traffic():
            try:
                for i in range(3):
                    svc.scheduler.submit([5 + i, 7, 9, 7, 9], timeout=120)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        def oneshot_traffic():
            try:
                for i in range(2):
                    svc.engine.generate([[3 + i, 8, 11]])
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [
            threading.Thread(target=sched_traffic),
            threading.Thread(target=oneshot_traffic),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs

    def test_debug_goodput_merges_both_engines(
        self, goodput_service, monkeypatch
    ):
        """/debug/goodput from a service running BOTH engines: the merged
        report carries continuous-side kinds (decode/prefill) AND the
        one-shot kind in one consistent rendering, with the category
        fractions still summing to 1 over the merged busy time."""
        svc = goodput_service
        self._drive_both_engines(svc)
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(svc).test_client()
        report = client.get("/debug/goodput").get_json()
        kinds = report["kinds"]
        assert kinds.get("oneshot", {}).get("windows", 0) > 0, (
            "one-shot engine's ledger missing from the merged report"
        )
        assert (
            kinds.get("decode", {}).get("windows", 0) > 0
            or kinds.get("prefill", {}).get("windows", 0) > 0
        ), "continuous engine's ledger missing from the merged report"
        fracs = sum(
            v["frac"] for c, v in report["categories"].items() if c != "idle"
        )
        assert fracs == pytest.approx(1.0, rel=1e-4)
        # busy time merges as a SUM over engines; each engine's own busy
        # is bounded by it
        for e in (svc.engine, svc.scheduler.engine):
            assert e.ledger.state()["busy_s"] <= report["busy_s"] + 1e-9

    def test_debug_incidents_spools_and_serves_with_both_engines(
        self, goodput_service, monkeypatch
    ):
        """/debug/incidents from the same dual-engine service: a bundle
        spooled while both engines journal captures goodput_window events
        from BOTH (oneshot + continuous kinds) in one journal, and the
        spool round-trips it."""
        svc = goodput_service
        self._drive_both_engines(svc)
        bid = svc.record_incident("deadline_exceeded")
        assert bid is not None
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(svc).test_client()
        listing = client.get("/debug/incidents").get_json()["incidents"]
        assert any(i["id"] == bid for i in listing)
        bundle = client.get(f"/debug/incidents?id={bid}").get_json()
        assert bundle["meta"]["engine_mode"] == "continuous"
        gw_kinds = {
            e.get("kind") for e in bundle["journal"]
            if e["type"] == "goodput_window"
        }
        assert "oneshot" in gw_kinds, (
            "bundle journal missing the one-shot engine's windows"
        )
        assert gw_kinds & {"decode", "prefill", "verify"}, (
            "bundle journal missing the continuous engine's windows"
        )


# ---------------------------------------------------------------------------
# per-request speculation stats in /generate timings (satellite)
# ---------------------------------------------------------------------------
class TestSpecStats:
    def test_spec_counts_surface_per_request(self, tiny):
        cfg, params = tiny
        spec = dataclasses.replace(
            PAGED, spec_paged=True, spec_paged_tokens=4, decode_sync_steps=1,
        )
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=spec, dtypes=FP32
        )
        sched = ContinuousScheduler(eng)
        try:
            info = {}
            # repeat-heavy prompt: prompt-lookup fires (the RAG shape)
            out = sched.submit(
                [3, 17, 42, 3, 17, 42, 3, 17], timeout=120, info=info
            )
            assert out
            gp = info["goodput"]
            assert gp["spec_drafted"] > 0, "no draft ever offered"
            assert gp["spec_accepted"] >= 0
            assert gp["spec_accept_len_mean"] >= 0.0
            # the aggregate stats and the per-request stats see the same
            # engine: a lone request's drafts ARE the engine's drafts
            assert gp["spec_drafted"] == eng.stats.spec_drafted_tokens
            assert gp["spec_accepted"] == eng.stats.spec_accepted_tokens
        finally:
            sched.shutdown()


# ---------------------------------------------------------------------------
# one-shot engine windows
# ---------------------------------------------------------------------------
class TestOneShot:
    def test_generate_records_oneshot_window_and_info(self, tiny):
        cfg, params = tiny
        eng = InferenceEngine(
            cfg, params, sampling=GREEDY,
            engine_config=EngineConfig(
                prompt_buckets=(16, 32), max_batch_size=2, max_seq_len=64,
                goodput=GoodputConfig(chip_hour_usd=3.6),
            ),
            dtypes=FP32,
        )
        info = {}
        out = eng.generate([[3, 17, 42, 7]], info=info)[0]
        assert out
        gp = info["goodput"]
        assert gp["chip_ms"] > 0
        assert 0.0 < gp["goodput_frac"] <= 1.0
        assert gp["cost_usd"] > 0
        st = eng.ledger.state()
        assert st["kinds"]["oneshot"]["windows"] == 1
        # the fused call split: both prefill and decode shares attributed
        cats = st["categories"]
        assert cats["prefill_compute"] > 0 and cats["decode_useful"] > 0


# ---------------------------------------------------------------------------
# config env round-trip
# ---------------------------------------------------------------------------
class TestConfig:
    def test_env_round_trip(self):
        cfg = AppConfig.from_env({
            "TPU_RAG_GOODPUT": "0",
            "TPU_RAG_CHIP_HOUR_USD": "4.2",
            "TPU_RAG_GOODPUT_PEAK_TFLOPS": "197",
            "TPU_RAG_GOODPUT_HBM_GBS": "819",
        })
        gp = cfg.engine.goodput
        assert gp.enabled is False
        assert gp.chip_hour_usd == pytest.approx(4.2)
        assert gp.peak_tflops == pytest.approx(197.0)
        assert gp.hbm_gbs == pytest.approx(819.0)

    def test_defaults_on(self):
        gp = AppConfig.from_env({}).engine.goodput
        assert gp.enabled is True
        assert gp.chip_hour_usd == 0.0

    @pytest.mark.parametrize("env", [
        {"TPU_RAG_GOODPUT": "yes"},
        {"TPU_RAG_CHIP_HOUR_USD": "-1"},
        {"TPU_RAG_GOODPUT_PEAK_TFLOPS": "-5"},
    ])
    def test_invalid_values_raise(self, env):
        with pytest.raises(ValueError):
            AppConfig.from_env(env)


# ---------------------------------------------------------------------------
# service fixture (the /debug/goodput contract test)
# ---------------------------------------------------------------------------
class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode(
            "utf-8", "replace"
        )


@pytest.fixture(scope="module")
def goodput_service(tmp_path_factory):
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(
        model=llama_cfg, encoder=enc_cfg,
        flight=FlightConfig(
            spool_dir=str(tmp_path_factory.mktemp("spool")), cooldown_s=0.0,
        ),
        system_message="Use the context.",
    )
    params = init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32)
    engine = InferenceEngine(
        llama_cfg, params,
        sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
        engine_config=EngineConfig(
            prompt_buckets=(128, 256), max_batch_size=2, max_seq_len=512,
        ),
        dtypes=FP32,
    )
    ceng = ContinuousEngine(
        llama_cfg, params,
        sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
        engine_config=EngineConfig(
            prompt_buckets=(64, 256), max_batch_size=4, max_seq_len=320,
        ),
        dtypes=FP32,
    )
    sched = ContinuousScheduler(ceng, retry_backoff_s=0.0)
    encoder = EncoderRunner(
        enc_cfg, init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32, 64), max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size)
    svc = RagService(
        cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store,
        scheduler=sched,
    )
    svc.ready = True
    texts = ["alpha beta gamma", "delta epsilon zeta"]
    vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
    store.add(list(vecs), [
        {"filename": "f", "chunk_id": i, "text": t}
        for i, t in enumerate(texts)
    ])
    yield svc
    svc.shutdown()
