"""kNN kernel numerics (interpret mode vs oracles) + vector store semantics."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.ops.knn import BIG, knn_topk_pallas, knn_topk_xla


def _random_problem(seed, N=1024, D=64, Q=4, n_valid=None):
    rng = np.random.RandomState(seed)
    e = rng.randn(N, D).astype(np.float32)
    q = rng.randn(Q, D).astype(np.float32)
    n_valid = N if n_valid is None else n_valid
    norms = (e**2).sum(1)
    norms[n_valid:] = BIG
    return q, e, norms, n_valid


class TestKnnKernel:
    @pytest.mark.parametrize("n_valid", [1024, 1000, 700])
    def test_pallas_matches_numpy_oracle(self, n_valid):
        q, e, norms, nv = _random_problem(0, n_valid=n_valid)
        pv, pi = knn_topk_pallas(
            jnp.asarray(q), jnp.asarray(e), jnp.asarray(norms)[None, :],
            k=5, block_n=256, interpret=True,
        )
        d = ((q[:, None, :] - e[None, :nv, :]) ** 2).sum(-1)
        oracle_idx = np.argsort(d, axis=1)[:, :5]
        np.testing.assert_array_equal(np.asarray(pi), oracle_idx)
        np.testing.assert_allclose(
            np.asarray(pv), np.take_along_axis(d, oracle_idx, 1), rtol=1e-4, atol=1e-3
        )

    def test_xla_fallback_matches_oracle(self):
        q, e, norms, nv = _random_problem(1)
        ev, ei = knn_topk_xla(jnp.asarray(q), jnp.asarray(e), jnp.asarray(norms)[None, :], k=5)
        d = ((q[:, None, :] - e[None, :, :]) ** 2).sum(-1)
        oracle_idx = np.argsort(d, axis=1)[:, :5]
        np.testing.assert_array_equal(np.asarray(ei), oracle_idx)

    def test_single_query_single_block(self):
        q, e, norms, _ = _random_problem(2, N=256, Q=1)
        pv, pi = knn_topk_pallas(
            jnp.asarray(q), jnp.asarray(e), jnp.asarray(norms)[None, :],
            k=5, block_n=256, interpret=True,
        )
        ev, ei = knn_topk_xla(jnp.asarray(q), jnp.asarray(e), jnp.asarray(norms)[None, :], k=5)
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(ei))


class TestVectorStore:
    def _mk(self, n=10, dim=8, path=None, seed=0):
        rng = np.random.RandomState(seed)
        store = VectorStore(dim=dim, path=path)
        vecs = rng.randn(n, dim).astype(np.float32)
        meta = [{"filename": "a.pdf", "chunk_id": i, "text": f"chunk {i}"} for i in range(n)]
        assert store.add(vecs, meta) == n
        return store, vecs, meta

    def test_search_returns_nearest(self):
        store, vecs, meta = self._mk()
        res = store.search(vecs[3], k=3)
        assert res[0].metadata["chunk_id"] == 3
        assert res[0].distance == pytest.approx(0.0, abs=1e-4)
        assert len(res) == 3

    def test_search_k_clamped_to_size(self):
        store, vecs, _ = self._mk(n=2)
        assert len(store.search(vecs[0], k=5)) == 2

    def test_empty_store_search(self):
        store = VectorStore(dim=8)
        assert store.search(np.zeros(8)) == []

    def test_dedup_idempotent_reingest(self):
        """The reference duplicates every chunk on pod restart (survey §3.1);
        re-adding identical content must be a no-op here."""
        store, vecs, meta = self._mk()
        assert store.add(vecs, meta) == 0
        assert store.ntotal == 10

    def test_dim_mismatch_rejected(self):
        store = VectorStore(dim=8)
        with pytest.raises(ValueError, match="dim"):
            store.add([np.zeros(4, np.float32)], [{"text": "x"}])

    def test_save_load_roundtrip(self, tmp_path):
        p = str(tmp_path / "idx")
        store, vecs, meta = self._mk(path=p)
        store.save()
        loaded = VectorStore.load(p)
        assert loaded.ntotal == 10
        assert loaded.generation == store.generation
        r1 = store.search(vecs[5], k=2)
        r2 = loaded.search(vecs[5], k=2)
        assert [x.metadata for x in r1] == [y.metadata for y in r2]
        # dedup state survives persistence
        assert loaded.add(vecs, meta) == 0

    def test_open_or_create(self, tmp_path):
        p = str(tmp_path / "idx")
        s = VectorStore.open_or_create(p, dim=8)
        assert s.ntotal == 0
        s.add([np.ones(8, np.float32)], [{"text": "t"}])
        s.save()
        s2 = VectorStore.open_or_create(p, dim=8)
        assert s2.ntotal == 1

    def test_corrupt_metadata_rejected(self, tmp_path):
        p = str(tmp_path / "idx")
        store, _, _ = self._mk(path=p)
        store.save()
        with open(p) as f:
            meta = json.load(f)
        meta["count"] = 99
        with open(p, "w") as f:
            json.dump(meta, f)
        with pytest.raises(ValueError, match="corrupt"):
            VectorStore.load(p)

    def test_native_codec_writes_checksummed_payload(self, tmp_path):
        """The C++ snapshot codec (native/indexio.cpp) is the payload
        writer when the toolchain is present: magic header + CRC."""
        from rag_llm_k8s_tpu.index.store import _indexio

        if _indexio() is None:
            pytest.skip("no C++ toolchain")
        p = str(tmp_path / "idx")
        store, vecs, _ = self._mk(path=p)
        store.save()
        with open(p + ".vectors.npy", "rb") as f:
            assert f.read(8) == b"TPURIDX1"
        with open(p) as f:
            assert json.load(f)["vector_format"] == "indexio"
        loaded = VectorStore.load(p)
        np.testing.assert_array_equal(loaded._vectors, store._vectors)

    def test_payload_corruption_detected_by_crc(self, tmp_path):
        """A flipped payload byte fails the CRC on load — faiss's writer and
        np.save would both return silently corrupted vectors here."""
        from rag_llm_k8s_tpu.index.store import _indexio

        if _indexio() is None:
            pytest.skip("no C++ toolchain")
        p = str(tmp_path / "idx")
        store, _, _ = self._mk(path=p)
        store.save()
        vec_path = p + ".vectors.npy"
        data = bytearray(open(vec_path, "rb").read())
        data[60] ^= 0xFF  # one payload byte (header is 48 bytes)
        open(vec_path, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="CRC|corrupt"):
            VectorStore.load(p)

    def test_header_corruption_rejected_before_allocation(self, tmp_path):
        """The CRC covers the payload only — a corrupted header (count vs
        payload_bytes mismatch) must raise cleanly, never size the read
        buffer (heap-overflow vector)."""
        import struct

        from rag_llm_k8s_tpu.index.store import _indexio

        if _indexio() is None:
            pytest.skip("no C++ toolchain")
        p = str(tmp_path / "idx")
        store, _, _ = self._mk(path=p)
        store.save()
        vec_path = p + ".vectors.npy"
        data = bytearray(open(vec_path, "rb").read())
        data[16:24] = struct.pack("<q", 1 << 40)  # count field
        open(vec_path, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="inconsistent|corrupt"):
            VectorStore.load(p)

    def test_npy_snapshots_still_load(self, tmp_path):
        """Back-compat: pre-codec snapshots (plain .npy payload) load."""
        p = str(tmp_path / "idx")
        store, vecs, _ = self._mk(path=p)
        store.save()
        # overwrite the payload with the legacy npy format
        np.save(open(p + ".vectors.npy", "wb"), store._vectors)
        loaded = VectorStore.load(p)
        assert loaded.ntotal == store.ntotal
        np.testing.assert_array_equal(loaded._vectors, store._vectors)

    def test_empty_store_roundtrips_through_codec(self, tmp_path):
        p = str(tmp_path / "idx")
        s = VectorStore(dim=8, path=p)
        s.save()
        assert VectorStore.load(p).ntotal == 0

    def test_info_shape(self):
        store, _, _ = self._mk()
        info = store.info()
        assert info["total_vectors"] == 10
        assert info["dimension"] == 8
        assert len(info["sample_chunks"]) == 5

    def test_concurrent_adds_no_loss(self):
        """The race the reference has at rag.py:68-86: concurrent ingest must
        not lose vectors."""
        store = VectorStore(dim=8)
        rng = np.random.RandomState(7)
        batches = [
            (
                rng.randn(5, 8).astype(np.float32),
                [{"filename": f"f{t}.pdf", "chunk_id": i, "text": f"{t}-{i}"} for i in range(5)],
            )
            for t in range(8)
        ]
        threads = [
            threading.Thread(target=lambda b=b: store.add(b[0], b[1])) for b in batches
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert store.ntotal == 40

    def test_grow_across_pad_bucket(self):
        """Index growth past the padding bucket keeps search correct."""
        rng = np.random.RandomState(3)
        store = VectorStore(dim=8)
        v1 = rng.randn(500, 8).astype(np.float32)
        store.add(v1, [{"text": f"a{i}"} for i in range(500)])
        _ = store.search(v1[0], k=1)  # builds 512-pad snapshot
        v2 = rng.randn(50, 8).astype(np.float32)
        store.add(v2, [{"text": f"b{i}"} for i in range(50)])
        res = store.search(v2[10], k=1)  # needs 1024-pad snapshot
        assert res[0].metadata["text"] == "b10"


class TestIncrementalDeviceIndex:
    def test_adds_within_bucket_are_row_updates(self):
        """Ingest must not re-upload the whole padded matrix per add: once a
        snapshot exists, in-bucket adds transfer only the new rows."""
        rng = np.random.RandomState(7)
        store = VectorStore(dim=8)
        v0 = rng.randn(20, 8).astype(np.float32)
        store.add(v0, [{"text": f"a{i}"} for i in range(20)])
        _ = store.search(v0[0], k=1)  # materializes the 512-pad snapshot
        assert store.transfer_stats == {"row_update_batches": 0, "full_uploads": 1}

        for b in range(5):  # five more batches, all within the 512 bucket
            vb = rng.randn(30, 8).astype(np.float32)
            store.add(vb, [{"text": f"b{b}_{i}"} for i in range(30)])
            last = vb[-1]
        assert store.transfer_stats["row_update_batches"] == 5
        assert store.transfer_stats["full_uploads"] == 1  # no re-uploads

        # and the in-place snapshot ranks exactly like a fresh rebuild
        res = store.search(last, k=3)
        assert res[0].metadata["text"] == "b4_29"
        fresh = VectorStore(dim=8)
        fresh.add(np.asarray(store._vectors), [dict(m) for m in store._metadata])
        want = fresh.search(last, k=3)
        assert [r.metadata["text"] for r in res] == [r.metadata["text"] for r in want]
        assert [r.distance for r in res] == pytest.approx([r.distance for r in want])

    def test_bucket_growth_triggers_one_full_upload(self):
        rng = np.random.RandomState(8)
        store = VectorStore(dim=8)
        store.add(rng.randn(500, 8).astype(np.float32),
                  [{"text": f"a{i}"} for i in range(500)])
        _ = store.search(np.zeros(8, np.float32), k=1)
        v2 = rng.randn(50, 8).astype(np.float32)
        store.add(v2, [{"text": f"b{i}"} for i in range(50)])  # outgrows 512
        res = store.search(v2[10], k=1)
        assert res[0].metadata["text"] == "b10"
        assert store.transfer_stats["full_uploads"] == 2
        assert store.transfer_stats["row_update_batches"] == 0


class TestCorpusScale:
    """Retrieval at corpus scale (VERDICT r3 #5): ingest to N >= 100k in
    batches, assert transfers stay O(batch) with O(log N) full uploads, and
    ranking stays exact vs the numpy oracle. (faiss IndexFlatL2 — rag.py:61 —
    shrugs at this scale; the device index must too.)"""

    def test_100k_ingest_bucket_growth_and_exactness(self):
        rng = np.random.RandomState(11)
        D, BATCH, NBATCH = 16, 4096, 25  # 102_400 vectors
        store = VectorStore(dim=D)
        _ = store.search(np.zeros(D, np.float32), k=1)  # materialize early
        for b in range(NBATCH):
            vb = rng.randn(BATCH, D).astype(np.float32)
            store.add(vb, [{"text": f"b{b}_{i}"} for i in range(BATCH)])
            # touch the snapshot each batch (as serving does between ingests)
            store.device_snapshot()
        N = store.ntotal
        assert N == BATCH * NBATCH
        # transfers: one row-update per in-bucket batch; a full re-upload only
        # on the O(log N) bucket growths (512 -> 131072 is 8 doublings; +1
        # initial + 1 final-bucket rebuild tolerance)
        growths = int(np.log2(131072 // 512))
        stats = store.transfer_stats
        assert stats["row_update_batches"] + stats["full_uploads"] <= NBATCH + growths + 2
        assert stats["full_uploads"] <= growths + 2
        assert stats["row_update_batches"] >= NBATCH - growths - 1

        # exactness at scale: top-5 matches brute-force numpy on 3 queries
        V = np.asarray(store._vectors)
        for qi in (0, 7, 31):
            q = V[qi * 100] + rng.randn(D).astype(np.float32) * 0.01
            got = store.search(q, k=5)
            d = ((V - q[None, :]) ** 2).sum(axis=1)
            want = np.argsort(d, kind="stable")[:5]
            assert [r.metadata["text"] for r in got] == [
                store._metadata[int(i)]["text"] for i in want
            ]
            np.testing.assert_allclose(
                [r.distance for r in got], d[want], rtol=1e-4, atol=1e-4
            )
