"""70B-geometry streaming-load proof (CI-sized).

Llama-3.1-70B is the family's tp=8 deployment (`LlamaConfig.llama_3_1_70b`:
hidden 8192, intermediate 28672, 64 q / 8 kv heads — every sharded dim
divides a v5e-8 exactly, like 8B). One TRUE-shape layer (~6 GB bf16 on
disk) streams through the loader in the int8 deployment mode
(`quant="int8"`, the ~9 GB/chip configuration from the config docstring):
tensors must arrive TP-sharded in the quantized layout without the bf16
tree ever materializing, and the loaded tree must run a forward.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
from rag_llm_k8s_tpu.models.llama import LlamaModel, make_kv_cache
from rag_llm_k8s_tpu.models.loader import load_safetensors_params
from rag_llm_k8s_tpu.parallel.sharding import make_streaming_put
from rag_llm_k8s_tpu.utils.synth import write_synth_checkpoint

CFG_70B_L1 = dataclasses.replace(LlamaConfig.llama_3_1_70b(), num_layers=1)


@pytest.fixture(scope="module")
def synth_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("synth70b")
    write_synth_checkpoint(str(out), CFG_70B_L1, n_shards=2)
    return str(out)


class TestStreaming70B:
    def test_int8_streamed_load_is_sharded_and_quantized(self, synth_dir, mesh_tp8):
        import resource

        import psutil

        peak_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        put = make_streaming_put(mesh_tp8, dtype=jnp.bfloat16)
        params = load_safetensors_params(
            synth_dir, CFG_70B_L1, DTypePolicy(), put=put, quant="int8"
        )
        rss_after = psutil.Process().memory_info().rss
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        wq = params["layers"]["attn"]["wq"]
        assert wq["kernel_q"].dtype == jnp.int8
        assert wq["kernel_q"].shape == (1, 8192, 64 * 128)
        assert "tp" in str(wq["kernel_q"].sharding.spec)
        assert wq["qscale"].dtype == jnp.float32
        gate = params["layers"]["mlp"]["w_gate"]
        assert gate["kernel_q"].shape == (1, 8192, 28672)
        # EVERY projection group must be quantized — a per-group dtype check
        # (the byte bound alone can't see one small group slipping to bf16)
        for grp, names in (("attn", ("wq", "wk", "wv", "wo")),
                           ("mlp", ("w_gate", "w_up", "w_down"))):
            for name in names:
                sub = params["layers"][grp][name]
                assert sub["kernel_q"].dtype == jnp.int8, (grp, name)
                assert sub["qscale"].dtype == jnp.float32, (grp, name)
                assert "kernel" not in sub, (grp, name)
        assert params["lm_head_q"].dtype == jnp.int8  # 70B is untied
        assert params["embedding"].dtype == jnp.bfloat16  # gather-only
        # int8 halves the placed bytes vs the ~5.5 GiB bf16 layer-1 tree
        # (embedding stays bf16 by design): ~3.7 GiB actual. The bound must
        # sit BELOW the bf16 figure or a silently-skipped quantization of
        # any kernel group would still pass.
        dev_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree.leaves(params)
            if hasattr(x, "dtype")
        )
        assert dev_bytes < 4.5 * (1 << 30), f"{dev_bytes / (1 << 30):.2f} GiB"

        # streaming claim (same contract test_loader_8b.py pins): the
        # TRANSIENT host overhead above the final resident set stays at a
        # few vocab-sized tensors, never the whole bf16 checkpoint
        embed_bytes = CFG_70B_L1.vocab_size * CFG_70B_L1.hidden_size * 2
        transient = peak - max(rss_after, peak_before)
        assert transient < 3 * embed_bytes + 512 * (1 << 20), (
            f"transient host overhead {transient / (1 << 30):.2f} GiB suggests "
            "the loader materialized more than a streamed group"
        )

        # the loaded quantized tree must drive a forward end to end
        model = LlamaModel(CFG_70B_L1, DTypePolicy(), attn_impl="xla", quantized=True)
        B, S = 1, 4
        cache = make_kv_cache(CFG_70B_L1, B, S, jnp.bfloat16)
        logits, _ = model.apply(
            {"params": params},
            jnp.zeros((B, S), jnp.int32),
            jnp.broadcast_to(jnp.arange(S), (B, S)),
            cache,
            jnp.zeros((B,), jnp.int32),
            jnp.full((B,), S, jnp.int32),
            jnp.int32(0),
        )
        assert logits.shape == (B, S, CFG_70B_L1.vocab_size)
        assert bool(jnp.all(jnp.isfinite(logits)))
