"""Tenant-dimensional observability (ISSUE 18, docs/OBSERVABILITY.md
"Tenant attribution").

- **Cardinality is bounded by construction**: the TenantTracker interns
  every raw tenant id before it may become a label value — a 10k-unique-id
  churn storm leaves at most ``top_k`` tracked names + ``__other__`` on
  every bound family, demotions prune synchronously, a cold tenant that
  turns hot re-promotes, and concurrent interns racing a scrape-side
  prune never break the bound.
- **Conservation**: a 3-tenant workload through the paged continuous
  scheduler books per-tenant chip-seconds whose sum tracks the
  scheduler's independently measured busy time within 5% — attribution
  adds a dimension, never invents or loses chip time.
- **Same report, two sources**: ``GET /debug/tenants`` (live journal
  snapshot) and ``scripts/flightview.py --tenants`` (offline journal)
  render through the SAME stdlib-only module (obs/tenants.py) and are
  byte-identical — proven with the offline half run in a subprocess
  whose ``jax`` import is poisoned.
- **Prometheus HELP escaping**: backslash + newline only, per the text
  exposition spec — a multi-line help string must never split a comment
  into a line the scraper rejects.
- **Replay**: the trace record preserves ``tenant`` and the lockstep
  driver forwards it into its re-driven submits — a re-driven journal
  prices per tenant exactly like the recording.

``make tenants-smoke`` runs TestTenantsSmoke; the full matrix runs under
tier1.
"""

import json
import re
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from rag_llm_k8s_tpu.core.config import (
    AppConfig,
    DTypePolicy,
    EncoderConfig,
    EngineConfig,
    FlightConfig,
    LlamaConfig,
    SamplingConfig,
    TenantConfig,
)
from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine, ContinuousScheduler
from rag_llm_k8s_tpu.engine.encoder import EncoderRunner
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.index.store import VectorStore
from rag_llm_k8s_tpu.models.bge_m3 import init_encoder_params
from rag_llm_k8s_tpu.models.llama import init_llama_params
from rag_llm_k8s_tpu.obs import flight
from rag_llm_k8s_tpu.obs import metrics as obs_metrics
from rag_llm_k8s_tpu.obs import tenants as obs_tenants
from rag_llm_k8s_tpu.sim import replay
from rag_llm_k8s_tpu.server.app import RagService, create_app

from scripts import flightview  # noqa: E402

FP32 = DTypePolicy.fp32()
GREEDY = SamplingConfig(do_sample=False, max_new_tokens=8)
# sync=4 mirrors test_goodput's conservation config: real window shapes
# amortize the ledger's per-step bookkeeping so the 5% bound judges
# attribution, not degenerate sub-ms windows
PAGED = EngineConfig(
    prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=128,
    kv_paged=True, kv_block_size=16, decode_sync_steps=4,
)
OTHER = obs_metrics.TenantTracker.OTHER


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params


def _tenant_children(fam):
    """The distinct ``tenant`` label values currently held by a family."""
    return {dict(labels).get("tenant") for labels, _ in fam.items()}


# ---------------------------------------------------------------------------
# Prometheus HELP escaping (exposition grammar)
# ---------------------------------------------------------------------------
class TestHelpEscaping:
    def test_backslash_and_newline_escaped_quotes_literal(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter(
            "rag_esc_total",
            'line one\nline two with \\ backslash and "quotes"',
        ).inc()
        text = reg.render_prometheus()
        helps = [
            ln for ln in text.splitlines()
            if ln.startswith("# HELP rag_esc_total")
        ]
        # ONLY backslash and newline escape in HELP (the spec); quotes
        # stay literal — label-value escaping must not leak in here
        assert helps == [
            "# HELP rag_esc_total line one\\nline two with "
            '\\\\ backslash and "quotes"'
        ]

    def test_exposition_grammar_holds_with_hostile_help(self):
        """Every line of an exposition carrying newline/backslash help is
        a well-formed comment or sample — nothing splits mid-line."""
        reg = obs_metrics.MetricsRegistry()
        reg.counter("rag_g1_total", "a\nb").inc(2)
        reg.gauge("rag_g2", "c\\d").inc(1)
        fam = reg.labeled_histogram("rag_g3_seconds", "e\nf\\g",
                                    buckets=(0.1, 1.0))
        fam.labels(tenant="t").observe(0.5)
        comment = re.compile(r"^# (HELP|TYPE) [A-Za-z_:][A-Za-z0-9_:]* .+$")
        sample = re.compile(
            r"^[A-Za-z_:][A-Za-z0-9_:]*(\{[^{}\n]*\})? "
            r"(-?[0-9][0-9eE.+-]*|[+-]Inf|nan)$"
        )
        for ln in reg.render_prometheus().splitlines():
            if not ln:
                continue
            assert comment.match(ln) or sample.match(ln), ln


# ---------------------------------------------------------------------------
# the cardinality-bounded tracker
# ---------------------------------------------------------------------------
class TestTenantTracker:
    def test_hot_tenants_keep_names_cold_fold_to_other(self):
        trk = obs_metrics.TenantTracker(top_k=2)
        for _ in range(5):
            assert trk.intern("a") == "a"
        for _ in range(3):
            assert trk.intern("b") == "b"
        # the third distinct tenant can't displace a (5) or b (3) at count 1
        assert trk.intern("c") == OTHER
        assert trk.tracked() == ("a", "b")

    def test_cold_tenant_repromotes_when_it_turns_hot(self):
        trk = obs_metrics.TenantTracker(top_k=2)
        for _ in range(10):
            trk.intern("a")
        for _ in range(5):
            trk.intern("b")
        # c rides __other__ until its count STRICTLY passes the tracked
        # minimum (ties keep the incumbent — no exposition flapping)
        outs = [trk.intern("c") for _ in range(6)]
        assert outs[:-1] == [OTHER] * 5
        assert outs[-1] == "c"
        assert trk.tracked() == ("a", "c")

    def test_other_can_never_be_impersonated(self):
        trk = obs_metrics.TenantTracker(top_k=2)
        for _ in range(50):
            assert trk.intern(OTHER) == OTHER
        assert trk.tracked() == ()

    def test_churn_storm_bound_on_bound_family(self):
        """10k unique ids against K=4: the bound family ends with at most
        K tracked children + __other__, and every intern returned either
        a currently-tracked name or __other__."""
        reg = obs_metrics.MetricsRegistry()
        fam = reg.labeled_counter("rag_tenant_storm_total", "churn")
        trk = obs_metrics.TenantTracker(top_k=4)
        trk.bind(fam)
        hot = [f"team-{i}" for i in range(4)]
        for name in hot:
            for _ in range(100):
                fam.labels(tenant=trk.intern(name)).inc()
        for i in range(10_000):
            label = trk.intern(f"drive-by-{i}")
            fam.labels(tenant=label).inc()
        trk.prune()
        assert set(trk.tracked()) == set(hot)
        children = _tenant_children(fam)
        assert len(children) <= trk.top_k + 1
        assert children <= set(hot) | {OTHER}
        snap = trk.snapshot()
        assert snap["table_size"] <= trk.capacity
        assert snap["tracked"] == sorted(hot)

    def test_demotion_prunes_bound_family_synchronously(self):
        reg = obs_metrics.MetricsRegistry()
        fam = reg.labeled_counter("rag_tenant_demote_total", "demote")
        trk = obs_metrics.TenantTracker(top_k=1)
        trk.bind(fam)
        fam.labels(tenant=trk.intern("a")).inc()
        assert "a" in _tenant_children(fam)
        # b overtakes a: the demotion prunes a's series inside intern()
        for _ in range(3):
            label = trk.intern("b")
            fam.labels(tenant=label).inc()
        assert trk.tracked() == ("b",)
        children = _tenant_children(fam)
        assert "a" not in children
        assert children <= {"b", OTHER}

    def test_concurrent_interns_racing_scrape_prune_keep_bound(self):
        """Worker threads intern churning ids while a scrape thread
        prunes/snapshots — no exceptions, and the final pruned family
        holds at most K+1 tenant children."""
        reg = obs_metrics.MetricsRegistry()
        fam = reg.labeled_counter("rag_tenant_race_total", "race")
        trk = obs_metrics.TenantTracker(top_k=4)
        trk.bind(fam)
        errs = []
        stop = threading.Event()

        def worker(base):
            try:
                for i in range(2000):
                    name = f"w{base}-{i % (5 + base)}"
                    fam.labels(tenant=trk.intern(name)).inc()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def scraper():
            try:
                while not stop.is_set():
                    trk.prune()
                    trk.snapshot()
                    reg.render_prometheus()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(b,))
                   for b in range(4)]
        st = threading.Thread(target=scraper)
        st.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        stop.set()
        st.join(timeout=60)
        assert not errs
        trk.prune()
        assert len(_tenant_children(fam)) <= trk.top_k + 1

    def test_invalid_construction_raises(self):
        with pytest.raises(ValueError):
            obs_metrics.TenantTracker(top_k=0)
        with pytest.raises(ValueError):
            obs_metrics.TenantTracker(top_k=8, capacity=4)


# ---------------------------------------------------------------------------
# the pure renderer (obs/tenants.py)
# ---------------------------------------------------------------------------
class TestTenantsReport:
    def _events(self):
        return [
            {"seq": 1, "t": 10.0, "type": "arrival", "rid": 1,
             "tenant": "a", "prompt_len": 4, "max_new": 8},
            {"seq": 2, "t": 10.1, "type": "admit", "rid": 1, "slot": 0},
            {"seq": 3, "t": 10.2, "type": "sync_window_open", "steps": 4},
            {"seq": 4, "t": 10.5, "type": "complete", "rid": 1,
             "n_tokens": 5, "chip_ms": 2000.0, "cost_usd": 0.01},
            {"seq": 5, "t": 10.6, "type": "shed", "tenant": "b",
             "reason": "queue_full", "status": 429},
            {"seq": 6, "t": 10.7, "type": "shadow_audit", "rid": 1,
             "outcome": "diverged", "n": 5},
        ]

    def test_rid_resolution_and_row_figures(self):
        rep = obs_tenants.render_report(
            obs_tenants.state_from_events(self._events())
        )
        rows = {r["tenant"]: r for r in rep["tenants"]}
        a = rows["a"]
        # admit/complete/shadow_audit carried only rid — the arrival's
        # tenant seeds the rid map everything later resolves through
        assert a["arrivals"] == 1 and a["admitted"] == 1
        assert a["completed"] == 1 and a["tokens"] == 5
        assert a["chip_s"] == pytest.approx(2.0)
        assert a["cost_usd"] == pytest.approx(0.01)
        assert a["audits"] == 1 and a["diverged"] == 1
        assert a["chip_share"] == pytest.approx(1.0)
        assert rows["b"]["sheds"] == 1
        assert rep["totals"]["tenants"] == 2
        assert rep["totals"]["chip_s"] == pytest.approx(2.0)
        assert rep["wall_s"] == pytest.approx(0.7)

    def test_untagged_events_fold_to_anon(self):
        evs = [
            {"seq": 1, "t": 0.0, "type": "arrival", "rid": 7,
             "prompt_len": 2, "max_new": 4},
            {"seq": 2, "t": 0.1, "type": "complete", "rid": 7,
             "n_tokens": 4, "chip_ms": 100.0},
        ]
        rep = obs_tenants.render_report(obs_tenants.state_from_events(evs))
        assert [r["tenant"] for r in rep["tenants"]] == ["anon"]
        assert rep["tenants"][0]["completed"] == 1

    def test_cost_derived_from_chip_seconds_when_unpriced(self):
        evs = [
            {"seq": 1, "t": 0.0, "type": "arrival", "rid": 1, "tenant": "a"},
            {"seq": 2, "t": 0.1, "type": "complete", "rid": 1,
             "n_tokens": 3, "chip_ms": 1800.0},
        ]
        rep = obs_tenants.render_report(
            obs_tenants.state_from_events(evs), chip_hour_usd=3600.0
        )
        assert rep["tenants"][0]["cost_usd"] == pytest.approx(1.8)
        assert rep["totals"]["cost_usd"] == pytest.approx(1.8)

    def test_rows_sorted_by_chip_then_name(self):
        evs = []
        for i, (tn, ms) in enumerate(
            [("x", 100.0), ("y", 300.0), ("w", 100.0)]
        ):
            evs.append({"seq": 2 * i, "t": float(i), "type": "arrival",
                        "rid": i, "tenant": tn})
            evs.append({"seq": 2 * i + 1, "t": float(i), "type": "complete",
                        "rid": i, "n_tokens": 1, "chip_ms": ms})
        rep = obs_tenants.render_report(obs_tenants.state_from_events(evs))
        assert [r["tenant"] for r in rep["tenants"]] == ["y", "w", "x"]


# ---------------------------------------------------------------------------
# config round-trip
# ---------------------------------------------------------------------------
class TestTenantConfig:
    def test_defaults_on(self):
        cfg = AppConfig.from_env({})
        assert cfg.tenants.enabled is True
        assert cfg.tenants.top_k == 8

    def test_env_round_trip(self):
        cfg = AppConfig.from_env(
            {"TPU_RAG_TENANTS": "0", "TPU_RAG_TENANT_TOP_K": "3"}
        )
        assert cfg.tenants.enabled is False
        assert cfg.tenants.top_k == 3

    @pytest.mark.parametrize("env", [
        {"TPU_RAG_TENANT_TOP_K": "0"},
        {"TPU_RAG_TENANT_TOP_K": "nope"},
        {"TPU_RAG_TENANTS": "maybe"},
    ])
    def test_invalid_values_raise(self, env):
        with pytest.raises(ValueError):
            AppConfig.from_env(env)


# ---------------------------------------------------------------------------
# replay: the trace record carries tenant end-to-end
# ---------------------------------------------------------------------------
class TestReplayTenant:
    def test_lockstep_round_trip_preserves_tenant(self, tiny):
        """Record a tenant-stamped lockstep run, extract its trace,
        re-drive it: arrivals AND admits stay tenant-stamped both times,
        and the re-extracted trace carries identical tenants."""
        cfg, params = tiny
        trace = {"arrivals": [
            {"rid": 201 + i, "t_step": [0, 0, 1, 2][i],
             "ids": [3 + i, 17, 42, 7 + i], "prompt_len": 4, "max_new": 6,
             "seed": None, "tenant": ["a", "b", "a", OTHER][i]}
            for i in range(4)
        ]}

        def drive(t):
            eng = ContinuousEngine(
                cfg, params, sampling=GREEDY, engine_config=PAGED,
                dtypes=FP32,
            )
            flight.configure(enabled=True, capacity=8192)
            flight.recorder().clear()
            drv = replay.LockstepDriver(eng, emit=flight.emit)
            drv.drive(t)
            return flight.recorder().snapshot()

        j1 = drive(trace)
        t1 = replay.extract_trace(j1)
        got = {a["rid"]: a.get("tenant") for a in t1["arrivals"]}
        assert got == {201: "a", 202: "b", 203: "a", 204: OTHER}
        admits = [e for e in j1 if e["type"] == "admit"]
        assert admits and all(
            e.get("tenant") == got[e["rid"]] for e in admits
        )
        j2 = drive(t1)
        t2 = replay.extract_trace(j2)
        assert [a.get("tenant") for a in t2["arrivals"]] \
            == [a.get("tenant") for a in t1["arrivals"]]
        # and the offline report books the same tenant set either way
        r1 = obs_tenants.render_report(obs_tenants.state_from_events(j1))
        r2 = obs_tenants.render_report(obs_tenants.state_from_events(j2))
        assert [r["tenant"] for r in r1["tenants"]] \
            == [r["tenant"] for r in r2["tenants"]]


# ---------------------------------------------------------------------------
# service edge: extraction, gating, exposition, SLO section
# ---------------------------------------------------------------------------
class TestTenantService:
    def test_debug_tenants_gated_403_unless_armed(
        self, tenant_service, monkeypatch
    ):
        monkeypatch.delenv("TPU_RAG_FAULTS", raising=False)
        monkeypatch.delenv("TPU_RAG_DEBUG", raising=False)
        client = create_app(tenant_service).test_client()
        r = client.get("/debug/tenants")
        assert r.status_code == 403
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(tenant_service).test_client()
        assert client.get("/debug/tenants").status_code == 200

    def test_edge_extraction_body_then_header_then_anon(
        self, tenant_service, monkeypatch
    ):
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(tenant_service).test_client()
        r = client.post(
            "/generate",
            json={"prompt": "alpha", "tenant_id": "team-body"},
            headers={"x-tenant-id": "team-header"},
        )
        assert r.status_code == 200
        r = client.post(
            "/generate", json={"prompt": "alpha"},
            headers={"x-tenant-id": "team-header"},
        )
        assert r.status_code == 200
        r = client.post("/generate", json={"prompt": "alpha"})
        assert r.status_code == 200
        rep = client.get("/debug/tenants").get_json()
        assert rep["enabled"] is True
        names = {row["tenant"] for row in rep["report"]["tenants"]}
        # body field beat the header on the first request
        assert {"team-body", "team-header", "anon"} <= names
        assert all(
            row["completed"] >= 1
            for row in rep["report"]["tenants"]
            if row["tenant"] in ("team-body", "team-header", "anon")
        )
        # the live halves ride alongside the journal-derived report
        assert set(rep["tracker"]["counts"]) >= {"team-body", "team-header"}
        assert "team-body" in rep["ledger"]
        assert rep["ledger"]["team-body"]["chip_s"] > 0

    def test_exposition_carries_bounded_tenant_families(
        self, tenant_service, monkeypatch
    ):
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(tenant_service).test_client()
        assert client.post(
            "/generate", json={"prompt": "alpha", "tenant_id": "team-body"}
        ).status_code == 200
        text = client.get("/metrics").get_data(as_text=True)
        assert re.search(
            r'rag_tenant_http_requests_total\{[^}]*tenant="team-body"[^}]*\}',
            text,
        )
        assert "rag_tenant_request_seconds_bucket" in text
        assert "rag_tenant_chip_seconds_total" in text
        assert "rag_tenant_tokens_total" in text
        assert "rag_tenant_tracked" in text
        vals = set(re.findall(r'\btenant="([^"]*)"', text))
        trk = tenant_service.tenant_tracker
        assert vals <= set(trk.tracked()) | {OTHER}
        assert len(vals) <= trk.top_k + 1

    def test_slo_report_carries_tenant_burn_section(
        self, tenant_service, monkeypatch
    ):
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(tenant_service).test_client()
        assert client.post(
            "/generate", json={"prompt": "alpha", "tenant_id": "team-slo"}
        ).status_code == 200
        rep = client.get("/slo").get_json()
        assert "tenants" in rep
        assert "team-slo" in rep["tenants"]
        entries = {e["name"]: e for e in rep["tenants"]["team-slo"]}
        assert "tenant:team-slo:availability" in entries
        assert "tenant:team-slo:request_p95" in entries
        for e in entries.values():
            assert "burn_rate" in e and "error_budget_remaining" in e

    def test_disabled_edge_leaves_requests_unstamped(
        self, tenant_service, monkeypatch
    ):
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        monkeypatch.setattr(tenant_service, "tenants_enabled", False)
        before = tenant_service.tenant_tracker.snapshot()["table_size"]
        client = create_app(tenant_service).test_client()
        r = client.post(
            "/generate",
            json={"prompt": "alpha", "tenant_id": "team-disabled"},
        )
        assert r.status_code == 200
        after = tenant_service.tenant_tracker.snapshot()["table_size"]
        assert after == before  # the edge never interned anything
        rep = client.get("/debug/tenants").get_json()
        assert rep["enabled"] is False
        names = {row["tenant"] for row in rep["report"]["tenants"]}
        assert "team-disabled" not in names


# ---------------------------------------------------------------------------
# smoke (make tenants-smoke): bound, conservation, byte-identity
# ---------------------------------------------------------------------------
class TestTenantsSmoke:
    def test_churn_storm_keeps_k_plus_other(self):
        """The cardinality acceptance bound: 10k unique tenant ids leave
        at most top_k tracked children + __other__ on a bound family."""
        reg = obs_metrics.MetricsRegistry()
        fam = reg.labeled_counter("rag_tenant_smoke_total", "smoke churn")
        trk = obs_metrics.TenantTracker(top_k=8)
        trk.bind(fam)
        hot = [f"team-{i}" for i in range(8)]
        # space-saving counts are overestimates: 10k evictions across a
        # 128-slot table ratchet the inherited floor up by ~10k/128 ≈ 78,
        # so the hot set needs counts clear of that climb to stay tracked
        for name in hot:
            for _ in range(200):
                fam.labels(tenant=trk.intern(name)).inc()
        for i in range(10_000):
            fam.labels(tenant=trk.intern(f"storm-{i}")).inc()
        trk.prune()
        children = _tenant_children(fam)
        assert len(children) <= trk.top_k + 1
        assert set(trk.tracked()) == set(hot)

    def test_three_tenant_conservation_through_paged_scheduler(self, tiny):
        """THE conservation acceptance: three tenants' rollup chip-seconds
        sum to the scheduler's independently measured busy time within
        5% — attribution one dimension finer than the ledger, same
        total."""
        cfg, params = tiny
        eng = ContinuousEngine(
            cfg, params, sampling=GREEDY, engine_config=PAGED, dtypes=FP32
        )
        eng.warmup(batch_sizes=(4,))  # compiles out of the measured span
        sched = ContinuousScheduler(eng)
        prompts = [
            [3, 17, 42, 7], [5, 5, 8], [11] * 12,
            [2, 9], [4] * 20, [7, 8, 9, 10, 11, 12],
        ]
        tenants = ["a", "b", "c", "a", "b", "c"]
        try:
            outs = [None] * len(prompts)

            def run(i):
                outs[i] = sched.submit(
                    prompts[i], timeout=120, tenant=tenants[i]
                )

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(o is not None for o in outs)
            rolls = eng.ledger.tenant_state()
            assert set(rolls) == {"a", "b", "c"}
            for r in rolls.values():
                assert r["requests"] == 2
                assert r["chip_s"] > 0
                assert 0.0 < r["goodput_frac"] <= 1.0
            total = sum(r["chip_s"] for r in rolls.values())
            busy = sched.busy_seconds()
            assert busy > 0
            assert abs(total - busy) / busy < 0.05, (
                f"per-tenant {total:.4f}s vs busy {busy:.4f}s"
            )
        finally:
            sched.shutdown()

    def test_debug_tenants_and_flightview_byte_identical_without_jax(
        self, tenant_service, monkeypatch, tmp_path
    ):
        """The one-renderer acceptance: the /debug/tenants ``report`` half
        and ``flightview --tenants`` over the exported journal serialize
        byte-identically — with the offline half run in a subprocess
        whose ``jax`` import is POISONED, proving the journal+stdlib
        contract (no live pod, no jax, nothing but the bundle)."""
        monkeypatch.setenv("TPU_RAG_FAULTS", "1")
        client = create_app(tenant_service).test_client()
        for tn in ("smoke-a", "smoke-b", "smoke-a"):
            r = client.post(
                "/generate", json={"prompt": "alpha", "tenant_id": tn}
            )
            assert r.status_code == 200
        # the renderers are pure over the event list, so byte-identity
        # needs both halves to see the SAME journal — wait out any async
        # stragglers (shadow audits) until live report and exported
        # bundle agree on event count (journal is append-only: equal
        # length over the same recorder means equal events)
        deadline = time.monotonic() + 30.0
        while True:
            live = client.get("/debug/tenants").get_json()["report"]
            journal = tenant_service.flight.snapshot()
            if live["events"] == len(journal):
                break
            assert time.monotonic() < deadline, (
                f"journal never quiesced: report folded {live['events']} "
                f"events, snapshot has {len(journal)}"
            )
            time.sleep(0.05)
        bundle = {
            "schema_version": flight.SCHEMA_VERSION,
            "journal": journal,
        }
        path = tmp_path / "journal.json"
        path.write_text(json.dumps(bundle))
        assert {"smoke-a", "smoke-b"} <= {
            r["tenant"] for r in live["tenants"]
        }
        # in-process first (the cheap half of the contract)...
        offline = flightview.build_tenant_report(
            flightview.load_events(bundle)
        )
        assert json.dumps(offline, sort_keys=True) \
            == json.dumps(live, sort_keys=True)
        # ...then the poisoned-import half: a jax.py that raises shadows
        # the real package, so ANY jax import in the offline path crashes
        poison = tmp_path / "poison"
        poison.mkdir()
        (poison / "jax.py").write_text(
            'raise ImportError("poisoned: the offline tenant renderer '
            'must not import jax")\n'
        )
        script = (
            "import json, sys\n"
            f"sys.path.insert(0, {str(poison)!r})\n"
            f"sys.path.insert(0, {str(REPO_ROOT)!r})\n"
            "from scripts import flightview\n"
            f"bundle = json.loads(open({str(path)!r}).read())\n"
            "rep = flightview.build_tenant_report("
            "flightview.load_events(bundle))\n"
            "sys.stdout.write(json.dumps(rep, sort_keys=True))\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout == json.dumps(live, sort_keys=True)
        # the CLI renders both forms standalone
        assert flightview.main([str(path), "--tenants", "--json"]) == 0
        assert flightview.main([str(path), "--tenants"]) == 0


# ---------------------------------------------------------------------------
# service fixture
# ---------------------------------------------------------------------------
class ByteTokenizer:
    def encode(self, text):
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        return bytes((i - 3) % 256 for i in ids if i >= 3).decode(
            "utf-8", "replace"
        )


@pytest.fixture(scope="module")
def tenant_service(tmp_path_factory):
    llama_cfg = LlamaConfig.tiny(vocab_size=300)
    enc_cfg = EncoderConfig.tiny(vocab_size=300)
    cfg = AppConfig(
        model=llama_cfg, encoder=enc_cfg,
        flight=FlightConfig(
            spool_dir=str(tmp_path_factory.mktemp("spool")), cooldown_s=0.0,
        ),
        tenants=TenantConfig(enabled=True, top_k=8),
        system_message="Use the context.",
    )
    params = init_llama_params(jax.random.PRNGKey(0), llama_cfg, FP32)
    engine = InferenceEngine(
        llama_cfg, params,
        sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
        engine_config=EngineConfig(
            prompt_buckets=(128, 256), max_batch_size=2, max_seq_len=512,
        ),
        dtypes=FP32,
    )
    ceng = ContinuousEngine(
        llama_cfg, params,
        sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
        engine_config=EngineConfig(
            prompt_buckets=(64, 256), max_batch_size=4, max_seq_len=320,
        ),
        dtypes=FP32,
    )
    sched = ContinuousScheduler(ceng, retry_backoff_s=0.0)
    encoder = EncoderRunner(
        enc_cfg, init_encoder_params(jax.random.PRNGKey(1), enc_cfg, FP32),
        dtypes=FP32, length_buckets=(32, 64), max_batch=4,
    )
    store = VectorStore(dim=enc_cfg.hidden_size)
    svc = RagService(
        cfg, engine, ByteTokenizer(), encoder, ByteTokenizer(), store,
        scheduler=sched,
    )
    svc.ready = True
    texts = ["alpha beta gamma", "delta epsilon zeta"]
    vecs = encoder.encode([ByteTokenizer().encode(t) for t in texts])
    store.add(list(vecs), [
        {"filename": "f", "chunk_id": i, "text": t}
        for i, t in enumerate(texts)
    ])
    yield svc
    svc.shutdown()
