"""Llama model tests: shapes, cache semantics, and logit parity vs HF torch.

The parity test is the survey's recommended oracle (SURVEY §4): a tiny random
HF ``LlamaForCausalLM`` (same GQA + llama3 RoPE scaling code path the real
8B uses) is converted through the production loader mapping and must produce
matching logits.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig, RopeScalingConfig
from rag_llm_k8s_tpu.models.llama import (
    KVCache,
    LlamaModel,
    init_llama_params,
    make_kv_cache,
    mask_window,
    rope_frequencies,
)
from rag_llm_k8s_tpu.models.loader import convert_hf_state_dict

FP32 = DTypePolicy.fp32()


def _window(B, S, start=0):
    """(kv_start, kv_len) vectors for a full [start, S) valid window."""
    return jnp.full((B,), start, jnp.int32), jnp.full((B,), S, jnp.int32)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return cfg, params, LlamaModel(cfg, FP32)


class TestForward:
    def test_logits_shape_and_dtype(self, tiny):
        cfg, params, model = tiny
        B, S = 2, 8
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        logits, new_cache = model.apply(
            {"params": params}, tokens, pos, cache, *_window(B, S), jnp.int32(0)
        )
        assert logits.shape == (B, S, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert new_cache.k.shape == (cfg.num_layers, B, cfg.num_kv_heads, S, cfg.head_dim)

    def test_causality(self, tiny):
        """Changing a future token must not change past logits."""
        cfg, params, model = tiny
        B, S = 1, 8
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        t1 = jnp.array([[5, 6, 7, 8, 9, 10, 11, 12]], jnp.int32)
        t2 = t1.at[0, -1].set(99)
        l1, _ = model.apply({"params": params}, t1, pos, cache, *_window(B, S), jnp.int32(0))
        l2, _ = model.apply({"params": params}, t2, pos, cache, *_window(B, S), jnp.int32(0))
        np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], atol=1e-5)
        assert not np.allclose(l1[:, -1], l2[:, -1])

    def test_prefill_then_decode_matches_full_forward(self, tiny):
        """Incremental decode through the KV cache must reproduce the logits of
        one full forward pass — the core cache-correctness invariant."""
        cfg, params, model = tiny
        B, S = 1, 10
        tokens = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))

        # full forward
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        full_logits, _ = model.apply(
            {"params": params}, tokens, pos, cache, *_window(B, S), jnp.int32(0)
        )

        # prefill 6, then decode 4 one at a time
        P = 6
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        plogits, cache = model.apply(
            {"params": params}, tokens[:, :P], pos[:, :P], cache, *_window(B, P), jnp.int32(0)
        )
        np.testing.assert_allclose(plogits, full_logits[:, :P], rtol=2e-4, atol=2e-4)

        for t in range(P, S):
            dlogits, cache = model.apply(
                {"params": params},
                tokens[:, t : t + 1],
                pos[:, t : t + 1],
                cache,
                *_window(B, t + 1),
                jnp.int32(t),
            )
            np.testing.assert_allclose(
                dlogits[:, 0], full_logits[:, t], rtol=2e-4, atol=2e-4
            )

    def test_left_padding_invariance(self, tiny):
        """Left-padded prefill (the engine's batching scheme) must produce the
        same final-token logits as unpadded."""
        cfg, params, model = tiny
        S, PAD = 6, 3
        tokens = jax.random.randint(jax.random.PRNGKey(3), (1, S), 3, cfg.vocab_size)
        T = S + PAD

        # unpadded
        cache = make_kv_cache(cfg, 1, T, jnp.float32)
        pos = jnp.arange(S)[None, :]
        l_ref, _ = model.apply(
            {"params": params}, tokens, pos, cache, *_window(1, S), jnp.int32(0)
        )

        # left-padded by PAD zeros
        padded = jnp.concatenate([jnp.zeros((1, PAD), jnp.int32), tokens], axis=1)
        pad_mask = jnp.concatenate(
            [jnp.zeros((1, PAD), jnp.int32), jnp.ones((1, S), jnp.int32)], axis=1
        )
        cache = make_kv_cache(cfg, 1, T, jnp.float32)
        kv_start, kv_len = mask_window(pad_mask)
        pos_p = jnp.concatenate([jnp.zeros((1, PAD), jnp.int32), pos], axis=1)
        l_pad, _ = model.apply(
            {"params": params}, padded, pos_p, cache, kv_start, kv_len, jnp.int32(0)
        )
        np.testing.assert_allclose(l_pad[:, -1], l_ref[:, -1], rtol=2e-4, atol=2e-4)


class TestRope:
    def test_no_scaling_matches_analytic(self):
        cfg = LlamaConfig.tiny()
        f = rope_frequencies(cfg)
        expected = 1.0 / (cfg.rope_theta ** (np.arange(0, cfg.head_dim, 2) / cfg.head_dim))
        np.testing.assert_allclose(np.asarray(f), expected, rtol=1e-6)

    def test_llama3_scaling_bands(self):
        """Low-freq band divides by factor; high-freq band unchanged."""
        cfg = LlamaConfig.llama_3_1_8b()
        scaled = np.asarray(rope_frequencies(cfg))
        base = 1.0 / (cfg.rope_theta ** (np.arange(0, 128, 2) / 128))
        s = cfg.rope_scaling
        wavelen = 2 * np.pi / base
        high_w = s.original_max_position_embeddings / s.high_freq_factor
        low_w = s.original_max_position_embeddings / s.low_freq_factor
        np.testing.assert_allclose(scaled[wavelen < high_w], base[wavelen < high_w], rtol=1e-6)
        np.testing.assert_allclose(
            scaled[wavelen > low_w], base[wavelen > low_w] / s.factor, rtol=1e-6
        )


class TestHFParity:
    """Logit parity against transformers' torch Llama (the reference's engine)."""

    @pytest.mark.parametrize("rope_scaled", [False, True])
    def test_tiny_logit_parity(self, rope_scaled):
        torch = pytest.importorskip("torch")
        from transformers import LlamaConfig as HFConfig
        from transformers import LlamaForCausalLM

        cfg = LlamaConfig.tiny(vocab_size=128)
        if rope_scaled:
            cfg = LlamaConfig(
                **{
                    **cfg.__dict__,
                    "rope_scaling": RopeScalingConfig(
                        factor=8.0,
                        low_freq_factor=1.0,
                        high_freq_factor=4.0,
                        original_max_position_embeddings=16,
                    ),
                }
            )
        hf_cfg = HFConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            num_key_value_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            rms_norm_eps=cfg.rms_norm_eps,
            rope_theta=cfg.rope_theta,
            max_position_embeddings=cfg.max_seq_len,
            tie_word_embeddings=False,
            attention_bias=False,
            mlp_bias=False,
        )
        if rope_scaled:
            hf_cfg.rope_scaling = {
                "rope_type": "llama3",
                "factor": 8.0,
                "low_freq_factor": 1.0,
                "high_freq_factor": 4.0,
                "original_max_position_embeddings": 16,
            }
        torch.manual_seed(0)
        hf_model = LlamaForCausalLM(hf_cfg).eval().float()

        state = dict(hf_model.state_dict())
        params = convert_hf_state_dict(state, cfg, FP32)

        B, S = 2, 12
        rng = np.random.RandomState(0)
        tokens_np = rng.randint(0, cfg.vocab_size, size=(B, S))
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(tokens_np)).logits.numpy()

        model = LlamaModel(cfg, FP32)
        cache = make_kv_cache(cfg, B, S, jnp.float32)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        logits, _ = model.apply(
            {"params": params}, jnp.asarray(tokens_np), pos, cache, *_window(B, S), jnp.int32(0)
        )
        np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=1e-3, atol=1e-3)

    def test_safetensors_roundtrip(self, tmp_path):
        """Production path: shard files on disk -> streamed, converted tree."""
        from safetensors.numpy import save_file

        from rag_llm_k8s_tpu.models.loader import load_safetensors_params

        cfg = LlamaConfig.tiny(vocab_size=64)
        rng = np.random.RandomState(1)
        state = {
            "model.embed_tokens.weight": rng.randn(64, cfg.hidden_size).astype(np.float32),
            "model.norm.weight": rng.randn(cfg.hidden_size).astype(np.float32),
            "lm_head.weight": rng.randn(64, cfg.hidden_size).astype(np.float32),
        }
        for i in range(cfg.num_layers):
            p = f"model.layers.{i}."
            state[p + "self_attn.q_proj.weight"] = rng.randn(
                cfg.num_heads * cfg.head_dim, cfg.hidden_size
            ).astype(np.float32)
            state[p + "self_attn.k_proj.weight"] = rng.randn(
                cfg.num_kv_heads * cfg.head_dim, cfg.hidden_size
            ).astype(np.float32)
            state[p + "self_attn.v_proj.weight"] = rng.randn(
                cfg.num_kv_heads * cfg.head_dim, cfg.hidden_size
            ).astype(np.float32)
            state[p + "self_attn.o_proj.weight"] = rng.randn(
                cfg.hidden_size, cfg.num_heads * cfg.head_dim
            ).astype(np.float32)
            state[p + "mlp.gate_proj.weight"] = rng.randn(
                cfg.intermediate_size, cfg.hidden_size
            ).astype(np.float32)
            state[p + "mlp.up_proj.weight"] = rng.randn(
                cfg.intermediate_size, cfg.hidden_size
            ).astype(np.float32)
            state[p + "mlp.down_proj.weight"] = rng.randn(
                cfg.hidden_size, cfg.intermediate_size
            ).astype(np.float32)
            state[p + "input_layernorm.weight"] = rng.randn(cfg.hidden_size).astype(np.float32)
            state[p + "post_attention_layernorm.weight"] = rng.randn(cfg.hidden_size).astype(
                np.float32
            )
        # split across two shard files like the real 4-shard layout
        keys = sorted(state)
        half = len(keys) // 2
        save_file({k: state[k] for k in keys[:half]}, str(tmp_path / "model-00001-of-00002.safetensors"))
        save_file({k: state[k] for k in keys[half:]}, str(tmp_path / "model-00002-of-00002.safetensors"))

        params = load_safetensors_params(str(tmp_path), cfg, FP32)
        direct = convert_hf_state_dict(state, cfg, FP32)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params,
            direct,
        )

    def test_unknown_and_missing_keys_rejected(self):
        cfg = LlamaConfig.tiny(vocab_size=16)
        with pytest.raises(ValueError, match="missing"):
            convert_hf_state_dict({"model.embed_tokens.weight": np.zeros((16, 64))}, cfg, FP32)
        good = {"bogus.weight": np.zeros((2, 2))}
        with pytest.raises(KeyError, match="unrecognized"):
            convert_hf_state_dict(good, cfg, FP32)
