"""Continuous batching: concurrent submits coalesce and return correct results."""

import threading
import time

import jax
import pytest

from rag_llm_k8s_tpu.core.config import DTypePolicy, EngineConfig, LlamaConfig, SamplingConfig
from rag_llm_k8s_tpu.engine.batching import BatchScheduler
from rag_llm_k8s_tpu.engine.engine import InferenceEngine
from rag_llm_k8s_tpu.models.llama import init_llama_params

FP32 = DTypePolicy.fp32()


@pytest.fixture(scope="module")
def engine():
    cfg = LlamaConfig.tiny()
    params = init_llama_params(jax.random.PRNGKey(0), cfg, FP32)
    return InferenceEngine(
        cfg,
        params,
        sampling=SamplingConfig(do_sample=False, max_new_tokens=6),
        engine_config=EngineConfig(prompt_buckets=(16,), max_batch_size=4),
        dtypes=FP32,
    )


class TestBatchScheduler:
    def test_concurrent_submits_match_solo(self, engine):
        prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5], [3, 5, 8, 9, 7], [9, 3, 2], [3, 8]]
        want = [engine.generate([p])[0] for p in prompts]

        sched = BatchScheduler(engine, max_wait_ms=20.0)
        calls_before = engine.stats.generate_calls
        results = [None] * len(prompts)

        def worker(i):
            results[i] = sched.submit(prompts[i], timeout=120)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sched.shutdown()

        assert results == want
        # 6 concurrent requests with cap 4 must coalesce into < 6 engine calls
        assert engine.stats.generate_calls - calls_before < len(prompts)

    def test_incompatible_max_new_not_mixed(self, engine):
        sched = BatchScheduler(engine, max_wait_ms=20.0)
        r_short = sched.submit([3, 1, 4], max_new_tokens=2, timeout=120)
        r_long = sched.submit([3, 1, 4], max_new_tokens=5, timeout=120)
        sched.shutdown()
        assert len(r_short) <= 2 and len(r_long) <= 5

    def test_shutdown_rejects(self, engine):
        sched = BatchScheduler(engine)
        sched.shutdown()
        with pytest.raises(RuntimeError):
            sched.submit([1, 2, 3])

    def test_shutdown_drains_queued_and_carried(self, engine):
        """Items still queued (or held as the mismatch carry) at shutdown
        must be FAILED, not abandoned — the server submits with
        timeout=None, so an un-acked item would block its HTTP thread
        forever."""
        sched = BatchScheduler(engine, max_wait_ms=700.0)
        release = threading.Event()
        orig_generate = sched.engine.generate

        def slow_generate(*a, **kw):
            release.wait(timeout=30)
            return orig_generate(*a, **kw)

        sched.engine.generate = slow_generate
        try:
            results = {}

            def run(name, max_new):
                try:
                    results[name] = ("ok", sched.submit(
                        [3, 17], max_new_tokens=max_new, timeout=60
                    ))
                except BaseException as e:  # noqa: BLE001
                    results[name] = ("err", type(e).__name__)

            # t1 leads; t2 arrives DURING t1's coalescing window with a
            # mismatched max_new, so the worker holds it as the CARRY and
            # proceeds into (blocked) generate; t3 then sits on the queue —
            # shutdown must fail both drain paths (carry AND queue)
            threads = [
                threading.Thread(target=run, args=("t1", 2)),
                threading.Thread(target=run, args=("t2", 3)),
                threading.Thread(target=run, args=("t3", 4)),
            ]
            threads[0].start()
            time.sleep(0.2)  # worker picked t1, is inside the drain window
            threads[1].start()
            time.sleep(0.2)  # worker carried t2, entered blocked generate
            threads[2].start()
            time.sleep(0.2)  # t3 queued behind the in-flight batch
            sched._stop.set()
            release.set()  # unblock the in-flight batch
            sched._queue.put(None)
            sched._worker.join(timeout=30)
            for t in threads:
                t.join(timeout=30)
                assert not t.is_alive(), "submitter hung after shutdown"
            # the in-flight batch completes; carried + queued fail loudly
            assert results["t1"][0] == "ok"
            assert results["t2"] == ("err", "RuntimeError")
            assert results["t3"] == ("err", "RuntimeError")
        finally:
            sched.engine.generate = orig_generate
            sched.shutdown()


class TestNoReorderOnMismatch:
    def test_worker_never_requeues_drained_items(self, engine):
        """The carry fix means a mismatched request is held as next round's
        leader, NEVER put back on the queue (a tail re-queue would reorder
        it behind requests that arrived later and could starve it under
        sustained mixed load). Detect any worker-thread re-put directly."""
        import time

        sched = BatchScheduler(engine, max_wait_ms=100.0)
        try:
            worker_puts = []
            orig_put = sched._queue.put

            def spy_put(item, *a, **kw):
                if threading.current_thread() is sched._worker:
                    worker_puts.append(item)
                return orig_put(item, *a, **kw)

            sched._queue.put = spy_put

            outs = {}

            def run(name, max_new):
                outs[name] = sched.submit([3, 17], max_new_tokens=max_new, timeout=120)

            # a leads round 1; b (different executable key) is drained during
            # a's coalescing window and must be carried, not re-queued
            ta = threading.Thread(target=run, args=("a", 4))
            ta.start()
            time.sleep(0.02)  # worker is now inside a's drain window
            tb = threading.Thread(target=run, args=("b", 5))
            tb.start()
            ta.join(timeout=120)
            tb.join(timeout=120)
            assert set(outs) == {"a", "b"} and all(outs.values())
            assert worker_puts == []  # the old behavior re-put b here
        finally:
            sched.shutdown()


class TestCoalescer:
    def test_concurrent_submits_batch_and_return_in_order(self):
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        calls = []
        lock = threading.Lock()

        def batch_fn(items):
            with lock:
                calls.append(list(items))
            time.sleep(0.05)  # hold the worker so later arrivals accumulate
            return [x * 10 for x in items]

        co = Coalescer(batch_fn, max_batch=4, max_wait_ms=1.0)
        try:
            results = [None] * 8

            def run(i):
                results[i] = co.submit(i, timeout=30)

            threads = [threading.Thread(target=run, args=(i,)) for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert results == [i * 10 for i in range(8)]
            # 8 concurrent items, cap 4: the first call runs while the rest
            # queue, so everything lands in < 8 calls (natural batching even
            # with a ~zero window)
            assert len(calls) < 8
            assert max(len(c) for c in calls) > 1
        finally:
            co.shutdown()

    def test_error_delivered_to_every_waiter(self):
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        def batch_fn(items):
            raise ValueError("boom")

        co = Coalescer(batch_fn, max_batch=4, max_wait_ms=1.0)
        try:
            with pytest.raises(ValueError, match="boom"):
                co.submit(1, timeout=30)
        finally:
            co.shutdown()

    def test_wrong_result_count_is_an_error_not_a_hang(self):
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        co = Coalescer(lambda items: [], max_batch=4, max_wait_ms=1.0)
        try:
            with pytest.raises(RuntimeError, match="results"):
                co.submit(1, timeout=30)
        finally:
            co.shutdown()

    def test_shutdown_rejects_new_submits(self):
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        co = Coalescer(lambda items: items, max_batch=2, max_wait_ms=1.0)
        co.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            co.submit(1, timeout=5)

    def test_zero_window_still_drains_queued_items(self):
        """max_wait_ms=0 contract: items that accumulated while the worker
        was busy must form ONE batch (the deadline never blocks draining
        what is already queued)."""
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        calls = []
        lock = threading.Lock()
        gate = threading.Event()

        def batch_fn(items):
            with lock:
                calls.append(list(items))
            if len(calls) == 1:
                gate.wait(10)  # hold the first batch until the rest queue up
            return [x * 10 for x in items]

        co = Coalescer(batch_fn, max_batch=8, max_wait_ms=0.0)
        try:
            results = [None] * 5

            def run(i):
                results[i] = co.submit(i, timeout=30)

            t0 = threading.Thread(target=run, args=(0,))
            t0.start()
            while not calls:  # first item is now in flight
                time.sleep(0.001)
            rest = [threading.Thread(target=run, args=(i,)) for i in range(1, 5)]
            for t in rest:
                t.start()
            time.sleep(0.05)  # the 4 are queued behind the held batch
            gate.set()
            t0.join(30)
            for t in rest:
                t.join(30)
            assert results == [i * 10 for i in range(5)]
            assert len(calls) == 2, calls  # 1 held batch + ONE drained batch of 4
            assert sorted(calls[1]) == [1, 2, 3, 4]
        finally:
            co.shutdown()


class TestPendingHint:
    """pending_hint contract: the drain loop exits the moment every request
    in flight toward the stage is aboard — a solo submit pays ~0 ms of a
    large window; a hinted burst still coalesces into one batch."""

    def test_coalescer_solo_skips_window(self):
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        co = Coalescer(
            lambda items: [x * 10 for x in items], max_batch=8,
            max_wait_ms=2000.0, pending_hint=lambda: 1,
        )
        try:
            t0 = time.monotonic()
            assert co.submit(3, timeout=30) == 30
            # far below the 2 s window: the hint ended the wait immediately
            assert time.monotonic() - t0 < 0.5
        finally:
            co.shutdown()

    def test_coalescer_hinted_burst_still_coalesces(self):
        from rag_llm_k8s_tpu.engine.batching import Coalescer

        calls = []
        lock = threading.Lock()
        inflight = [0]

        def batch_fn(items):
            with lock:
                calls.append(list(items))
            return [x * 10 for x in items]

        co = Coalescer(
            batch_fn, max_batch=8, max_wait_ms=5000.0,
            pending_hint=lambda: inflight[0],
        )
        try:
            results = [None] * 4
            inflight[0] = 4  # all 4 "in flight" before any submit lands

            def run(i):
                # stagger arrivals well past any fixed-poll granularity
                time.sleep(0.01 * i)
                results[i] = co.submit(i, timeout=30)

            threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t0
            assert results == [i * 10 for i in range(4)]
            assert len(calls) == 1 and sorted(calls[0]) == [0, 1, 2, 3]
            # the batch ran when the 4th arrived, NOT at the 5 s deadline
            assert wall < 2.0
        finally:
            co.shutdown()

    def test_scheduler_solo_skips_window(self, engine):
        sched = BatchScheduler(engine, max_wait_ms=2000.0, pending_hint=lambda: 1)
        try:
            t0 = time.monotonic()
            out = sched.submit([3, 1, 4], timeout=120)
            assert time.monotonic() - t0 < 1.0  # not the 2 s window
            assert out == engine.generate([[3, 1, 4]])[0]
        finally:
            sched.shutdown()
