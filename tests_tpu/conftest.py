"""Hardware test lane: runs on the REAL TPU chip (no platform forcing).

The main suite (`tests/`) pins an 8-virtual-device CPU platform for
mesh/sharding coverage; this lane is the complement — it executes the Pallas
kernels and the engine on actual hardware so on-chip correctness is a
repeatable artifact, not a commit-message claim. Run via ``make tpu-test``
or ``python -m pytest tests_tpu/ -q`` (skips itself entirely off-TPU).
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(reason=f"needs TPU (backend={jax.default_backend()})")
        for item in items:
            item.add_marker(skip)
