"""On-chip correctness: Pallas kernels vs XLA oracles, engine end-to-end.

These are the hardware counterparts of the interpret-mode tests in
``tests/`` — same oracles, real Mosaic compilation, real MXU/VPU numerics.
Parity checks run under ``jax.default_matmul_precision("highest")`` so both
sides accumulate in true fp32 (at default precision the MXU rounds inputs
to bf16 and the two implementations differ by rounding noise, not bugs).
"""

import jax
import jax.numpy as jnp
import numpy as np

from rag_llm_k8s_tpu.core.config import (
    DTypePolicy,
    EngineConfig,
    LlamaConfig,
    SamplingConfig,
)


class TestKnnKernel:
    def test_matches_oracle(self):
        from rag_llm_k8s_tpu.ops.knn import knn_topk_pallas, knn_topk_xla

        rng = np.random.RandomState(0)
        N, D, Q, k = 2048, 1024, 4, 5
        emb = jnp.asarray(rng.randn(N, D).astype(np.float32))
        emb = emb / jnp.linalg.norm(emb, axis=1, keepdims=True)
        queries = emb[:Q] + 0.01 * jnp.asarray(rng.randn(Q, D).astype(np.float32))
        norms = jnp.sum(emb * emb, axis=1)[None, :]

        with jax.default_matmul_precision("highest"):
            v_got, i_got = knn_topk_pallas(queries, emb, norms, k=k)
            v_ref, i_ref = knn_topk_xla(queries, emb, norms, k=k)
        np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(v_got), np.asarray(v_ref), rtol=1e-4, atol=1e-5)


class TestAttentionKernels:
    def test_flash_prefill_matches_oracle(self):
        from rag_llm_k8s_tpu.ops.attention import attention_xla, flash_attention

        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        B, S, H, K, hd = 2, 512, 8, 2, 128
        q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, K, hd), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, K, hd), jnp.float32)
        kv_start = jnp.array([0, 100], jnp.int32)
        with jax.default_matmul_precision("highest"):
            got = flash_attention(q, k, v, kv_start=kv_start, causal=True)
            want = attention_xla(q, k, v, kv_start=kv_start, causal=True)
        valid = (jnp.arange(S)[None, :] >= kv_start[:, None])[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, got, 0)),
            np.asarray(jnp.where(valid, want, 0)),
            rtol=2e-4, atol=2e-4,
        )

    def test_decode_matches_oracle(self):
        from rag_llm_k8s_tpu.ops.attention import decode_attention, decode_attention_xla

        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        L, B, H, K, T, hd = 2, 4, 8, 2, 640, 128
        q = jax.random.normal(ks[0], (B, 1, H, hd), jnp.float32)
        kc = jax.random.normal(ks[1], (L, B, K, T, hd), jnp.float32)
        vc = jax.random.normal(ks[2], (L, B, K, T, hd), jnp.float32)
        kv_start = jnp.array([0, 17, 300, 0], jnp.int32)
        kv_len = jnp.array([T, 400, 301, 128], jnp.int32)
        for lay in range(L):
            with jax.default_matmul_precision("highest"):
                got = decode_attention(q, kc, vc, kv_start, kv_len, jnp.int32(lay))
                want = decode_attention_xla(q, kc, vc, kv_start, kv_len, jnp.int32(lay))
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
            )

    def test_decode_q8_matches_oracle_on_mosaic(self):
        """int8-KV decode kernel on real Mosaic vs its XLA oracle — the
        epilogue-scaled dequant (scores x k_scale, probs x v_scale) must
        reproduce the dense math at quantization tolerance."""
        from rag_llm_k8s_tpu.ops.attention import (
            decode_attention_q8,
            decode_attention_xla_q8,
            quantize_kv,
        )

        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        L, B, H, K, T, hd = 2, 4, 8, 2, 640, 128
        q = jax.random.normal(ks[0], (B, 1, H, hd), jnp.float32)
        kc = jax.random.normal(ks[1], (L, B, K, T, hd), jnp.float32)
        vc = jax.random.normal(ks[2], (L, B, K, T, hd), jnp.float32)
        kq, kscale = quantize_kv(kc)
        vq, vscale = quantize_kv(vc)
        kv_start = jnp.array([0, 17, 300, 0], jnp.int32)
        kv_len = jnp.array([T, 400, 301, 128], jnp.int32)
        for lay in range(L):
            with jax.default_matmul_precision("highest"):
                got = decode_attention_q8(
                    q, kq, vq, kscale, vscale, kv_start, kv_len, jnp.int32(lay)
                )
                want = decode_attention_xla_q8(
                    q, kq, vq, kscale, vscale, kv_start, kv_len, jnp.int32(lay)
                )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=3e-3, atol=3e-3
            )

    def test_engine_int8_kv_generates(self):
        """One-shot engine with kv_quant=int8 end-to-end on chip: greedy ids
        must match the bf16-cache engine exactly on the tiny model."""
        from rag_llm_k8s_tpu.engine.engine import InferenceEngine
        from rag_llm_k8s_tpu.models.llama import init_llama_params

        cfg = LlamaConfig.tiny()
        DT = DTypePolicy()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        outs = {}
        for kvq in ("bf16", "int8"):
            eng = InferenceEngine(
                cfg, params,
                sampling=SamplingConfig(do_sample=False, max_new_tokens=16),
                engine_config=EngineConfig(
                    prompt_buckets=(128,), max_batch_size=2, kv_quant=kvq
                ),
                dtypes=DT,
            )
            outs[kvq] = eng.generate([[cfg.bos_token_id, 5, 7, 9], [cfg.bos_token_id, 3]])
        assert outs["bf16"] == outs["int8"]

    def test_chunk_prefill_matches_oracle(self):
        from rag_llm_k8s_tpu.ops.attention import (
            chunk_attention_xla,
            chunk_prefill_attention,
        )

        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        L, B, S, H, K, T, hd = 2, 2, 256, 8, 2, 1024, 128
        q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
        kc = jax.random.normal(ks[1], (L, B, K, T, hd), jnp.float32)
        vc = jax.random.normal(ks[2], (L, B, K, T, hd), jnp.float32)
        kv_start = jnp.array([0, 40], jnp.int32)
        for wi in (0, 256, T - S):
            kv_len = jnp.full((B,), wi + S, jnp.int32)
            for lay in range(L):
                with jax.default_matmul_precision("highest"):
                    got = chunk_prefill_attention(
                        q, kc, vc, kv_start, kv_len, jnp.int32(lay), jnp.int32(wi)
                    )
                    want = chunk_attention_xla(
                        q, kc, vc, kv_start, kv_len, jnp.int32(lay), jnp.int32(wi)
                    )
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
                )



class TestEngineOnChip:
    def test_generate_pallas_vs_xla_logits_path(self):
        """Full model prefill + one decode step, Pallas vs XLA oracle, at a
        real (1B-proxy) layer shape."""
        from rag_llm_k8s_tpu.models.llama import (
            LlamaModel,
            init_llama_params,
            make_kv_cache,
            mask_window,
        )

        fp32 = DTypePolicy.fp32()
        cfg = LlamaConfig.llama_3_2_1b()
        cfg = type(cfg)(**{**cfg.__dict__, "num_layers": 2, "vocab_size": 2048})
        params = init_llama_params(jax.random.PRNGKey(0), cfg, fp32)
        B, S, T = 2, 256, 384
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 3, cfg.vocab_size)
        pad_mask = jnp.ones((B, S), jnp.int32).at[1, :100].set(0)
        kv_start, _ = mask_window(pad_mask)
        kv_len = jnp.full((B,), S, jnp.int32)
        pos = jnp.clip(jnp.cumsum(pad_mask, axis=-1) - 1, 0)
        real_len = jnp.sum(pad_mask, axis=-1)

        def run_once(impl):
            with jax.default_matmul_precision("highest"):
                model = LlamaModel(cfg, fp32, attn_impl=impl)
                cache = make_kv_cache(cfg, B, T, jnp.float32)
                plog, cache = jax.jit(
                    lambda p, t: model.apply(
                        {"params": p}, t, pos, cache, kv_start, kv_len, jnp.int32(0)
                    )
                )(params, tokens)
                dlog, _ = jax.jit(
                    lambda p, t, c: model.apply(
                        {"params": p}, t, real_len[:, None].astype(jnp.int32), c,
                        kv_start, jnp.full((B,), S + 1, jnp.int32), jnp.int32(S),
                    )
                )(params, tokens[:, -1:], cache)
            return np.asarray(plog), np.asarray(dlog)

        p_ref, d_ref = run_once("xla")
        if not (np.isfinite(p_ref).all() and np.isfinite(d_ref).all()):
            # Known artifact of the tunneled (axon, experimental) platform:
            # under a long session the ORACLE forward — stock XLA einsum/
            # softmax with no scratch memory, where a race is impossible —
            # occasionally returns all-NaN over finite inputs, and rerunning
            # the identical computation succeeds. Retry the ORACLE only; the
            # Pallas side (the kernel under test, where uninitialized-scratch
            # races WOULD look like nondeterministic NaN) is never retried,
            # so a racy kernel bug still fails this test.
            import warnings

            warnings.warn(
                "xla oracle returned non-finite values on the axon platform; "
                "retrying the identical computation once"
            )
            p_ref, d_ref = run_once("xla")
        p_got, d_got = run_once("pallas")
        valid = np.asarray(pad_mask).astype(bool)[:, :, None]
        np.testing.assert_allclose(
            np.where(valid, p_got, 0), np.where(valid, p_ref, 0), rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(d_got, d_ref, rtol=1e-4, atol=1e-4)

    def test_engine_generate_smoke(self):
        """The real serving engine generates on hardware through the Pallas
        path: deterministic greedy, correct lengths, EOS-free tail."""
        from rag_llm_k8s_tpu.engine.engine import InferenceEngine
        from rag_llm_k8s_tpu.models.llama import init_llama_params

        cfg = LlamaConfig.tiny(vocab_size=512)
        cfg = type(cfg)(**{**cfg.__dict__, "num_heads": 8, "num_kv_heads": 8, "head_dim": 64})
        dtypes = DTypePolicy()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, dtypes)
        eng = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
            engine_config=EngineConfig(prompt_buckets=(16,), max_batch_size=4),
            dtypes=dtypes,
        )
        prompts = [[3, 5, 7], [11, 13, 17, 19, 23]]
        out1 = eng.generate(prompts)
        out2 = eng.generate(prompts)
        assert out1 == out2  # greedy determinism through the kernel path
        assert all(len(o) <= 8 for o in out1)
        assert all(t not in cfg.eos_token_ids for o in out1 for t in o)


class TestContinuousOnChip:
    def test_mid_flight_admission_parity(self):
        """Slot-based decode on real hardware: scatter cache writes + fused
        decode kernel produce the one-shot engine's greedy tokens, including
        for a request admitted mid-generation."""
        from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine
        from rag_llm_k8s_tpu.engine.engine import InferenceEngine
        from rag_llm_k8s_tpu.models.llama import init_llama_params

        DT = DTypePolicy()  # production bf16 policy
        cfg = LlamaConfig.tiny()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        greedy = SamplingConfig(do_sample=False, max_new_tokens=8)
        ecfg = EngineConfig(prompt_buckets=(16, 32), max_batch_size=4, max_seq_len=64)
        oracle = InferenceEngine(cfg, params, sampling=greedy, engine_config=ecfg, dtypes=DT)
        eng = ContinuousEngine(cfg, params, sampling=greedy, engine_config=ecfg, dtypes=DT)

        p1, p2 = [3, 17, 42, 7, 99], [5, 5, 8]
        want1 = oracle.generate([p1])[0]
        want2 = oracle.generate([p2])[0]
        eng.admit(1, p1, greedy.max_new_tokens)
        results = {}
        for _ in range(3):
            for rid, toks in eng.step():
                results[rid] = toks
        eng.admit(2, p2, greedy.max_new_tokens)
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results[1] == want1
        assert results[2] == want2

    def test_continuous_int8_kv_parity_on_chip(self):
        """Continuous batching over an int8 KV cache on real hardware:
        quantize-on-write scatter + the q8 decode kernel reproduce the
        one-shot int8-KV engine's greedy ids."""
        from rag_llm_k8s_tpu.engine.continuous import ContinuousEngine
        from rag_llm_k8s_tpu.engine.engine import InferenceEngine
        from rag_llm_k8s_tpu.models.llama import init_llama_params

        DT = DTypePolicy()
        cfg = LlamaConfig.tiny()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        greedy = SamplingConfig(do_sample=False, max_new_tokens=8)
        ecfg = EngineConfig(
            prompt_buckets=(16,), max_batch_size=2, max_seq_len=64,
            kv_quant="int8",
        )
        oracle = InferenceEngine(cfg, params, sampling=greedy, engine_config=ecfg, dtypes=DT)
        want = oracle.generate([[3, 17, 42, 7]])[0]
        eng = ContinuousEngine(cfg, params, sampling=greedy, engine_config=ecfg, dtypes=DT)
        assert eng._cache[0].dtype == jnp.int8
        eng.admit(1, [3, 17, 42, 7], greedy.max_new_tokens)
        results = {}
        while eng.has_active():
            for rid, toks in eng.step():
                results[rid] = toks
        assert results[1] == want


class Test8BShapesOnChip:
    def test_single_layer_and_lm_head_microbench(self):
        """True 8B geometry on ONE chip, as far as 16 GB HBM allows: a
        single stacked decoder layer + embed/lm_head (~2.5 GB bf16 weights)
        runs prefill-4096 and fused-kernel decode. Whole-model 8B bf16
        weights are ~16 GB — at or past a single v5e's HBM — so serving 8B
        is a tp>=2 deployment by budget: tp=4 holds ~4 GB weights +
        ~2.2 GB KV (B8 T4352) + activations per chip. Numbers recorded in
        docs/8B.md."""
        import dataclasses
        import time

        from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
        from rag_llm_k8s_tpu.engine.engine import InferenceEngine
        from rag_llm_k8s_tpu.models.llama import init_llama_params

        cfg = dataclasses.replace(LlamaConfig.llama_3_1_8b(), num_layers=1)
        DT = DTypePolicy()
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        eng = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=32),
            engine_config=EngineConfig(prompt_buckets=(4096,), max_batch_size=1),
            dtypes=DT,
        )
        prompt = list(range(5, 4000))
        t0 = time.monotonic()
        eng.warmup(batch_sizes=(1,), buckets=(4096,), max_new_tokens=32)
        compile_s = time.monotonic() - t0
        t0 = time.monotonic()
        out = eng.generate([prompt], max_new_tokens=32)[0]
        e2e_s = time.monotonic() - t0
        assert len(out) == 32
        # steady-state decode: amortize a second call (cache warm)
        t0 = time.monotonic()
        eng.generate([prompt], max_new_tokens=32)
        e2e2_s = time.monotonic() - t0
        print(
            f"\n8B-L1 on chip: compile {compile_s:.1f}s, "
            f"prefill4096+32tok {e2e_s * 1e3:.0f} ms (warm {e2e2_s * 1e3:.0f} ms)"
        )

    def test_full_depth_8b_int8_serves_on_one_chip(self):
        """The WHOLE 32-layer 8B model on ONE v5e chip via weight-only int8
        (~8.0 GiB weights vs ~15 GiB bf16): builds the quantized-layout tree
        at true shapes, runs prefill + greedy decode through the production
        engine, and records decode throughput. This is the artifact behind
        docs/8B.md's single-chip serving claim — the reference's actual
        model scale (download_model.py:5) executing end-to-end on hardware
        the bf16 layout cannot fit."""
        import time

        import jax.numpy as jnp

        from rag_llm_k8s_tpu.core.config import DTypePolicy, LlamaConfig
        from rag_llm_k8s_tpu.engine.engine import InferenceEngine
        from rag_llm_k8s_tpu.models.llama import (
            init_llama_params,
            quantize_llama_params,
        )

        cfg = LlamaConfig.llama_3_1_8b()
        DT = DTypePolicy()
        shapes = jax.eval_shape(lambda: init_llama_params(jax.random.PRNGKey(0), cfg, DT))
        qshapes = jax.eval_shape(quantize_llama_params, shapes)
        params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), qshapes)
        weight_gib = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
        ) / 2**30
        assert weight_gib < 9.0, f"int8 8B should be ~8 GiB, got {weight_gib:.2f}"

        B, S, NEW = 8, 128, 64
        eng = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=NEW),
            engine_config=EngineConfig(
                prompt_buckets=(S,), max_batch_size=B, weight_quant="int8"
            ),
            dtypes=DT,
        )
        assert eng.model.quantized  # pass-through: tree already int8
        prompts = [[cfg.bos_token_id] * S] * B
        t0 = time.monotonic()
        eng.warmup(batch_sizes=(B,), buckets=(S,))
        compile_s = time.monotonic() - t0
        outs = eng.generate(prompts)
        assert all(len(o) == NEW for o in outs)
        t0 = time.monotonic()
        outs = eng.generate(prompts)
        tok_s = sum(len(o) for o in outs) / (time.monotonic() - t0)
        print(
            f"\n8B int8 FULL DEPTH on one chip: {weight_gib:.2f} GiB weights, "
            f"compile {compile_s:.1f}s, decode {tok_s:.0f} tok/s (B={B})"
        )
        assert tok_s > 100  # sanity floor; measured ~610 at B=8


class TestSingleFetchOnChip:
    def test_fused_rag_generate_matches_host_assembly(self):
        """Hardware counterpart of tests/test_fused_rag.py: device-side
        prompt assembly (generate_rag) must emit the same greedy tokens as
        the host-assembled prompt through the SAME engine, on real Mosaic
        kernels, and cost exactly ONE device->host fetch."""
        import numpy as np

        from rag_llm_k8s_tpu.engine.engine import InferenceEngine
        from rag_llm_k8s_tpu.index.store import VectorStore
        from rag_llm_k8s_tpu.models.llama import init_llama_params

        DT = DTypePolicy()
        cfg = LlamaConfig.tiny(vocab_size=512)
        params = init_llama_params(jax.random.PRNGKey(0), cfg, DT)
        eng = InferenceEngine(
            cfg, params,
            sampling=SamplingConfig(do_sample=False, max_new_tokens=8),
            engine_config=EngineConfig(prompt_buckets=(256,), max_batch_size=2),
            dtypes=DT,
        )

        def seg_ids(md):
            return [3 + (b % 500) for b in (
                f"Document '{md['filename']}' (chunk {md['chunk_id']}): "
                f"{md['text']}\n\n"
            ).encode()]

        store = VectorStore(dim=8)
        rng = np.random.default_rng(0)
        texts = ["alpha beta gamma", "delta epsilon", "zeta eta"]
        store.add(
            [rng.standard_normal(8).astype(np.float32) for _ in texts],
            [{"filename": "f.pdf", "chunk_id": i, "text": t} for i, t in enumerate(texts)],
        )
        store.attach_token_source(seg_ids)
        toks_dev, lens_dev = store.token_snapshot()

        a = [cfg.bos_token_id] + [3 + (b % 500) for b in b"SYS\n\nContext: "]
        b = [3 + (x % 500) for x in b"\n\nUser: what?\n\nChatbot:"]
        d = np.linspace(0.1, 0.5, 3, dtype=np.float32)
        packed = jnp.asarray(
            np.concatenate([d, np.asarray([2, 0, 1], np.float32)])[None, :]
        )
        host_ids = list(a)
        for i in (2, 0, 1):
            host_ids += seg_ids(store._metadata[i])
        host_ids += b
        assert len(host_ids) <= 256
        want = eng.generate([host_ids])[0]
        got = eng.generate_rag(
            np.asarray(a, np.int32), np.asarray(b, np.int32),
            packed, toks_dev, lens_dev, n_chunks=3,
        )
        assert got == want
