"""Journal replay: parse flight journals, extract traces, diff decision
streams, and re-drive a trace against a live engine deterministically.

Three layers, bottom up:

- **Parsing** (``parse_journal``): seq-orders a journal's events and
  skips event types this build does not know — with a warning, never a
  crash — so a journal recorded by a NEWER build still replays on its
  known subset (the forward-compat pin tests/test_replay.py carries).
- **Decision streams** (``decision_stream`` / ``diff_journals``): the
  normalized projection of a journal onto scheduler *decisions* —
  admissions, window plans, budget splits, preemptions, evictions,
  EOS, resubmissions, completions — with wall-clock measurements
  (durations, chip-ms, timestamps) stripped, so two runs of the same
  trace compare equal exactly when the scheduler decided the same
  things. ``scripts/flightview.py --replay-diff`` renders the diff.
- **The lockstep driver** (``LockstepDriver``): re-drives a trace's
  arrivals against a live engine single-threaded, making the decisions
  ``ContinuousScheduler._run_loop`` makes (group admission up to the
  free-slot count, step under backpressure, resume preemptions, recover
  resets) — but on a deterministic step-indexed clock instead of wall
  time. Record → ``extract_trace`` → re-drive is a fixed point: the
  replayed decision stream equals the recording exactly (the fidelity
  contract docs/REPLAY.md states, pinned by ``make replay-smoke``).

The engine is duck-typed — the real ``ContinuousEngine`` on CPU for
fidelity replay, or ``sim/simulator.py``'s ``SimEngine`` for pure-host
what-if runs — both answer the same narrow surface (``admission_state``,
``free_slots``, ``admit_many``, ``step``, ``drain_preempted``,
``has_active``, ``slots``, ``reset``, ``buckets``).

Import discipline: stdlib-only, no package-internal imports (SIM-PURITY);
siblings load by file path via ``policy.load_sibling``.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import importlib.util as _ilu
import os as _os


def _load_sibling(name: str):
    here = _os.path.dirname(_os.path.abspath(__file__))
    path = _os.path.normpath(_os.path.join(here, name + ".py"))
    spec = _ilu.spec_from_file_location(
        "_rag_sim_" + _os.path.basename(name), path
    )
    mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


policy = _load_sibling("policy")
_flight = policy.load_sibling("../obs/flight")  # EVENTS catalog, stream_hash

logger = logging.getLogger(__name__)

TRACE_SCHEMA_VERSION = 1

#: Journal events that ARE scheduler decisions (vs measurements): the
#: decision-stream projection keeps exactly these, in seq order.
DECISION_EVENTS = (
    "arrival", "admit", "sync_window_open", "sync_window_close",
    "window_budget", "prefill_chunk_sched", "block_grow", "preempt",
    "evict", "eos", "reset", "resubmit", "complete",
    "pool_exhausted",
)

#: Attrs that carry wall-clock measurements, not decisions — stripped
#: before comparison (two identical re-drives never clock alike).
TIMING_ATTRS = frozenset(
    {"seq", "t", "duration_ms", "chip_ms", "cost_usd", "t_ms", "dt_ms"}
)


# ----------------------------------------------------------------------
# parsing (forward-compatible)
# ----------------------------------------------------------------------

def parse_journal(events: Iterable[Dict]) -> Dict:
    """Normalize a raw journal: seq-order its events, drop malformed
    entries and event types outside this build's ``flight.EVENTS`` —
    logged once per unknown type, never raised (a newer recorder's
    journal replays on the known subset). Returns ``{"events": [...],
    "skipped": {type_or_reason: count}}``."""
    known = set(_flight.EVENTS)
    out: List[Dict] = []
    skipped: Dict[str, int] = {}
    for e in events:
        if not isinstance(e, dict) or not isinstance(e.get("type"), str):
            skipped["<malformed>"] = skipped.get("<malformed>", 0) + 1
            continue
        t = e["type"]
        if t not in known:
            skipped[t] = skipped.get(t, 0) + 1
            continue
        out.append(e)
    out.sort(key=lambda e: e.get("seq", 0))
    for t, n in skipped.items():
        logger.warning(
            "journal: skipped %d event(s) of unknown type %r — recorded "
            "by a newer schema? replaying the known subset", n, t,
        )
    return {"events": out, "skipped": skipped}


def extract_trace(events: Iterable[Dict]) -> Dict:
    """A journal's request arrivals as a re-drivable trace. Each arrival
    carries what ``LockstepDriver`` needs — rid, prompt (ids when the
    recording kept them, else length), max_new, seed — plus two clocks:
    ``t`` (seconds since the first arrival, for timed load generation)
    and ``t_step`` (scheduler step boundaries that preceded it, the
    lockstep visibility clock), and ``n_out`` (the recorded output
    length, the simulator's generation-length oracle)."""
    parsed = parse_journal(events)["events"]
    arrivals: List[Dict] = []
    out_lens: Dict[int, int] = {}
    steps_before = 0
    t0: Optional[float] = None
    for e in parsed:
        typ = e["type"]
        if typ == "sync_window_open":
            steps_before += 1
        elif typ == "goodput_window" and _is_stall_window(e):
            # a preempt-stall step opened no window but WAS one scheduler
            # step call — the lockstep clock must count it
            steps_before += 1
        elif typ == "reset":
            # a step that died mid-flight (fault, device loss) emitted no
            # window at all, only the reset — but it consumed one step
            # boundary on the lockstep clock. (Caveat: a reset raised at
            # admission time would overcount by one; admission resets are
            # rare and chaos recordings fault the step path.)
            steps_before += 1
        elif typ == "arrival":
            t = float(e.get("t", 0.0))
            if t0 is None:
                t0 = t
            a: Dict = {
                "rid": e.get("rid"),
                "t": round(t - t0, 6),
                "t_step": steps_before,
                "prompt_len": int(e.get("prompt_len", 0)),
                "max_new": int(e.get("max_new", 1)),
            }
            for k in ("seed", "deadline_ms", "ids", "session", "tenant"):
                if k in e:
                    a[k] = e[k]
            arrivals.append(a)
        elif typ == "eos" and e.get("rid") is not None:
            out_lens[e["rid"]] = int(e.get("n_tokens", 0))
        elif typ == "complete" and e.get("rid") is not None:
            out_lens[e["rid"]] = int(e.get("n_tokens", 0))
    for a in arrivals:
        if a["rid"] in out_lens:
            a["n_out"] = out_lens[a["rid"]]
    return {"schema_version": TRACE_SCHEMA_VERSION, "arrivals": arrivals}


#: event types that END a request's lifecycle in a journal — a request
#: whose chain lacks all of them was in flight when the recording stopped
#: (i.e. when the process died, for a WAL epoch)
_TERMINAL_EVENTS = ("complete", "evict")


def extract_inflight(events: Iterable[Dict]) -> Dict:
    """What a dead incarnation's WAL owes the next one: every request
    with an ``arrival`` but no terminal event (``complete``, ``evict``,
    or a gave-up ``resubmit``), each with the prompt the arrival recorded
    (real ids, or the deterministic synthetic filler when the recording
    kept lengths only) and the emitted-token stream rebuilt by
    concatenating its ``token_emit`` deltas in seq order. The warm
    restart (server/main.py) resubmits each record through the
    scheduler's fold path; ``synthetic_prompt`` marks records a restart
    should SKIP when byte-exactness matters (a synthetic prompt resumes
    the shape, not the stream)."""
    parsed = parse_journal(events)["events"]
    arrivals: Dict[int, Dict] = {}
    emitted: Dict[int, List[int]] = {}
    terminal: Dict[int, str] = {}
    for e in parsed:
        rid = e.get("rid")
        if rid is None:
            continue
        typ = e["type"]
        if typ == "arrival" and rid not in arrivals:
            arrivals[rid] = e
        elif typ == "token_emit":
            emitted.setdefault(rid, []).extend(
                int(t) for t in e.get("toks", ())
            )
        elif typ in _TERMINAL_EVENTS:
            terminal[rid] = typ
        elif typ == "resubmit" and e.get("outcome") == "gave_up":
            terminal[rid] = "gave_up"
    inflight: List[Dict] = []
    for rid in sorted(arrivals):
        if rid in terminal:
            continue
        a = arrivals[rid]
        rec: Dict = {
            "rid": rid,
            "prompt": _arrival_prompt(a),
            "prompt_len": int(a.get("prompt_len", 0)),
            "max_new": int(a.get("max_new", 1)),
            "emitted": emitted.get(rid, []),
            "synthetic_prompt": not bool(a.get("ids")),
        }
        for k in ("seed", "deadline_ms", "tenant", "session"):
            if k in a:
                rec[k] = a[k]
        inflight.append(rec)
    return {
        "inflight": inflight,
        "arrivals": len(arrivals),
        "terminal": {
            out: sum(1 for v in terminal.values() if v == out)
            for out in sorted(set(terminal.values()))
        },
    }


def build_restore_report(epochs: Dict[int, List[Dict]]) -> Dict:
    """The ``flightview --restore-report`` payload over a scanned WAL
    directory (``flight.scan_wal``'s ``{epoch: [events]}``): per epoch,
    what the incarnation did (arrivals/completions/drain trail), what it
    left in flight, and what the NEXT incarnation's restore pass actually
    did about it (resumed / rehydrated / skipped — the ``restore`` and
    ``outcome="restored"`` resubmit events it journaled)."""
    report: Dict = {"epochs": []}
    for epoch in sorted(epochs):
        evs = parse_journal(epochs[epoch])["events"]
        flight_state = extract_inflight(evs)
        drain = [
            {k: v for k, v in e.items() if k in
             ("phase", "reason", "in_flight", "deadline_s", "timed_out")}
            for e in evs if e["type"] == "drain"
        ]
        resumed, rehydrated, skipped = [], [], []
        for e in evs:
            if e["type"] == "restore":
                phase = e.get("phase")
                if phase == "resume":
                    resumed.append({
                        "rid": e.get("rid"),
                        "orig_rid": e.get("orig_rid"),
                        "orig_epoch": e.get("orig_epoch"),
                        "n_emitted": int(e.get("n_emitted", 0)),
                    })
                elif phase == "rehydrate":
                    rehydrated.append({
                        "key": e.get("key"),
                        "tokens": int(e.get("tokens", 0)),
                    })
                elif phase == "skip":
                    skipped.append({
                        "orig_rid": e.get("orig_rid"),
                        "reason": e.get("reason"),
                    })
        completes = sum(1 for e in evs if e["type"] == "complete")
        report["epochs"].append({
            "epoch": epoch,
            "events": len(evs),
            "arrivals": flight_state["arrivals"],
            "completes": completes,
            "inflight_at_end": [
                {"rid": r["rid"], "prompt_len": r["prompt_len"],
                 "n_emitted": len(r["emitted"]),
                 "synthetic_prompt": r["synthetic_prompt"]}
                for r in flight_state["inflight"]
            ],
            "drain": drain,
            "restored": resumed,
            "rehydrated": rehydrated,
            "skipped": skipped,
        })
    return report


def _is_stall_window(e: Dict) -> bool:
    """A ``goodput_window`` whose whole duration is preempt churn (the
    ledger's ``record_preempt_stall``): a scheduler step that opened no
    sync window — still one step boundary on the lockstep clock."""
    return "preempt_rework" in e and not any(
        k in e for k in
        ("decode_useful", "prefill_compute", "padding_bubble",
         "spec_rejected", "prefill_skipped")
    )


# ----------------------------------------------------------------------
# decision streams + diffing
# ----------------------------------------------------------------------

def decision_stream(events: Iterable[Dict]) -> List[Dict]:
    """The journal's decisions, normalized for comparison: only
    ``DECISION_EVENTS``, timing attrs stripped, seq order kept."""
    parsed = parse_journal(events)["events"]
    keep = set(DECISION_EVENTS)
    return [
        {k: v for k, v in e.items() if k not in TIMING_ATTRS}
        for e in parsed if e["type"] in keep
    ]


def request_chains(events: Iterable[Dict]) -> Dict[int, List[Dict]]:
    """Per-request decision chains: the rid-keyed subset of the decision
    stream. Window-plan events carry no rid and are excluded — this is
    the projection that stays exact even for journals recorded by the
    THREADED scheduler, whose window interleaving is timing-dependent
    while every per-request decision is not."""
    chains: Dict[int, List[Dict]] = {}
    for d in decision_stream(events):
        rid = d.get("rid")
        if rid is not None:
            chains.setdefault(rid, []).append(d)
    return chains


def first_divergence(
    a: Sequence[Dict], b: Sequence[Dict]
) -> Optional[Tuple[int, Optional[Dict], Optional[Dict]]]:
    """Index + both sides of the first differing decision (None when the
    streams are identical; a pure length mismatch diverges at the end of
    the shorter stream with the missing side None)."""
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return i, x, y
    if len(a) != len(b):
        i = min(len(a), len(b))
        return i, (a[i] if i < len(a) else None), (b[i] if i < len(b) else None)
    return None


def _occupancy(events: Sequence[Dict]) -> Dict:
    """Mean active rows / steps per sync window — the occupancy shape a
    replay or simulation must land near even when exact interleaving
    cannot be compared."""
    opens = [e for e in events if e.get("type") == "sync_window_open"]
    n = len(opens)
    return {
        "windows": n,
        "mean_active": round(
            sum(int(e.get("active", 0)) for e in opens) / n, 4
        ) if n else 0.0,
        "mean_steps": round(
            sum(int(e.get("steps", 1)) for e in opens) / n, 4
        ) if n else 0.0,
    }


def diff_journals(events_a: Iterable[Dict], events_b: Iterable[Dict]) -> Dict:
    """Event-by-event comparison of two journals' decision streams (live
    vs replayed/simulated): identical flag, the first divergent decision,
    per-event-type count deltas, and occupancy deltas. The flightview
    ``--replay-diff`` payload."""
    ea = parse_journal(events_a)["events"]
    eb = parse_journal(events_b)["events"]
    sa, sb = decision_stream(ea), decision_stream(eb)
    div = first_divergence(sa, sb)
    counts: Dict[str, List[int]] = {}
    for side, evs in ((0, ea), (1, eb)):
        for e in evs:
            counts.setdefault(e["type"], [0, 0])[side] += 1
    occ_a, occ_b = _occupancy(ea), _occupancy(eb)
    chains_a, chains_b = request_chains(ea), request_chains(eb)
    rid_div = sorted(
        rid for rid in set(chains_a) | set(chains_b)
        if chains_a.get(rid) != chains_b.get(rid)
    )
    return {
        "identical": div is None,
        "decisions": [len(sa), len(sb)],
        "first_divergence": None if div is None else {
            "index": div[0], "a": div[1], "b": div[2],
        },
        "event_counts": {
            t: {"a": c[0], "b": c[1], "delta": c[1] - c[0]}
            for t, c in sorted(counts.items())
        },
        "occupancy": {
            "a": occ_a, "b": occ_b,
            "mean_active_delta": round(
                occ_b["mean_active"] - occ_a["mean_active"], 4
            ),
        },
        "requests_diverged": rid_div,
        "requests_identical": div is None or not rid_div,
    }


# ----------------------------------------------------------------------
# the lockstep driver
# ----------------------------------------------------------------------

class _Req:
    """Driver-side mirror of the scheduler's ``_Pending`` (no threading
    — lockstep has no other thread to signal)."""

    __slots__ = ("rid", "prompt", "max_new", "seed", "emitted",
                 "retries_left", "retried", "resumed", "tenant")

    def __init__(self, rid, prompt, max_new, seed, retries_left,
                 tenant=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.seed = seed
        self.emitted: List[int] = []
        self.retries_left = int(retries_left)
        self.retried = False
        self.resumed = False
        self.tenant = tenant


def _arrival_prompt(a: Dict) -> List[int]:
    """An arrival's prompt: the recorded ids when the journal kept them,
    else a deterministic synthetic filler of the recorded length (shape-
    faithful replay: every scheduling decision depends on lengths, only
    token streams need the real ids)."""
    ids = a.get("ids")
    if ids:
        return [int(x) for x in ids]
    n = max(1, int(a.get("prompt_len", 1)))
    rid = int(a.get("rid") or 0)
    return [(7 + ((rid * 131 + i * 31) % 97)) for i in range(n)]


class LockstepDriver:
    """Deterministic single-threaded re-drive of a trace against a live
    (duck-typed) engine — the scheduler's decision loop on a step-indexed
    clock. ``emit`` receives the scheduler-level events the threaded
    scheduler would journal (``arrival``/``resubmit``/``complete``);
    engine-level events flow from the engine itself. Pass the package's
    ``flight.emit`` to record, a collector to capture, or nothing to
    discard."""

    def __init__(
        self,
        engine,
        emit: Optional[Callable] = None,
        retries: int = 1,
        arrival_ids: bool = True,
    ):
        self.engine = engine
        self.emit = emit if emit is not None else (lambda *a, **k: None)
        self.retries = max(0, int(retries))
        self.arrival_ids = bool(arrival_ids)
        self.steps_done = 0
        self.results: Dict[int, List[int]] = {}
        self.errors: Dict[int, BaseException] = {}
        self._queue: deque = deque()

    # -- driving -------------------------------------------------------
    def drive(self, trace) -> Dict[int, List[int]]:
        """Re-drive every arrival to completion; returns rid → emitted
        tokens (failures land in ``self.errors`` instead). Deadlines in
        the trace are ignored — lockstep has no wall clock to expire
        them against (timed load generation goes through the real
        threaded scheduler instead)."""
        arrivals = trace["arrivals"] if isinstance(trace, dict) else list(trace)
        pending = deque(
            sorted(arrivals, key=lambda a: int(a.get("t_step", 0)))
        )
        waiting: Dict[int, _Req] = {}
        eng = self.engine

        def make_visible():
            while pending and int(pending[0].get("t_step", 0)) <= self.steps_done:
                a = pending.popleft()
                req = _Req(
                    a.get("rid"), _arrival_prompt(a),
                    a.get("max_new", 1), a.get("seed"), self.retries,
                    tenant=a.get("tenant"),
                )
                arr = {"prompt_len": len(req.prompt), "max_new": req.max_new}
                if req.seed is not None:
                    arr["seed"] = req.seed
                if "deadline_ms" in a:
                    arr["deadline_ms"] = a["deadline_ms"]
                if req.tenant is not None:
                    # forward the trace's tenant into the live submit: the
                    # re-driven journal prices per tenant exactly like the
                    # recording (and the engine's ledger rolls it up)
                    arr["tenant"] = req.tenant
                    ledger = getattr(eng, "ledger", None)
                    note = getattr(ledger, "note_tenant", None)
                    if note is not None:
                        note(req.rid, req.tenant)
                if self.arrival_ids:
                    arr["ids"] = list(req.prompt)
                self.emit("arrival", req.rid, **arr)
                self._queue.append(req)

        while True:
            make_visible()
            if not self._queue:
                if waiting or eng.has_active():
                    self._step(waiting)
                    continue
                if pending:
                    # idle: jump the clock to the next arrival
                    self.steps_done = int(pending[0].get("t_step", 0))
                    continue
                break
            item = self._queue.popleft()
            while item is not None:
                state = eng.admission_state(len(item.prompt))
                if state == "never":
                    self.errors[item.rid] = RuntimeError(
                        f"pool cannot hold request {item.rid}'s prompt "
                        f"({len(item.prompt)} tokens)"
                    )
                    item = self._queue.popleft() if self._queue else None
                    continue
                if state == "wait":
                    self._step(waiting)
                    make_visible()
                    continue
                free = eng.free_slots()
                if not free:
                    self._step(waiting)
                    make_visible()
                    continue
                batch = [item]
                while len(batch) < len(free) and self._queue:
                    batch.append(self._queue.popleft())
                self._admit(batch, waiting)
                item = self._queue.popleft() if self._queue else None
            if waiting or eng.has_active():
                self._step(waiting)
        return self.results

    # -- internals -----------------------------------------------------
    def _admit(self, batch: List[_Req], waiting: Dict[int, _Req]) -> None:
        eng = self.engine
        try:
            admitted = eng.admit_many(
                [(b.rid, b.prompt, b.max_new, b.seed) for b in batch]
            )
        except BaseException as e:  # noqa: BLE001 — duck-typed engines
            if type(e).__name__ == "EngineStateLost":
                self._handle_reset(e, waiting, extra=batch, emitted={})
                return
            for b in batch:
                self.errors[b.rid] = e
            return
        for b, res in zip(batch, admitted):
            if isinstance(res, BaseException):
                if type(res).__name__ == "PoolExhausted":
                    # the chunk raced the pool: requeue (backpressure)
                    self._queue.append(b)
                else:
                    self.errors[b.rid] = res
                continue
            _, finished = res
            if finished is not None:
                self._deliver(b, finished)
            else:
                waiting[b.rid] = b

    def _step(self, waiting: Dict[int, _Req]) -> None:
        eng = self.engine
        try:
            done = eng.step()
        except BaseException as e:  # noqa: BLE001 — mirror _safe_step
            emitted = {
                s.request_id: list(s.tokens) for s in eng.slots if s.active
            }
            try:
                eng.reset()
            except BaseException:  # noqa: BLE001
                logger.exception("engine reset failed after step failure")
            self._handle_reset(e, waiting, extra=[], emitted=emitted)
            self.steps_done += 1
            return
        self.steps_done += 1
        for rid, tokens in done:
            it = waiting.pop(rid, None)
            if it is not None:
                self._deliver(it, tokens)
        # pool-preemption resume (scheduled backpressure, burns no retry)
        for rid, toks in eng.drain_preempted():
            it = waiting.pop(rid, None)
            if it is None:
                continue
            self._fold(it, toks)
            it.resumed = True
            mark = getattr(eng, "mark_rework", None)
            if mark:
                mark(rid)
            self.emit("resubmit", rid, outcome="preempt_resume",
                      n_emitted=len(toks))
            self._queue.append(it)

    def _fold(self, it: _Req, toks: List[int]) -> None:
        if policy.resume_fits(len(it.prompt), len(toks),
                              max(self.engine.buckets)):
            it.emitted.extend(toks)
            it.prompt = list(it.prompt) + toks
            it.max_new = max(1, it.max_new - len(toks))

    def _handle_reset(self, cause, waiting, extra, emitted) -> None:
        items = list(waiting.values()) + list(extra)
        waiting.clear()
        retry = []
        for it in items:
            if it.retries_left > 0:
                retry.append(it)
            else:
                self.emit("resubmit", it.rid, outcome="gave_up")
                disc = getattr(self.engine, "discard_request_goodput", None)
                if disc:
                    disc(it.rid)
                self.errors[it.rid] = cause
        for it in retry:
            toks = emitted.get(it.rid, [])
            self._fold(it, toks)
            it.retries_left -= 1
            it.retried = True
            mark = getattr(self.engine, "mark_rework", None)
            if mark:
                mark(it.rid)
            self.emit("resubmit", it.rid, outcome="resubmitted",
                      n_emitted=len(toks))
            self._queue.append(it)

    def _deliver(self, it: _Req, tokens: List[int]) -> None:
        result = it.emitted + list(tokens)
        self.results[it.rid] = result
        eng = self.engine
        pop_blocks = getattr(eng, "pop_blocks_allocated", None)
        if pop_blocks:
            pop_blocks(it.rid)
        extra = {}
        pop_gp = getattr(eng, "pop_request_goodput", None)
        gp = pop_gp(it.rid) if pop_gp else None
        if gp is not None:
            extra["chip_ms"] = gp["chip_ms"]
            if "cost_usd" in gp:
                extra["cost_usd"] = round(gp["cost_usd"], 8)
        pop_spec = getattr(eng, "pop_spec_seen", None)
        if pop_spec:
            pop_spec(it.rid)
        if it.tenant is not None:
            extra["tenant"] = it.tenant
        self.emit(
            "complete", it.rid, n_tokens=len(result),
            stream_fnv=_flight.stream_hash(result), **extra,
        )


def replay_journal(
    engine,
    events: Iterable[Dict],
    emit: Optional[Callable] = None,
    retries: int = 1,
) -> Dict:
    """Convenience fidelity check: extract the trace from ``events``,
    re-drive it on ``engine``, and return ``{"trace", "results",
    "errors", "driver"}`` — the caller diffs the engine's fresh journal
    against the recording with ``diff_journals``."""
    trace = extract_trace(events)
    drv = LockstepDriver(engine, emit=emit, retries=retries)
    results = drv.drive(trace)
    return {
        "trace": trace, "results": results,
        "errors": drv.errors, "driver": drv,
    }
