"""Seeded synthetic trace generation: load shapes for the simulator.

Produces the same trace schema ``sim/replay.py::extract_trace`` emits
from a recording, so everything downstream — ``LockstepDriver``,
``SimEngine``, ``flightview --replay-diff`` — consumes generated and
recorded load identically. The generator models the parts of RAG serving
load that move capacity numbers:

- **arrival process**: Poisson at ``rate_qps`` with burst episodes
  (``burst_prob`` per arrival, rate × ``burst_factor`` for
  ``burst_len`` arrivals) — the tail the mean-rate estimate hides;
- **sessions**: follow-up turns re-arrive with their history folded into
  the prompt (longer prompts deeper in a session — the KV-pressure ramp);
- **tenant mix**: weighted tenant classes scaling prompt/output budgets;
- **hot-chunk skew**: when ``emit_ids`` is on, prompts are built from
  chunk-shaped token runs drawn Zipf(``zipf_a``) over ``hot_chunks``
  distinct chunks — the skew that makes prefix reuse and hot-set
  pinning worth simulating;
- **prompt/output lengths**: lognormal prompt lengths clamped to
  ``prompt_len_range``, uniform output budgets in ``max_new_range``.

Everything is driven by one ``random.Random(seed)`` — the same seed and
knobs reproduce the identical trace, byte for byte (pinned by
tests/test_replay.py).

Import discipline: stdlib-only, no package-internal imports (SIM-PURITY).
"""

from __future__ import annotations

import bisect
import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

TRACE_SCHEMA_VERSION = 1

DEFAULT_TENANTS: Tuple[Tuple[str, float, float], ...] = (
    # (name, mix weight, budget scale)
    ("free", 0.7, 1.0),
    ("pro", 0.3, 1.6),
)


class _Zipf:
    """Rank-skewed sampler: P(rank r) ∝ 1/(r+1)^a over ``n`` items."""

    def __init__(self, n: int, a: float):
        w = [1.0 / ((r + 1) ** a) for r in range(max(1, int(n)))]
        total = sum(w)
        acc, self.cum = 0.0, []
        for x in w:
            acc += x / total
            self.cum.append(acc)

    def sample(self, rng: random.Random) -> int:
        return bisect.bisect_left(self.cum, rng.random())


def generate(
    n_requests: int,
    seed: int = 0,
    rate_qps: float = 8.0,
    burst_prob: float = 0.05,
    burst_factor: float = 4.0,
    burst_len: int = 8,
    session_prob: float = 0.35,
    session_max_turns: int = 5,
    tenants: Sequence[Tuple[str, float, float]] = DEFAULT_TENANTS,
    prompt_len_lognorm: Tuple[float, float] = (4.6, 0.6),
    prompt_len_range: Tuple[int, int] = (16, 512),
    max_new_range: Tuple[int, int] = (8, 128),
    hot_chunks: int = 64,
    chunk_len: int = 32,
    zipf_a: float = 1.1,
    step_period_s: float = 0.05,
    emit_ids: bool = False,
    rid_base: int = 1,
) -> Dict:
    """A reproducible synthetic trace of ``n_requests`` arrivals. Each
    arrival carries ``t`` (seconds), ``t_step`` (``t`` quantized by
    ``step_period_s`` — the lockstep visibility clock), ``prompt_len``,
    ``max_new``, ``session``, ``tenant``, and (``emit_ids``) the prompt
    token ids themselves, chunk-structured with Zipf-hot chunks."""
    if n_requests <= 0:
        return {"schema_version": TRACE_SCHEMA_VERSION, "arrivals": []}
    rng = random.Random(int(seed))
    zipf = _Zipf(hot_chunks, zipf_a)
    t_names = [t[0] for t in tenants]
    t_weights = [max(0.0, float(t[1])) for t in tenants]
    t_scale = {t[0]: float(t[2]) for t in tenants}
    lo_p, hi_p = int(prompt_len_range[0]), int(prompt_len_range[1])
    lo_m, hi_m = int(max_new_range[0]), int(max_new_range[1])
    mu, sigma = prompt_len_lognorm

    arrivals: List[Dict] = []
    open_sessions: List[Dict] = []
    t = 0.0
    burst_left = 0
    next_session = 1
    for i in range(int(n_requests)):
        rate = rate_qps * (burst_factor if burst_left > 0 else 1.0)
        if burst_left > 0:
            burst_left -= 1
        elif rng.random() < burst_prob:
            burst_left = int(burst_len)
        t += rng.expovariate(max(rate, 1e-9))

        sess: Optional[Dict] = None
        if open_sessions and rng.random() < session_prob:
            sess = rng.choice(open_sessions)
        if sess is None:
            sess = {
                "id": next_session,
                "tenant": rng.choices(t_names, weights=t_weights)[0],
                "turns": 0,
                "history": 0,  # tokens of prior turns folded forward
                "chunks": [],  # the session's retrieved hot-chunk ranks
            }
            next_session += 1
            open_sessions.append(sess)
        scale = t_scale.get(sess["tenant"], 1.0)
        base_len = int(round(math.exp(rng.gauss(mu, sigma)) * scale))
        prompt_len = max(lo_p, min(hi_p, base_len + sess["history"]))
        max_new = rng.randint(lo_m, min(hi_m, max(lo_m, int(hi_m * scale))))
        a: Dict = {
            "rid": rid_base + i,
            "t": round(t, 6),
            "t_step": int(t / max(step_period_s, 1e-9)),
            "prompt_len": prompt_len,
            "max_new": max_new,
            "session": sess["id"],
            "tenant": sess["tenant"],
        }
        if emit_ids:
            want_chunks = max(1, prompt_len // max(1, chunk_len))
            while len(sess["chunks"]) < want_chunks:
                sess["chunks"].append(zipf.sample(rng))
            ids: List[int] = []
            for rank in sess["chunks"][:want_chunks]:
                ids.extend(
                    1000 + rank * chunk_len + j for j in range(chunk_len)
                )
            # per-turn query tail: fresh (cold) tokens after the chunks
            while len(ids) < prompt_len:
                ids.append(100000 + rng.randrange(20000))
            a["ids"] = ids[:prompt_len]
        arrivals.append(a)
        sess["turns"] += 1
        sess["history"] += max_new // 2  # half the answer quoted back
        if sess["turns"] >= session_max_turns:
            open_sessions.remove(sess)
    return {"schema_version": TRACE_SCHEMA_VERSION, "arrivals": arrivals}


def describe(trace: Dict) -> Dict:
    """Shape summary of a trace (generated or extracted): counts, rate,
    prompt/output length quantiles, tenant/session mix — the sanity
    check before a capacity run."""
    arrivals = trace.get("arrivals", [])
    n = len(arrivals)
    if n == 0:
        return {"requests": 0}
    ts = [float(a.get("t", 0.0)) for a in arrivals]
    span = max(ts) - min(ts)
    plens = sorted(int(a.get("prompt_len", 0)) for a in arrivals)
    mnews = sorted(int(a.get("max_new", 0)) for a in arrivals)
    tenants: Dict[str, int] = {}
    sessions = set()
    for a in arrivals:
        if "tenant" in a:
            tenants[a["tenant"]] = tenants.get(a["tenant"], 0) + 1
        if "session" in a:
            sessions.add(a["session"])

    def q(xs: List[int], f: float) -> int:
        return xs[min(len(xs) - 1, int(f * (len(xs) - 1)))]

    return {
        "requests": n,
        "span_s": round(span, 3),
        "rate_qps": round(n / span, 3) if span > 0 else float(n),
        "prompt_len": {"p50": q(plens, 0.5), "p95": q(plens, 0.95),
                       "max": plens[-1]},
        "max_new": {"p50": q(mnews, 0.5), "p95": q(mnews, 0.95)},
        "tenants": tenants,
        "sessions": len(sessions),
    }
